"""SQLite storage backend — the SQL (JDBC-analog) backend.

Capability parity with the reference JDBC backend
(storage/jdbc/src/main/scala/org/apache/predictionio/data/storage/jdbc/):
metadata DAOs, per-app event tables named ``pio_event_<appId>[_<channel>]``
(JDBCLEvents.scala:37), and a models table. SQLite is the embedded default
(the reference defaults to PGSQL); the DAO contract keeps any SQL engine
pluggable behind the same registry.
"""

from __future__ import annotations

import json
import sqlite3
import threading
import uuid
from datetime import datetime, timedelta, timezone
from pathlib import Path
from typing import Sequence

from predictionio_tpu import faults
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base


def _ts(dt: datetime) -> float:
    return dt.timestamp()


def _from_ts(ts: float) -> datetime:
    return datetime.fromtimestamp(ts, tz=timezone.utc)


def _is_missing_table(err: sqlite3.OperationalError) -> bool:
    return "no such table" in str(err)


class SQLiteStorageClient:
    """One sqlite database file shared by all DAOs of this source."""

    def __init__(self, config: dict | None = None):
        self.config = config or {}
        path = self.config.get("path", ":memory:")
        if path != ":memory:":
            Path(path).parent.mkdir(parents=True, exist_ok=True)
        self.lock = threading.RLock()
        self.conn = sqlite3.connect(path, check_same_thread=False)
        self.conn.execute("PRAGMA journal_mode=WAL")
        # counts writes total_changes can't see (DROP TABLE in remove());
        # part of the events change_token
        self.ddl_bump = 0
        self._init_meta_tables()

    def query(self, sql: str, params: tuple | list = ()) -> list:
        """Locked read: serialized against writers on the shared connection
        so readers never observe another thread's uncommitted transaction."""
        with self.lock:
            return self.conn.execute(sql, params).fetchall()

    def query_one(self, sql: str, params: tuple | list = ()):
        rows = self.query(sql, params)
        return rows[0] if rows else None

    def _init_meta_tables(self) -> None:
        with self.lock, self.conn:
            self.conn.executescript(
                """
                CREATE TABLE IF NOT EXISTS pio_apps (
                  id INTEGER PRIMARY KEY AUTOINCREMENT,
                  name TEXT NOT NULL UNIQUE,
                  description TEXT);
                CREATE TABLE IF NOT EXISTS pio_access_keys (
                  accesskey TEXT PRIMARY KEY,
                  appid INTEGER NOT NULL,
                  events TEXT NOT NULL);
                CREATE TABLE IF NOT EXISTS pio_channels (
                  id INTEGER PRIMARY KEY AUTOINCREMENT,
                  name TEXT NOT NULL,
                  appid INTEGER NOT NULL,
                  UNIQUE(name, appid));
                CREATE TABLE IF NOT EXISTS pio_engine_instances (
                  id TEXT PRIMARY KEY,
                  status TEXT NOT NULL,
                  starttime REAL NOT NULL,
                  endtime REAL NOT NULL,
                  engineid TEXT NOT NULL,
                  engineversion TEXT NOT NULL,
                  enginevariant TEXT NOT NULL,
                  enginefactory TEXT NOT NULL,
                  batch TEXT,
                  env TEXT,
                  runtimeconf TEXT,
                  datasourceparams TEXT,
                  preparatorparams TEXT,
                  algorithmsparams TEXT,
                  servingparams TEXT);
                CREATE TABLE IF NOT EXISTS pio_evaluation_instances (
                  id TEXT PRIMARY KEY,
                  status TEXT NOT NULL,
                  starttime REAL NOT NULL,
                  endtime REAL NOT NULL,
                  evaluationclass TEXT,
                  engineparamsgeneratorclass TEXT,
                  batch TEXT,
                  env TEXT,
                  runtimeconf TEXT,
                  evaluatorresults TEXT,
                  evaluatorresultshtml TEXT,
                  evaluatorresultsjson TEXT);
                CREATE TABLE IF NOT EXISTS pio_models (
                  id TEXT PRIMARY KEY,
                  models BLOB NOT NULL);
                """
            )

    def close(self) -> None:
        with self.lock:
            self.conn.close()


class SQLiteApps(base.Apps):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def insert(self, app: base.App) -> int | None:
        with self._c.lock:
            try:
                with self._c.conn:
                    if app.id != 0:
                        cur = self._c.conn.execute(
                            "INSERT INTO pio_apps (id, name, description) VALUES (?,?,?)",
                            (app.id, app.name, app.description),
                        )
                    else:
                        cur = self._c.conn.execute(
                            "INSERT INTO pio_apps (name, description) VALUES (?,?)",
                            (app.name, app.description),
                        )
                    return cur.lastrowid
            except sqlite3.IntegrityError:
                return None

    def get(self, app_id: int) -> base.App | None:
        row = self._c.query_one(
            "SELECT id, name, description FROM pio_apps WHERE id=?", (app_id,)
        )
        return base.App(*row) if row else None

    def get_by_name(self, name: str) -> base.App | None:
        row = self._c.query_one(
            "SELECT id, name, description FROM pio_apps WHERE name=?", (name,)
        )
        return base.App(*row) if row else None

    def get_all(self) -> list[base.App]:
        rows = self._c.query("SELECT id, name, description FROM pio_apps ORDER BY id")
        return [base.App(*r) for r in rows]

    def update(self, app: base.App) -> bool:
        with self._c.lock, self._c.conn:
            cur = self._c.conn.execute(
                "UPDATE pio_apps SET name=?, description=? WHERE id=?",
                (app.name, app.description, app.id),
            )
            return cur.rowcount > 0

    def delete(self, app_id: int) -> bool:
        with self._c.lock, self._c.conn:
            cur = self._c.conn.execute("DELETE FROM pio_apps WHERE id=?", (app_id,))
            return cur.rowcount > 0


class SQLiteAccessKeys(base.AccessKeys):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def insert(self, access_key: base.AccessKey) -> str | None:
        key = access_key.key or base.generate_access_key()
        with self._c.lock:
            try:
                with self._c.conn:
                    self._c.conn.execute(
                        "INSERT INTO pio_access_keys (accesskey, appid, events) VALUES (?,?,?)",
                        (key, access_key.appid, json.dumps(access_key.events)),
                    )
                return key
            except sqlite3.IntegrityError:
                return None

    def get(self, key: str) -> base.AccessKey | None:
        row = self._c.query_one(
            "SELECT accesskey, appid, events FROM pio_access_keys WHERE accesskey=?",
            (key,),
        )
        return base.AccessKey(row[0], row[1], json.loads(row[2])) if row else None

    def get_all(self) -> list[base.AccessKey]:
        rows = self._c.query("SELECT accesskey, appid, events FROM pio_access_keys")
        return [base.AccessKey(r[0], r[1], json.loads(r[2])) for r in rows]

    def get_by_appid(self, appid: int) -> list[base.AccessKey]:
        rows = self._c.query(
            "SELECT accesskey, appid, events FROM pio_access_keys WHERE appid=?",
            (appid,),
        )
        return [base.AccessKey(r[0], r[1], json.loads(r[2])) for r in rows]

    def update(self, access_key: base.AccessKey) -> bool:
        with self._c.lock, self._c.conn:
            cur = self._c.conn.execute(
                "UPDATE pio_access_keys SET appid=?, events=? WHERE accesskey=?",
                (access_key.appid, json.dumps(access_key.events), access_key.key),
            )
            return cur.rowcount > 0

    def delete(self, key: str) -> bool:
        with self._c.lock, self._c.conn:
            cur = self._c.conn.execute(
                "DELETE FROM pio_access_keys WHERE accesskey=?", (key,)
            )
            return cur.rowcount > 0


class SQLiteChannels(base.Channels):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def insert(self, channel: base.Channel) -> int | None:
        if not base.Channel.is_valid_name(channel.name):
            return None
        with self._c.lock:
            try:
                with self._c.conn:
                    if channel.id != 0:
                        cur = self._c.conn.execute(
                            "INSERT INTO pio_channels (id, name, appid) VALUES (?,?,?)",
                            (channel.id, channel.name, channel.appid),
                        )
                    else:
                        cur = self._c.conn.execute(
                            "INSERT INTO pio_channels (name, appid) VALUES (?,?)",
                            (channel.name, channel.appid),
                        )
                    return cur.lastrowid
            except sqlite3.IntegrityError:
                return None

    def get(self, channel_id: int) -> base.Channel | None:
        row = self._c.query_one(
            "SELECT id, name, appid FROM pio_channels WHERE id=?", (channel_id,)
        )
        return base.Channel(*row) if row else None

    def get_by_appid(self, appid: int) -> list[base.Channel]:
        rows = self._c.query(
            "SELECT id, name, appid FROM pio_channels WHERE appid=?", (appid,)
        )
        return [base.Channel(*r) for r in rows]

    def delete(self, channel_id: int) -> bool:
        with self._c.lock, self._c.conn:
            cur = self._c.conn.execute(
                "DELETE FROM pio_channels WHERE id=?", (channel_id,)
            )
            return cur.rowcount > 0


class SQLiteEngineInstances(base.EngineInstances):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def insert(self, instance: base.EngineInstance) -> str:
        instance_id = instance.id or uuid.uuid4().hex
        instance.id = instance_id
        with self._c.lock, self._c.conn:
            self._c.conn.execute(
                "INSERT OR REPLACE INTO pio_engine_instances VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
                self._row(instance),
            )
        return instance_id

    @staticmethod
    def _row(i: base.EngineInstance):
        return (
            i.id,
            i.status,
            _ts(i.start_time),
            _ts(i.end_time),
            i.engine_id,
            i.engine_version,
            i.engine_variant,
            i.engine_factory,
            i.batch,
            json.dumps(i.env),
            json.dumps(i.runtime_conf),
            i.datasource_params,
            i.preparator_params,
            i.algorithms_params,
            i.serving_params,
        )

    @staticmethod
    def _parse(row) -> base.EngineInstance:
        return base.EngineInstance(
            id=row[0],
            status=row[1],
            start_time=_from_ts(row[2]),
            end_time=_from_ts(row[3]),
            engine_id=row[4],
            engine_version=row[5],
            engine_variant=row[6],
            engine_factory=row[7],
            batch=row[8] or "",
            env=json.loads(row[9] or "{}"),
            runtime_conf=json.loads(row[10] or "{}"),
            datasource_params=row[11] or "{}",
            preparator_params=row[12] or "{}",
            algorithms_params=row[13] or "[]",
            serving_params=row[14] or "{}",
        )

    def get(self, instance_id: str) -> base.EngineInstance | None:
        row = self._c.query_one(
            "SELECT * FROM pio_engine_instances WHERE id=?", (instance_id,)
        )
        return self._parse(row) if row else None

    def get_all(self) -> list[base.EngineInstance]:
        rows = self._c.query("SELECT * FROM pio_engine_instances")
        return [self._parse(r) for r in rows]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[base.EngineInstance]:
        rows = self._c.query(
            "SELECT * FROM pio_engine_instances WHERE status=? AND engineid=? "
            "AND engineversion=? AND enginevariant=? ORDER BY starttime DESC",
            (
                base.EngineInstanceStatus.COMPLETED,
                engine_id,
                engine_version,
                engine_variant,
            ),
        )
        return [self._parse(r) for r in rows]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> base.EngineInstance | None:
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def update(self, instance: base.EngineInstance) -> bool:
        with self._c.lock, self._c.conn:
            cur = self._c.conn.execute(
                "UPDATE pio_engine_instances SET status=?, starttime=?, endtime=?, "
                "engineid=?, engineversion=?, enginevariant=?, enginefactory=?, "
                "batch=?, env=?, runtimeconf=?, datasourceparams=?, "
                "preparatorparams=?, algorithmsparams=?, servingparams=? WHERE id=?",
                self._row(instance)[1:] + (instance.id,),
            )
            return cur.rowcount > 0

    def delete(self, instance_id: str) -> bool:
        with self._c.lock, self._c.conn:
            cur = self._c.conn.execute(
                "DELETE FROM pio_engine_instances WHERE id=?", (instance_id,)
            )
            return cur.rowcount > 0


class SQLiteEvaluationInstances(base.EvaluationInstances):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def insert(self, instance: base.EvaluationInstance) -> str:
        instance_id = instance.id or uuid.uuid4().hex
        instance.id = instance_id
        with self._c.lock, self._c.conn:
            self._c.conn.execute(
                "INSERT OR REPLACE INTO pio_evaluation_instances VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?,?)",
                self._row(instance),
            )
        return instance_id

    @staticmethod
    def _row(i: base.EvaluationInstance):
        return (
            i.id,
            i.status,
            _ts(i.start_time),
            _ts(i.end_time),
            i.evaluation_class,
            i.engine_params_generator_class,
            i.batch,
            json.dumps(i.env),
            json.dumps(i.runtime_conf),
            i.evaluator_results,
            i.evaluator_results_html,
            i.evaluator_results_json,
        )

    @staticmethod
    def _parse(row) -> base.EvaluationInstance:
        return base.EvaluationInstance(
            id=row[0],
            status=row[1],
            start_time=_from_ts(row[2]),
            end_time=_from_ts(row[3]),
            evaluation_class=row[4] or "",
            engine_params_generator_class=row[5] or "",
            batch=row[6] or "",
            env=json.loads(row[7] or "{}"),
            runtime_conf=json.loads(row[8] or "{}"),
            evaluator_results=row[9] or "",
            evaluator_results_html=row[10] or "",
            evaluator_results_json=row[11] or "",
        )

    def get(self, instance_id: str) -> base.EvaluationInstance | None:
        row = self._c.query_one(
            "SELECT * FROM pio_evaluation_instances WHERE id=?", (instance_id,)
        )
        return self._parse(row) if row else None

    def get_all(self) -> list[base.EvaluationInstance]:
        rows = self._c.query("SELECT * FROM pio_evaluation_instances")
        return [self._parse(r) for r in rows]

    def get_completed(self) -> list[base.EvaluationInstance]:
        rows = self._c.query(
            "SELECT * FROM pio_evaluation_instances WHERE status=? "
            "ORDER BY starttime DESC",
            (base.EvaluationInstanceStatus.EVALCOMPLETED,),
        )
        return [self._parse(r) for r in rows]

    def update(self, instance: base.EvaluationInstance) -> bool:
        with self._c.lock, self._c.conn:
            cur = self._c.conn.execute(
                "UPDATE pio_evaluation_instances SET status=?, starttime=?, "
                "endtime=?, evaluationclass=?, engineparamsgeneratorclass=?, "
                "batch=?, env=?, runtimeconf=?, evaluatorresults=?, "
                "evaluatorresultshtml=?, evaluatorresultsjson=? WHERE id=?",
                self._row(instance)[1:] + (instance.id,),
            )
            return cur.rowcount > 0

    def delete(self, instance_id: str) -> bool:
        with self._c.lock, self._c.conn:
            cur = self._c.conn.execute(
                "DELETE FROM pio_evaluation_instances WHERE id=?", (instance_id,)
            )
            return cur.rowcount > 0


class SQLiteModels(base.Models):
    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    def insert(self, model: base.Model) -> None:
        with self._c.lock, self._c.conn:
            self._c.conn.execute(
                "INSERT OR REPLACE INTO pio_models (id, models) VALUES (?,?)",
                (model.id, model.models),
            )

    def get(self, model_id: str) -> base.Model | None:
        row = self._c.query_one(
            "SELECT id, models FROM pio_models WHERE id=?", (model_id,)
        )
        return base.Model(row[0], row[1]) if row else None

    def delete(self, model_id: str) -> bool:
        with self._c.lock, self._c.conn:
            cur = self._c.conn.execute(
                "DELETE FROM pio_models WHERE id=?", (model_id,)
            )
            return cur.rowcount > 0


class SQLiteEvents(base.Events):
    """Per-(app, channel) event tables named ``pio_event_<appId>[_<ch>]``
    (reference JDBCLEvents.scala:37)."""

    entity_indexed = True  # (entitytype, entityid) btree index per table

    def __init__(self, client: SQLiteStorageClient):
        self._c = client

    @staticmethod
    def _table(app_id: int, channel_id: int | None) -> str:
        suffix = f"_{channel_id}" if channel_id is not None else ""
        return f"pio_event_{app_id}{suffix}"

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        t = self._table(app_id, channel_id)
        with self._c.lock, self._c.conn:
            self._c.conn.executescript(
                f"""
                CREATE TABLE IF NOT EXISTS {t} (
                  id TEXT PRIMARY KEY,
                  event TEXT NOT NULL,
                  entitytype TEXT NOT NULL,
                  entityid TEXT NOT NULL,
                  targetentitytype TEXT,
                  targetentityid TEXT,
                  properties TEXT,
                  eventtime REAL NOT NULL,
                  eventtimezone TEXT,
                  tags TEXT,
                  prid TEXT,
                  creationtime REAL NOT NULL);
                CREATE INDEX IF NOT EXISTS {t}_time ON {t} (eventtime);
                CREATE INDEX IF NOT EXISTS {t}_entity ON {t} (entitytype, entityid);
                """
            )
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        t = self._table(app_id, channel_id)
        with self._c.lock, self._c.conn:
            self._c.conn.execute(f"DROP TABLE IF EXISTS {t}")
            # DROP TABLE bumps neither total_changes nor our own
            # connection's data_version; the token must still change
            self._c.ddl_bump += 1
        return True

    def change_token(
        self, app_id: int, channel_id: int | None = None
    ) -> object | None:
        """(data_version, total_changes, ddl_bump): ``PRAGMA
        data_version`` bumps when ANOTHER connection commits,
        ``total_changes`` counts this connection's row writes, and
        ``ddl_bump`` covers this connection's DROP TABLEs (remove()) —
        together any write to the database changes the triple.
        Database-wide, so it may over-invalidate across apps (allowed by
        the contract)."""
        with self._c.lock:
            dv = self._c.conn.execute("PRAGMA data_version").fetchone()[0]
            return (dv, self._c.conn.total_changes, self._c.ddl_bump)

    def tail_end(
        self, app_id: int, channel_id: int | None = None
    ) -> object | None:
        t = self._table(app_id, channel_id)
        try:
            row = self._c.query_one(f"SELECT COALESCE(MAX(rowid), 0) FROM {t}")
        except sqlite3.OperationalError as err:
            if _is_missing_table(err):
                return 0
            raise
        return int(row[0]) if row and row[0] is not None else 0

    def tail_events(
        self,
        app_id: int,
        channel_id: int | None = None,
        after: object | None = None,
        limit: int | None = None,
    ) -> tuple[list[Event], object]:
        """Max-rowid cursor: rowids are monotone for appends, so
        ``rowid > after`` is exactly the events inserted since the
        cursor (INSERT OR REPLACE assigns a fresh rowid — a replaced
        event re-delivers in its new state, deduped by the consumer)."""
        t = self._table(app_id, channel_id)
        cursor = int(after or 0)
        lim = int(limit) if limit is not None and limit > 0 else -1
        try:
            rows = self._c.query(
                f"SELECT rowid, * FROM {t} WHERE rowid > ? "
                f"ORDER BY rowid LIMIT ?",
                (cursor, lim),
            )
        except sqlite3.OperationalError as err:
            if _is_missing_table(err):
                return [], cursor
            raise
        if rows:
            cursor = int(rows[-1][0])
        return [self._parse(r[1:]) for r in rows], cursor

    @staticmethod
    def _tz_offset_seconds(dt: datetime) -> int:
        off = dt.utcoffset()
        return int(off.total_seconds()) if off is not None else 0

    @staticmethod
    def _to_row(event: Event, event_id: str) -> tuple:
        return (
            event_id,
            event.event,
            event.entity_type,
            event.entity_id,
            event.target_entity_type,
            event.target_entity_id,
            event.properties.to_json(),
            _ts(event.event_time),
            str(SQLiteEvents._tz_offset_seconds(event.event_time)),
            json.dumps(list(event.tags)),
            event.pr_id,
            _ts(event.creation_time),
        )

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        return self.batch_insert([event], app_id, channel_id)[0]

    def batch_insert(
        self, events, app_id: int, channel_id: int | None = None
    ) -> list[str]:
        """Contract (base.Events): the namespace is auto-created and
        re-inserting an existing event_id replaces the stored event."""
        t = self._table(app_id, channel_id)
        rows, ids = [], []
        for event in events:
            event_id = event.event_id or uuid.uuid4().hex
            ids.append(event_id)
            rows.append(self._to_row(event, event_id))
        sql = f"INSERT OR REPLACE INTO {t} VALUES (?,?,?,?,?,?,?,?,?,?,?,?)"
        with self._c.lock:
            faults.fault_point("storage.sqlite.commit")
            try:
                with self._c.conn:
                    self._c.conn.executemany(sql, rows)
            except sqlite3.OperationalError as err:
                if not _is_missing_table(err):
                    raise
                self.init(app_id, channel_id)
                with self._c.conn:
                    self._c.conn.executemany(sql, rows)
        return ids

    @staticmethod
    def _parse(row) -> Event:
        try:
            tz = timezone(timedelta(seconds=int(row[8])))
        except (TypeError, ValueError):
            tz = timezone.utc
        return Event(
            event_id=row[0],
            event=row[1],
            entity_type=row[2],
            entity_id=row[3],
            target_entity_type=row[4],
            target_entity_id=row[5],
            properties=DataMap.from_json(row[6] or "{}"),
            event_time=_from_ts(row[7]).astimezone(tz),
            tags=tuple(json.loads(row[9] or "[]")),
            pr_id=row[10],
            creation_time=_from_ts(row[11]),
        )

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        t = self._table(app_id, channel_id)
        try:
            row = self._c.query_one(f"SELECT * FROM {t} WHERE id=?", (event_id,))
        except sqlite3.OperationalError as err:
            if _is_missing_table(err):
                return None
            raise
        return self._parse(row) if row else None

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        t = self._table(app_id, channel_id)
        with self._c.lock, self._c.conn:
            try:
                cur = self._c.conn.execute(f"DELETE FROM {t} WHERE id=?", (event_id,))
            except sqlite3.OperationalError as err:
                if _is_missing_table(err):
                    return False
                raise
            return cur.rowcount > 0

    @staticmethod
    def _rating_value_col(rating_key: str) -> tuple[str, list]:
        """(SELECT expression, its bound params) extracting the numeric
        rating from the properties JSON — the SQL-dialect hook the
        postgres backend overrides. JSON booleans extract as integers
        1/0 in sqlite, but the base/jsonl backends reject booleans (fall
        back to the event-name default) — parity requires the same."""
        if '"' in rating_key:
            raise ValueError("rating_key must not contain double quotes")
        path_expr = f"properties, '$.\"{rating_key}\"'"
        return (
            f"CASE WHEN json_type({path_expr}) IN ('integer', 'real') "
            f"THEN json_extract({path_expr}) ELSE NULL END",
            [],
        )

    def scan_ratings(
        self,
        app_id: int,
        channel_id: int | None = None,
        *,
        event_names=None,
        entity_type: str | None = None,
        target_entity_type: str | None = None,
        rating_key: str | None = "rating",
        default_ratings: dict[str, float] | None = None,
        override_ratings: dict[str, float] | None = None,
    ) -> base.RatingsBatch:
        """Columnar fast path: a 4-column SQL projection with json1
        extracting the rating — the DB does the filtering and property
        parse, Python only dense-indexes ids in fetchmany batches; no
        Event objects (reference JDBCPEvents JdbcRDD read,
        storage/jdbc/.../JDBCPEvents.scala:91)."""
        import numpy as np

        t = self._table(app_id, channel_id)
        clauses, params = ["targetentityid IS NOT NULL"], []
        if entity_type is not None:
            clauses.append("entitytype = ?")
            params.append(entity_type)
        if target_entity_type is not None:
            clauses.append("targetentitytype = ?")
            params.append(target_entity_type)
        if event_names is not None:
            event_names = list(event_names)
            if not event_names:
                return base.RatingsBatch.empty()
            clauses.append("event IN (" + ",".join("?" * len(event_names)) + ")")
            params.extend(event_names)
        if rating_key is None:
            value_col, vparams = "NULL", []  # implicit: name defaults only
        else:
            value_col, vparams = self._rating_value_col(rating_key)
        sql = (
            f"SELECT entityid, targetentityid, event, {value_col} "
            f"FROM {t} WHERE " + " AND ".join(clauses)
        )
        params = vparams + params  # value_col placeholders come first
        user_map: dict[str, int] = {}
        item_map: dict[str, int] = {}
        rows: list[int] = []
        cols: list[int] = []
        vals: list[float] = []
        defaults = default_ratings or {}
        forced = override_ratings or {}
        with self._c.lock:
            try:
                cur = self._c.conn.execute(sql, params)
            except sqlite3.OperationalError as err:
                if _is_missing_table(err):
                    cur = None
                else:
                    raise
            while cur is not None:
                batch = cur.fetchmany(65536)
                if not batch:
                    break
                for u, it, ev, v in batch:
                    fv = forced.get(ev)
                    if fv is not None:
                        v = fv
                    elif not isinstance(v, (int, float)) or isinstance(v, bool):
                        v = defaults.get(ev)
                        if v is None:
                            continue
                    rows.append(user_map.setdefault(u, len(user_map)))
                    cols.append(item_map.setdefault(it, len(item_map)))
                    vals.append(float(v))
        return base.RatingsBatch(
            entity_ids=list(user_map),
            target_ids=list(item_map),
            rows=np.asarray(rows, dtype=np.int32),
            cols=np.asarray(cols, dtype=np.int32),
            vals=np.asarray(vals, dtype=np.float32),
        )

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed_order: bool = False,
    ) -> list[Event]:
        t = self._table(app_id, channel_id)
        clauses, params = [], []
        if start_time is not None:
            clauses.append("eventtime >= ?")
            params.append(_ts(start_time))
        if until_time is not None:
            clauses.append("eventtime < ?")
            params.append(_ts(until_time))
        if entity_type is not None:
            clauses.append("entitytype = ?")
            params.append(entity_type)
        if entity_id is not None:
            clauses.append("entityid = ?")
            params.append(entity_id)
        if event_names is not None:
            if not event_names:
                return []  # empty name filter matches nothing
            clauses.append(
                "event IN (" + ",".join("?" * len(event_names)) + ")"
            )
            params.extend(event_names)
        if target_entity_type is not ...:
            if target_entity_type is None:
                clauses.append("targetentitytype IS NULL")
            else:
                clauses.append("targetentitytype = ?")
                params.append(target_entity_type)
        if target_entity_id is not ...:
            if target_entity_id is None:
                clauses.append("targetentityid IS NULL")
            else:
                clauses.append("targetentityid = ?")
                params.append(target_entity_id)
        where = (" WHERE " + " AND ".join(clauses)) if clauses else ""
        order = "DESC" if reversed_order else "ASC"
        sql = f"SELECT * FROM {t}{where} ORDER BY eventtime {order}"
        if limit is not None and limit >= 0:
            sql += f" LIMIT {int(limit)}"
        try:
            rows = self._c.query(sql, params)
        except sqlite3.OperationalError as err:
            if _is_missing_table(err):
                return []
            raise
        return [self._parse(r) for r in rows]

    def close(self) -> None:
        pass
