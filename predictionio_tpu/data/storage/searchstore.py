"""``search`` backend: indexed document store with full-text event search.

The TPU framework's analog of the reference's Elasticsearch backend
(storage/elasticsearch/ — metadata DAOs + ESLEvents/ESPEvents over an
indexed document store, ESUtils query builders). There is no external ES
here; the same ROLE — a storage source whose events are additionally
full-text indexed and queryable — is filled by sqlite's FTS5 engine in
the embedded database, behind the standard registry contract:

- every DAO of the sqlite backend is reused as-is (metadata, models,
  events CRUD/find/scan_ratings all behave identically),
- event writes additionally maintain an FTS5 index over the event name,
  entity/target ids and types, and flattened property text,
- :meth:`SearchEvents.search` answers FTS queries ("laptop AND NOT
  refurbished") with ranked Events — the ESUtils-query-DSL analog.

Configured like any source: ``PIO_STORAGE_SOURCES_<NAME>_TYPE=search``
plus ``_PATH`` for the database file; serves all three repositories.
"""

from __future__ import annotations

import sqlite3

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import sqlite as sq
from predictionio_tpu.data.storage.sqlite import _is_missing_table


class SearchStorageClient(sq.SQLiteStorageClient):
    """SQLite client whose event namespaces carry an FTS5 index."""

    def __init__(self, config: dict | None = None):
        super().__init__(config)
        with self.lock:
            has_fts = self.conn.execute(
                "SELECT sqlite_compileoption_used('ENABLE_FTS5')"
            ).fetchone()[0]
        if not has_fts:  # pragma: no cover - stock builds ship FTS5
            raise RuntimeError(
                "search storage backend needs an sqlite build with FTS5"
            )


def _flatten_properties(props: dict) -> str:
    """Property bag -> searchable text: keys and scalar values, nested
    containers walked (the ES document-body analog)."""
    out: list[str] = []

    def walk(v):
        if isinstance(v, dict):
            for k, vv in v.items():
                out.append(str(k))
                walk(vv)
        elif isinstance(v, (list, tuple)):
            for vv in v:
                walk(vv)
        elif v is not None:
            out.append(str(v))

    walk(props)
    return " ".join(out)


class SearchEvents(sq.SQLiteEvents):
    """SQLiteEvents + FTS5 maintenance and a ranked ``search`` query."""

    @staticmethod
    def _fts(app_id: int, channel_id: int | None) -> str:
        return SearchEvents._table(app_id, channel_id) + "_fts"

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        super().init(app_id, channel_id)
        fts = self._fts(app_id, channel_id)
        with self._c.lock, self._c.conn:
            self._c.conn.execute(
                f"CREATE VIRTUAL TABLE IF NOT EXISTS {fts} USING fts5("
                "event_id UNINDEXED, event, entitytype, entityid, "
                "targetentitytype, targetentityid, properties)"
            )
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._c.lock, self._c.conn:
            self._c.conn.execute(
                f"DROP TABLE IF EXISTS {self._fts(app_id, channel_id)}"
            )
        return super().remove(app_id, channel_id)

    def _index_rows(self, events_ids, app_id: int, channel_id: int | None):
        fts = self._fts(app_id, channel_id)
        rows = [
            (
                event_id,
                e.event,
                e.entity_type,
                e.entity_id,
                e.target_entity_type or "",
                e.target_entity_id or "",
                _flatten_properties(e.properties.to_dict()),
            )
            for e, event_id in events_ids
        ]
        def write() -> None:
            with self._c.lock, self._c.conn:
                # replace semantics: stale index rows of re-inserted ids
                self._c.conn.executemany(
                    f"DELETE FROM {fts} WHERE event_id = ?",
                    [(eid,) for _, eid in events_ids],
                )
                self._c.conn.executemany(
                    f"INSERT INTO {fts} VALUES (?,?,?,?,?,?,?)", rows
                )

        try:
            write()
        except sqlite3.OperationalError as err:
            # auto-create the namespace like the base insert contract —
            # covers DB files created by the plain sqlite backend (base
            # event table exists, FTS table doesn't)
            if not _is_missing_table(err):
                raise
            self.init(app_id, channel_id)
            write()

    # single-event insert is NOT overridden: SQLiteEvents.insert routes
    # through self.batch_insert, so the override below indexes it once.

    def batch_insert(
        self, events, app_id: int, channel_id: int | None = None
    ) -> list[str]:
        events = list(events)
        ids = super().batch_insert(events, app_id, channel_id)
        self._index_rows(list(zip(events, ids)), app_id, channel_id)
        return ids

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        deleted = super().delete(event_id, app_id, channel_id)
        if deleted:
            try:
                with self._c.lock, self._c.conn:
                    self._c.conn.execute(
                        f"DELETE FROM {self._fts(app_id, channel_id)} "
                        "WHERE event_id = ?",
                        (event_id,),
                    )
            except sqlite3.OperationalError as err:
                if not _is_missing_table(err):
                    raise  # no FTS table -> nothing indexed to remove
        return deleted

    def search(
        self,
        app_id: int,
        query: str,
        channel_id: int | None = None,
        limit: int | None = 20,
    ) -> list[Event]:
        """Ranked full-text search over an app's events.

        ``query`` uses FTS5 match syntax (terms, AND/OR/NOT, prefix*,
        column filters like ``properties: laptop``) — the role of the
        reference's ESUtils query-DSL builders
        (storage/elasticsearch/.../ESUtils.scala). Results are Events in
        bm25 relevance order.
        """
        fts = self._fts(app_id, channel_id)
        sql = f"SELECT event_id FROM {fts} WHERE {fts} MATCH ? ORDER BY rank"
        if limit is not None and limit >= 0:
            sql += f" LIMIT {int(limit)}"
        try:
            rows = self._c.query(sql, (query,))
        except sqlite3.OperationalError as err:
            if _is_missing_table(err):
                return []
            raise
        out = []
        for (event_id,) in rows:
            e = self.get(event_id, app_id, channel_id)
            if e is not None:
                out.append(e)
        return out


DAOS = {
    "Apps": sq.SQLiteApps,
    "AccessKeys": sq.SQLiteAccessKeys,
    "Channels": sq.SQLiteChannels,
    "EngineInstances": sq.SQLiteEngineInstances,
    "EvaluationInstances": sq.SQLiteEvaluationInstances,
    "Models": sq.SQLiteModels,
    "Events": SearchEvents,
}
