"""Typed JSON wire codec for the client-server storage backend.

The HTTP storage service (server/storage_server.py) and the ``http``
backend (data/storage/httpstorage.py) exchange DAO arguments and results
as JSON with tagged envelopes for the types JSON can't carry: datetimes,
bytes, numpy arrays, Event/PropertyMap, the storage dataclasses, and the
``...`` don't-care sentinel of ``Events.find``. Plays the role the JDBC
driver's SQL type mapping plays for the reference's client-server
backend (storage/jdbc/.../JDBCUtils.scala).

Plain dicts that happen to contain a reserved tag key are escaped as
``{"__dict__": [[k, v], ...]}`` so user property bags round-trip
byte-exactly.
"""

from __future__ import annotations

import base64
from dataclasses import fields, is_dataclass
from datetime import datetime
from typing import Any

import numpy as np

from predictionio_tpu.data.event import Event, parse_time
from predictionio_tpu.data.storage import base as storage_base

# DAO methods beyond the base-class surface that ride the wire when the
# backing implementation has them (403 from the service otherwise) —
# single source of truth for the server allowlist and the client proxies
EXTENSION_METHODS: dict[str, tuple[str, ...]] = {
    "events": ("search",),  # full-text queries of the `search` backend
}

_TAGS = (
    "__dt__", "__b64__", "__nd__", "__event__", "__pm__", "__dc__",
    "__ellipsis__", "__dict__", "__tuple__", "__set__",
)

# dataclasses allowed on the wire, by name (a closed set — the decoder
# must never instantiate arbitrary classes)
_DATACLASSES = {
    cls.__name__: cls
    for cls in (
        storage_base.App,
        storage_base.AccessKey,
        storage_base.Channel,
        storage_base.EngineInstance,
        storage_base.EvaluationInstance,
        storage_base.Model,
        storage_base.RatingsBatch,
    )
}


def _iso(dt: datetime) -> str:
    return dt.isoformat()


def dumps(obj: Any) -> bytes:
    """Typed-codec JSON bytes in one call — the binary frame codec's
    extras column (data/storage/frame.py) and any other caller that
    wants the envelope without hand-rolling json.dumps(encode(...))."""
    import json

    return json.dumps(encode(obj), separators=(",", ":")).encode()


def loads(data: bytes | str) -> Any:
    """Inverse of :func:`dumps`."""
    import json

    return decode(json.loads(data))


def encode(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if obj is ...:
        return {"__ellipsis__": True}
    if isinstance(obj, datetime):
        return {"__dt__": _iso(obj)}
    if isinstance(obj, (bytes, bytearray)):
        return {"__b64__": base64.b64encode(bytes(obj)).decode("ascii")}
    if isinstance(obj, np.ndarray):
        return {
            "__nd__": {
                "dtype": obj.dtype.str,
                "shape": list(obj.shape),
                "data": base64.b64encode(np.ascontiguousarray(obj).tobytes()).decode("ascii"),
            }
        }
    if isinstance(obj, np.generic):  # numpy scalar -> python scalar
        return encode(obj.item())
    if isinstance(obj, Event):
        return {"__event__": obj.to_dict(for_api=False)}
    # PropertyMap before DataMap/dict checks (it subclasses DataMap)
    from predictionio_tpu.data.propertymap import PropertyMap

    if isinstance(obj, PropertyMap):
        return {
            "__pm__": {
                "fields": encode(obj.to_dict()),
                "first": _iso(obj.first_updated),
                "last": _iso(obj.last_updated),
            }
        }
    from predictionio_tpu.data.datamap import DataMap

    if isinstance(obj, DataMap):
        return encode(obj.to_dict())
    if is_dataclass(obj) and type(obj).__name__ in _DATACLASSES:
        return {
            "__dc__": type(obj).__name__,
            "f": {f.name: encode(getattr(obj, f.name)) for f in fields(obj)},
        }
    if isinstance(obj, tuple):
        return {"__tuple__": [encode(v) for v in obj]}
    if isinstance(obj, set):
        return {"__set__": [encode(v) for v in sorted(obj, key=repr)]}
    if isinstance(obj, list):
        return [encode(v) for v in obj]
    if isinstance(obj, dict):
        if any(k in _TAGS for k in obj):
            return {"__dict__": [[encode(k), encode(v)] for k, v in obj.items()]}
        return {str(k): encode(v) for k, v in obj.items()}
    raise TypeError(f"cannot encode {type(obj).__name__} on the storage wire")


def decode(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, list):
        return [decode(v) for v in obj]
    if isinstance(obj, dict):
        if "__ellipsis__" in obj:
            return ...
        if "__dt__" in obj:
            return parse_time(obj["__dt__"])
        if "__b64__" in obj:
            return base64.b64decode(obj["__b64__"])
        if "__nd__" in obj:
            nd = obj["__nd__"]
            arr = np.frombuffer(
                base64.b64decode(nd["data"]), dtype=np.dtype(nd["dtype"])
            )
            return arr.reshape(nd["shape"]).copy()
        if "__event__" in obj:
            return Event.from_dict(obj["__event__"])
        if "__pm__" in obj:
            from predictionio_tpu.data.propertymap import PropertyMap

            pm = obj["__pm__"]
            return PropertyMap(
                decode(pm["fields"]),
                first_updated=parse_time(pm["first"]),
                last_updated=parse_time(pm["last"]),
            )
        if "__dc__" in obj:
            cls = _DATACLASSES.get(obj["__dc__"])
            if cls is None:
                raise ValueError(f"unknown wire dataclass {obj['__dc__']}")
            return cls(**{k: decode(v) for k, v in obj["f"].items()})
        if "__tuple__" in obj:
            return tuple(decode(v) for v in obj["__tuple__"])
        if "__set__" in obj:
            return set(decode(v) for v in obj["__set__"])
        if "__dict__" in obj:
            return {decode(k): decode(v) for k, v in obj["__dict__"]}
        return {k: decode(v) for k, v in obj.items()}
    raise TypeError(f"cannot decode wire value of type {type(obj).__name__}")
