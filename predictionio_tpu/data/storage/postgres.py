"""PostgreSQL storage backend — the reference's production JDBC default.

The reference's default production metadata/event store is PostgreSQL
over JDBC (storage/jdbc/src/main/scala/org/apache/predictionio/data/
storage/jdbc/StorageClient.scala; JDBCLEvents.scala:37 for the per-app
``pio_event_<appId>[_<channel>]`` tables). This backend completes that
role for the registry: ``PIO_STORAGE_SOURCES_<X>_TYPE=postgres`` with
``host``/``port``/``dbname``/``user``/``password`` (or a single ``url``
DSN) properties.

DESIGN: the DAO logic lives once, in sqlite.py — every DAO here is a
subclass of its sqlite counterpart, connected through
:class:`_DialectConn`, an adapter that speaks the sqlite3 surface the
DAOs use (``execute``/``executemany``/``executescript``, transaction
context manager, ``lastrowid``, exception classes) while translating
the SQL dialect:

- ``?`` placeholders -> ``%s`` (the DB-API ``format`` paramstyle);
- ``INSERT OR REPLACE INTO t`` -> ``INSERT ... ON CONFLICT (id) DO
  UPDATE SET col=EXCLUDED.col ...`` (explicit per-table column lists);
- ``INSERT INTO pio_apps/pio_channels`` -> ``... RETURNING id`` so the
  sqlite ``lastrowid`` contract holds for SERIAL keys;
- undefined-table errors (SQLSTATE 42P01) -> ``sqlite3.OperationalError
  ("no such table: ...")`` and unique violations (23505) ->
  ``sqlite3.IntegrityError`` so the DAOs' create-on-demand and
  duplicate-detection paths work unchanged;
- DDL is NOT translated: postgres-specific CREATE TABLE scripts live
  here (SERIAL keys, DOUBLE PRECISION timestamps, BYTEA models).

The psycopg2 driver is imported lazily at client construction (gated,
like the boto3-gated s3 backend); the dialect adapter itself is
driver-agnostic DB-API and is exercised in CI against a fake driver
backed by sqlite (tests/test_postgres.py) — the translation layer and
every DAO path run for real there even though no postgres server is
available in the build image.
"""

from __future__ import annotations

import re
import sqlite3
import threading

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.sqlite import (
    SQLiteAccessKeys,
    SQLiteApps,
    SQLiteChannels,
    SQLiteEngineInstances,
    SQLiteEvaluationInstances,
    SQLiteEvents,
    SQLiteModels,
    _is_missing_table,
)

# column lists for the INSERT OR REPLACE -> ON CONFLICT translation
# (order matters only for readability; the SET list is what counts)
_TABLE_COLUMNS = {
    "pio_engine_instances": (
        "id", "status", "starttime", "endtime", "engineid", "engineversion",
        "enginevariant", "enginefactory", "batch", "env", "runtimeconf",
        "datasourceparams", "preparatorparams", "algorithmsparams",
        "servingparams",
    ),
    "pio_evaluation_instances": (
        "id", "status", "starttime", "endtime", "evaluationclass",
        "engineparamsgeneratorclass", "batch", "env", "runtimeconf",
        "evaluatorresults", "evaluatorresultshtml", "evaluatorresultsjson",
    ),
    "pio_models": ("id", "models"),
    # any pio_event_* table
    "pio_event": (
        "id", "event", "entitytype", "entityid", "targetentitytype",
        "targetentityid", "properties", "eventtime", "eventtimezone",
        "tags", "prid", "creationtime",
    ),
}

_OR_REPLACE = re.compile(r"^\s*INSERT OR REPLACE INTO\s+(\S+)\s+", re.I)
_RETURNING_TABLES = ("pio_apps", "pio_channels")


def translate_sql(sql: str) -> str:
    """sqlite-dialect DAO SQL -> postgres dialect (module docstring)."""
    m = _OR_REPLACE.match(sql)
    if m:
        table = m.group(1)
        key = "pio_event" if table.startswith("pio_event_") else table
        cols = _TABLE_COLUMNS.get(key)
        if cols is None:
            raise ValueError(
                f"no ON CONFLICT column list for table {table!r}"
            )
        sets = ", ".join(f"{c}=EXCLUDED.{c}" for c in cols if c != "id")
        sql = (
            f"INSERT INTO {table} {sql[m.end():]} "
            f"ON CONFLICT (id) DO UPDATE SET {sets}"
        )
    sql = sql.replace("?", "%s")
    stripped = sql.lstrip().lower()
    if stripped.startswith("insert into") and " returning " not in stripped:
        table = sql.split()[2].split("(")[0]
        if table in _RETURNING_TABLES:
            sql += " RETURNING id"
    return sql


def _sqlstate(err) -> str | None:
    """SQLSTATE of a driver exception (psycopg2 exposes ``pgcode``; the
    test fake mimics it)."""
    return getattr(err, "pgcode", None)


class _Cursor:
    """Cursor shim: adds the sqlite ``lastrowid``-from-RETURNING trick."""

    def __init__(self, cur, returned_id=None):
        self._cur = cur
        self.lastrowid = returned_id

    @property
    def rowcount(self):
        return self._cur.rowcount

    def fetchone(self):
        return self._cur.fetchone()

    def fetchall(self):
        return self._cur.fetchall()

    def fetchmany(self, n):
        return self._cur.fetchmany(n)


class _DialectConn:
    """The sqlite3-connection surface the DAOs drive, over a DB-API
    postgres connection (module docstring)."""

    def __init__(self, conn):
        self._conn = conn
        self.total_changes = 0  # only read via change_token, overridden

    def __enter__(self):
        self._conn.__enter__()
        return self

    def __exit__(self, *exc):
        return self._conn.__exit__(*exc)

    def _run(self, method, sql, arg):
        tsql = translate_sql(sql)
        cur = self._conn.cursor()
        try:
            getattr(cur, method)(tsql, arg)
        except Exception as err:  # translate driver errors (docstring)
            state = _sqlstate(err)
            if state == "42P01":
                # undefined_table: the transaction is aborted — roll it
                # back so the DAO's create-and-retry can proceed (the
                # retry re-enters `with conn`, starting a fresh one)
                self._conn.rollback()
                raise sqlite3.OperationalError(
                    f"no such table: {err}"
                ) from err
            if state is not None and state.startswith("23"):
                self._conn.rollback()
                raise sqlite3.IntegrityError(str(err)) from err
            raise
        returned = None
        if tsql.endswith(" RETURNING id"):
            row = cur.fetchone()
            returned = row[0] if row else None
        if not tsql.lstrip().lower().startswith("select"):
            self.total_changes += max(0, cur.rowcount)
        return _Cursor(cur, returned)

    def execute(self, sql: str, params: tuple | list = ()):  # noqa: A003
        if sql.lstrip().upper().startswith("PRAGMA"):
            return _Cursor(self._conn.cursor())  # sqlite-only; no-op
        return self._run("execute", sql, tuple(params))

    def executemany(self, sql: str, rows) -> None:
        self._run("executemany", sql, list(rows))

    def executescript(self, script: str) -> None:
        # DDL scripts only (no string literals containing ';')
        for stmt in script.split(";"):
            if stmt.strip():
                self.execute(stmt)

    def commit(self) -> None:
        self._conn.commit()

    def close(self) -> None:
        self._conn.close()


class PostgresStorageClient:
    """One postgres connection shared by all DAOs of this source
    (serialized by ``lock``, like the sqlite client)."""

    def __init__(self, config: dict | None = None, connection=None):
        self.config = config or {}
        self.lock = threading.RLock()
        self.ddl_bump = 0
        if connection is None:
            connection = self._connect(self.config)
        self.conn = _DialectConn(connection)
        self._init_meta_tables()

    @staticmethod
    def _connect(config: dict):
        try:
            import psycopg2
        except ImportError as e:  # pragma: no cover - driver-gated
            raise ImportError(
                "the postgres storage backend needs psycopg2 "
                "(PIO_STORAGE_SOURCES_<X>_TYPE=postgres); install "
                "psycopg2-binary or switch the source type to sqlite"
            ) from e
        if config.get("url"):
            return psycopg2.connect(config["url"])
        return psycopg2.connect(
            host=config.get("host", "localhost"),
            port=int(config.get("port", 5432)),
            dbname=config.get("dbname", "pio"),
            user=config.get("user", "pio"),
            password=config.get("password", "pio"),
        )

    def query(self, sql: str, params: tuple | list = ()) -> list:
        with self.lock:
            cur = self.conn.execute(sql, params)
            rows = cur.fetchall()
            self.conn.commit()  # leave no idle-in-transaction reads
            return rows

    def query_one(self, sql: str, params: tuple | list = ()):
        rows = self.query(sql, params)
        return rows[0] if rows else None

    def _init_meta_tables(self) -> None:
        with self.lock, self.conn:
            self.conn.executescript(
                """
                CREATE TABLE IF NOT EXISTS pio_apps (
                  id SERIAL PRIMARY KEY,
                  name TEXT NOT NULL UNIQUE,
                  description TEXT);
                CREATE TABLE IF NOT EXISTS pio_access_keys (
                  accesskey TEXT PRIMARY KEY,
                  appid INTEGER NOT NULL,
                  events TEXT NOT NULL);
                CREATE TABLE IF NOT EXISTS pio_channels (
                  id SERIAL PRIMARY KEY,
                  name TEXT NOT NULL,
                  appid INTEGER NOT NULL,
                  UNIQUE(name, appid));
                CREATE TABLE IF NOT EXISTS pio_engine_instances (
                  id TEXT PRIMARY KEY,
                  status TEXT NOT NULL,
                  starttime DOUBLE PRECISION NOT NULL,
                  endtime DOUBLE PRECISION NOT NULL,
                  engineid TEXT NOT NULL,
                  engineversion TEXT NOT NULL,
                  enginevariant TEXT NOT NULL,
                  enginefactory TEXT NOT NULL,
                  batch TEXT,
                  env TEXT,
                  runtimeconf TEXT,
                  datasourceparams TEXT,
                  preparatorparams TEXT,
                  algorithmsparams TEXT,
                  servingparams TEXT);
                CREATE TABLE IF NOT EXISTS pio_evaluation_instances (
                  id TEXT PRIMARY KEY,
                  status TEXT NOT NULL,
                  starttime DOUBLE PRECISION NOT NULL,
                  endtime DOUBLE PRECISION NOT NULL,
                  evaluationclass TEXT,
                  engineparamsgeneratorclass TEXT,
                  batch TEXT,
                  env TEXT,
                  runtimeconf TEXT,
                  evaluatorresults TEXT,
                  evaluatorresultshtml TEXT,
                  evaluatorresultsjson TEXT);
                CREATE TABLE IF NOT EXISTS pio_models (
                  id TEXT PRIMARY KEY,
                  models BYTEA NOT NULL);
                """
            )

    def close(self) -> None:
        with self.lock:
            self.conn.close()


def _advance_serial(client, table: str) -> None:
    """After an explicit-id insert, lift the SERIAL sequence past it —
    sqlite's AUTOINCREMENT never reuses explicit ids, and without this a
    later auto-id insert would collide with the restored row."""
    with client.lock:
        client.query(
            f"SELECT setval(pg_get_serial_sequence('{table}', 'id'), "
            f"(SELECT COALESCE(MAX(id), 1) FROM {table}))"
        )


class PostgresApps(SQLiteApps):
    def insert(self, app: base.App) -> int | None:
        got = super().insert(app)
        if got is not None and app.id != 0:
            _advance_serial(self._c, "pio_apps")
        return got


class PostgresAccessKeys(SQLiteAccessKeys):
    pass


class PostgresChannels(SQLiteChannels):
    def insert(self, channel: base.Channel) -> int | None:
        got = super().insert(channel)
        if got is not None and channel.id != 0:
            _advance_serial(self._c, "pio_channels")
        return got


class PostgresEngineInstances(SQLiteEngineInstances):
    pass


class PostgresEvaluationInstances(SQLiteEvaluationInstances):
    pass


class PostgresModels(SQLiteModels):
    def get(self, model_id: str) -> base.Model | None:
        got = super().get(model_id)
        if got is None:
            return None
        # psycopg2 returns BYTEA as memoryview
        return base.Model(got.id, bytes(got.models))


class PostgresEvents(SQLiteEvents):
    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        t = self._table(app_id, channel_id)
        with self._c.lock, self._c.conn:
            self._c.conn.executescript(
                f"""
                CREATE TABLE IF NOT EXISTS {t} (
                  id TEXT PRIMARY KEY,
                  event TEXT NOT NULL,
                  entitytype TEXT NOT NULL,
                  entityid TEXT NOT NULL,
                  targetentitytype TEXT,
                  targetentityid TEXT,
                  properties TEXT,
                  eventtime DOUBLE PRECISION NOT NULL,
                  eventtimezone TEXT,
                  tags TEXT,
                  prid TEXT,
                  creationtime DOUBLE PRECISION NOT NULL);
                CREATE INDEX IF NOT EXISTS {t}_time ON {t} (eventtime);
                CREATE INDEX IF NOT EXISTS {t}_entity
                  ON {t} (entitytype, entityid)
                """
            )
        return True

    @staticmethod
    def _rating_value_col(rating_key: str) -> tuple[str, list]:
        # jsonb_typeof 'number' covers ints and floats and excludes
        # booleans — same semantics as the sqlite json_type filter; the
        # key rides as a bound parameter (used twice), no quoting rules
        return (
            "CASE WHEN jsonb_typeof((properties::jsonb) -> ?) = 'number' "
            "THEN ((properties::jsonb) ->> ?)::float8 ELSE NULL END",
            [rating_key, rating_key],
        )

    def batch_insert(
        self, events, app_id: int, channel_id: int | None = None
    ) -> list[str]:
        """One ON CONFLICT DO UPDATE statement cannot touch a row twice
        (SQLSTATE 21000), so duplicate explicit event_ids within a batch
        are deduped last-wins first — the result the sqlite/jsonl
        replace semantics produce for the same input."""
        events = list(events)
        explicit = {}
        generated = []
        for e in events:
            if e.event_id:
                explicit[e.event_id] = e  # last occurrence wins
            else:
                generated.append(e)
        if len(explicit) + len(generated) == len(events):
            return super().batch_insert(events, app_id, channel_id)
        deduped = generated + list(explicit.values())
        got = super().batch_insert(deduped, app_id, channel_id)
        gen_ids = iter(got[: len(generated)])
        return [e.event_id if e.event_id else next(gen_ids) for e in events]

    def scan_ratings(self, *args, **kwargs) -> base.RatingsBatch:
        try:
            return super().scan_ratings(*args, **kwargs)
        finally:
            # the inherited scan reads through conn.execute directly;
            # without this commit the shared connection would sit
            # idle-in-transaction (pinning vacuum) until the next write
            with self._c.lock:
                self._c.conn.commit()

    _TAIL_START = (0.0, "")

    def tail_end(
        self, app_id: int, channel_id: int | None = None
    ) -> object | None:
        t = self._table(app_id, channel_id)
        try:
            with self._c.lock:
                row = self._c.query_one(
                    f"SELECT creationtime, id FROM {t} "
                    f"ORDER BY creationtime DESC, id DESC LIMIT 1"
                )
                self._c.conn.commit()
        except sqlite3.OperationalError as err:
            if _is_missing_table(err):
                return self._TAIL_START
            raise
        if row is None:
            return self._TAIL_START
        return (float(row[0]), str(row[1]))

    def tail_events(
        self,
        app_id: int,
        channel_id: int | None = None,
        after: object | None = None,
        limit: int | None = None,
    ) -> tuple[list[Event], object]:
        """Postgres has no sqlite rowid, so the cursor is the keyset
        ``(creationtime, id)`` — ingest timestamp (server-assigned at
        insert) tie-broken by the primary key. The strictly-greater
        keyset predicate makes the tail exactly-once even when a burst
        shares one timestamp: a limit-truncated read resumes mid-tie at
        the id boundary instead of skipping or re-delivering."""
        t = self._table(app_id, channel_id)
        ct, last_id = self._TAIL_START if after is None else (
            float(after[0]),
            str(after[1]),
        )
        cursor: object = (ct, last_id)
        sql = (
            f"SELECT * FROM {t} WHERE creationtime > ? "
            f"OR (creationtime = ? AND id > ?) ORDER BY creationtime, id"
        )
        params: list = [ct, ct, last_id]
        if limit is not None and limit > 0:
            sql += " LIMIT ?"
            params.append(int(limit))
        try:
            with self._c.lock:
                rows = self._c.query(sql, params)
                self._c.conn.commit()
        except sqlite3.OperationalError as err:
            if _is_missing_table(err):
                return [], cursor
            raise
        if rows:
            cursor = (float(rows[-1][11]), str(rows[-1][0]))
        return [self._parse(r) for r in rows], cursor

    def change_token(
        self, app_id: int, channel_id: int | None = None
    ) -> object | None:
        """WAL position + this client's DDL bump: any committed write to
        the cluster advances the LSN (over-invalidation across apps is
        allowed by the contract, like the sqlite token)."""
        with self._c.lock:
            row = self._c.query_one("SELECT pg_current_wal_lsn()::text")
            return (row[0] if row else None, self._c.ddl_bump)


DAOS = {
    "Apps": PostgresApps,
    "AccessKeys": PostgresAccessKeys,
    "Channels": PostgresChannels,
    "EngineInstances": PostgresEngineInstances,
    "EvaluationInstances": PostgresEvaluationInstances,
    "Models": PostgresModels,
    "Events": PostgresEvents,
}
