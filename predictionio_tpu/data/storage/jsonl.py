"""Append-log (JSON-lines) event backend.

The file-backed analog of the reference's HBase event store
(storage/hbase/src/main/scala/.../HBEventsUtil.scala: table
``events_<appId>[_<ch>]``, log-structured writes): one ``.jsonl`` file per
(app, channel), writes append a put/delete record, reads replay the log
(last write per event id wins — LSM semantics without the compaction
daemon; ``remove`` drops the file, ``compact`` rewrites it).

Capability subset: Events only — like hbase in the reference
(SURVEY §2.3), metadata/models live in another source.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import uuid
from datetime import datetime
from pathlib import Path
from typing import Sequence

try:  # advisory cross-process locks; Unix-only (this framework targets Linux)
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX fallback: thread lock only
    fcntl = None

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.memory import query_events


class JSONLStorageClient:
    def __init__(self, config: dict | None = None):
        self.config = config or {}
        self.base_path = Path(
            self.config.get("path", "~/.pio_tpu/events")
        ).expanduser()
        self.base_path.mkdir(parents=True, exist_ok=True)
        self.lock = threading.RLock()


class JSONLEvents(base.Events):
    def __init__(self, client: JSONLStorageClient):
        self._c = client

    def _file(self, app_id: int, channel_id: int | None) -> Path:
        name = f"events_{app_id}" + (
            f"_{channel_id}" if channel_id is not None else ""
        )
        return self._c.base_path / f"{name}.jsonl"

    @contextlib.contextmanager
    def _locked(self, app_id: int, channel_id: int | None):
        """Thread lock + cross-process flock on a sidecar ``.lock`` file.

        Two processes sharing one event dir (event server + trainer) must
        serialize append vs compact: a record appended mid-compact by
        another process would be dropped by the rewrite. The lock file is
        separate from the data file because ``compact`` atomically
        replaces the data file (a lock on the replaced inode would guard
        nothing).
        """
        path = self._file(app_id, channel_id)
        with self._c.lock:
            if fcntl is None:
                yield path
                return
            with open(path.with_suffix(".jsonl.lock"), "w") as lf:
                fcntl.flock(lf, fcntl.LOCK_EX)
                try:
                    yield path
                finally:
                    fcntl.flock(lf, fcntl.LOCK_UN)

    def _replay(self, app_id: int, channel_id: int | None) -> dict[str, Event]:
        """Fold the log: last record per event id wins."""
        path = self._file(app_id, channel_id)
        table: dict[str, Event] = {}
        if not path.exists():
            return table
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if "$delete" in rec:
                    table.pop(rec["$delete"], None)
                else:
                    e = Event.from_dict(rec)
                    table[e.event_id] = e
        return table

    def _append(self, app_id: int, channel_id: int | None, record: dict) -> None:
        with self._locked(app_id, channel_id) as path:
            with open(path, "a") as f:
                f.write(json.dumps(record) + "\n")
                f.flush()
                os.fsync(f.fileno())

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._locked(app_id, channel_id) as path:
            path.touch()
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._locked(app_id, channel_id) as path:
            existed = path.exists()
            path.unlink(missing_ok=True)
        # drop the lock sidecar too (after releasing the flock) so a
        # deleted app/channel leaves nothing behind
        self._file(app_id, channel_id).with_suffix(".jsonl.lock").unlink(
            missing_ok=True
        )
        return existed

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        event_id = event.event_id or uuid.uuid4().hex
        e = event.with_event_id(event_id)
        # for_api=False: keep creationTime and microsecond timestamps so
        # the replayed event is byte-identical to the inserted one
        self._append(app_id, channel_id, e.to_dict(for_api=False))
        return event_id

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        with self._locked(app_id, channel_id):
            return self._replay(app_id, channel_id).get(event_id)

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        with self._locked(app_id, channel_id) as path:
            if event_id not in self._replay(app_id, channel_id):
                return False
            # append inline (not via _append): the flock is not reentrant
            # across two opens of the lock file in the same process
            with open(path, "a") as f:
                f.write(json.dumps({"$delete": event_id}) + "\n")
                f.flush()
                os.fsync(f.fileno())
            return True

    def compact(self, app_id: int, channel_id: int | None = None) -> int:
        """Rewrite the log to its live records; returns the live count.

        Holds the cross-process lock across replay+rewrite+replace so a
        concurrent writer in another process cannot append a record that
        the rewrite would drop.
        """
        with self._locked(app_id, channel_id) as path:
            table = self._replay(app_id, channel_id)
            tmp = path.with_suffix(".jsonl.tmp")
            with open(tmp, "w") as f:
                for e in table.values():
                    f.write(json.dumps(e.to_dict(for_api=False)) + "\n")
            tmp.replace(path)
            return len(table)

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed_order: bool = False,
    ) -> list[Event]:
        with self._locked(app_id, channel_id):
            events = list(self._replay(app_id, channel_id).values())
        return query_events(
            events,
            start_time,
            until_time,
            entity_type,
            entity_id,
            event_names,
            target_entity_type,
            target_entity_id,
            limit,
            reversed_order,
        )
