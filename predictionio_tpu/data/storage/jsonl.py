"""Append-log (JSON-lines) event backend.

The file-backed analog of the reference's HBase event store
(storage/hbase/src/main/scala/.../HBEventsUtil.scala: table
``events_<appId>[_<ch>]``, log-structured writes): one ``.jsonl`` file per
(app, channel), writes append a put/delete record, reads replay the log
(last write per event id wins — LSM semantics without the compaction
daemon; ``remove`` drops the file, ``compact`` rewrites it).

Capability subset: Events only — like hbase in the reference
(SURVEY §2.3), metadata/models live in another source.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import uuid
from datetime import datetime
from pathlib import Path
from typing import Sequence

import numpy as np

try:  # advisory cross-process locks; Unix-only (this framework targets Linux)
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX fallback: thread lock only
    fcntl = None

from predictionio_tpu import faults
from predictionio_tpu.data.event import Event
from predictionio_tpu.obs import metrics as obs_metrics
from predictionio_tpu.data.storage import base, columnar_cache
from predictionio_tpu.data.storage.memory import query_events

logger = logging.getLogger(__name__)

# chunk size for bounded-RSS bulk reads: past this buffer size the
# columnar read proves cleanliness and extracts ratings in line-aligned
# chunks so peak RSS stays O(buffer + chunk), not O(buffer + spans).
# Defined once in native (span tables cost ~176 bytes/line).
from predictionio_tpu.native import SCAN_CHUNK_BYTES  # noqa: E402


def fold_jsonl_file(
    path: Path, table: dict[str, Event], deleted: set[str] | None = None
) -> None:
    """Fold one event log into ``table``: records upsert by event id,
    ``{"$delete": id}`` markers pop — the shared last-write-wins replay
    used by the jsonl and partitioned backends. When ``deleted`` is given
    it accumulates the ids whose *final* state is deleted (a re-insert
    after a delete removes the id again)."""
    if not path.exists():
        return
    with open(path) as f:
        for raw in f:
            # only the FINAL line of a log can legitimately be torn (a
            # writer killed mid-append before its newline); a corrupt
            # line anywhere else is real damage and still raises
            complete = raw.endswith("\n")
            line = raw.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                if complete:
                    raise
                logger.warning(
                    "dropping torn trailing record in %s (writer died "
                    "mid-append; the event was never acked)", path
                )
                obs_metrics.counter(
                    "pio_storage_torn_tail_dropped_total",
                    "Torn (unacked) trailing log records dropped at replay",
                ).inc()
                break
            if "$delete" in rec:
                eid = rec["$delete"]
                table.pop(eid, None)
                if deleted is not None:
                    deleted.add(eid)
            else:
                e = Event.from_dict(rec)
                table[e.event_id] = e
                if deleted is not None:
                    deleted.discard(e.event_id)


def truncate_torn_tail(path: Path) -> int:
    """Crash recovery for an append-only log: if the final line lacks
    its newline (a writer was killed mid-append), truncate back to the
    last complete record; returns the bytes dropped.

    Must run BEFORE the first post-crash append — a new record written
    after torn bytes would concatenate into one corrupt MID-file line,
    which replay correctly refuses (only a final line may be torn).
    Dropping the tail is safe: acks happen after write+flush at minimum,
    and a completed flush puts the whole line in the page cache, which
    a process kill does not tear — so torn bytes are never acked."""
    try:
        with open(path, "r+b") as f:
            size = os.fstat(f.fileno()).st_size
            if size == 0:
                return 0
            f.seek(size - 1)
            if f.read(1) == b"\n":
                return 0
            pos = size
            last_nl = -1
            while pos > 0:
                step = min(65536, pos)
                f.seek(pos - step)
                block = f.read(step)
                nl = block.rfind(b"\n")
                if nl >= 0:
                    last_nl = pos - step + nl
                    break
                pos -= step
            keep = last_nl + 1
            f.truncate(keep)
            f.flush()
            os.fsync(f.fileno())
    except FileNotFoundError:
        return 0
    except OSError:  # pragma: no cover - unreadable log: replay will say
        return 0
    dropped = size - keep
    logger.warning(
        "truncated %d torn (unacked) trailing bytes of %s before "
        "reopening for append", dropped, path,
    )
    obs_metrics.counter(
        "pio_storage_torn_tail_truncated_total",
        "Torn trailing bytes truncated at append-reopen after a crash",
    ).inc()
    return dropped


def _maybe_blank_lines(buf: bytes) -> bool:
    """Cheap conservative probe for empty/whitespace-led lines. Stored
    records always start with '{', so a whitespace byte at a line start
    indicates (at worst) a blank line; false positives merely force a
    harmless compaction. Verbatim exports use this: the clean proof
    tolerates blank lines (FLAG_EMPTY), but an export's record count
    and output must not include non-records."""
    return buf.startswith((b"\n", b"\r", b" ", b"\t")) or any(
        p in buf for p in (b"\n\n", b"\n\r", b"\n ", b"\n\t")
    )


def has_delete_markers(buf: bytes) -> bool:
    """Delete MARKERS are whole records ``{"$delete": ...}`` — the probe
    anchors at line starts so a property VALUE containing "$delete"
    (which survives rewriting) can't look like one."""
    return buf.startswith(b'{"$delete"') or b'\n{"$delete"' in buf


def _clean_scan_check(scanned) -> tuple[bool, list[str], int]:
    """Cleanliness predicate over one WHOLE-buffer span scan: returns
    (dirty, unique ids, count of lines with a scanned id). Dirty when
    any id repeats or any line's id wasn't scannable (degraded
    pure-Python mode flags ALL lines, escaped ids flag a few) — either
    could hide a replacement. The chunked path applies the same rule
    with hash-based uniqueness (see _chunked_clean_extract) so it never
    materializes per-id strings; keep the two predicates in lockstep."""
    from predictionio_tpu import native

    ids = scanned.offs[:, native.F_EVENT_ID]
    _, uniq = native.index_spans(
        scanned.buf, ids, scanned.lens[:, native.F_EVENT_ID]
    )
    n_with_id = int((ids >= 0).sum())
    n_lines = int((scanned.flags & native.FLAG_EMPTY == 0).sum())
    return (len(uniq) < n_with_id or n_with_id < n_lines), uniq, n_with_id


def prove_clean(buf: bytes):
    """Prove an event-log buffer replay-clean (no delete markers, unique
    event ids) so a columnar scan can treat it as a plain record set.

    Returns ``(needs_compact, scanned)`` where ``scanned`` is the native
    span scan (reusable by the ratings extraction) or None.
    """
    from predictionio_tpu import native

    if not buf:
        return False, None
    if has_delete_markers(buf):
        return True, None
    scanned = native.scan_events(buf)
    dirty, _, _ = _clean_scan_check(scanned)
    return dirty, scanned


def prove_clean_chunked(buf: bytes, chunk_bytes: int | None = None):
    """Chunked :func:`prove_clean` for multi-GB logs: per-chunk span
    scans (O(chunk) memory) plus a global uniqueness check over 64-bit
    id hashes. A hash collision can only FALSELY flag dirty (forcing a
    harmless compaction) — two equal ids always collide, so a true
    duplicate is never missed. Returns ``(needs_compact, None)``; the
    span scan is not reusable by design (it never exists whole).
    """
    dirty, _ = _chunked_clean_extract(buf, None, chunk_bytes)
    return dirty, None


def _chunked_clean_extract(
    buf: bytes,
    filters: dict | None,
    chunk_bytes: int | None = None,
):
    """One chunked pass proving cleanliness AND (with ``filters``)
    extracting ratings from the same per-chunk span scans — the
    single-scan property of the whole-buffer path, at O(chunk) memory.

    Returns ``(dirty, result)``: dirty means a compaction is required
    and any partial extraction was discarded; result is the
    ``load_ratings_jsonl``-shaped tuple (or None when ``filters`` is
    None — prove-only mode, or when dirty)."""
    from predictionio_tpu import native

    if chunk_bytes is None:
        chunk_bytes = SCAN_CHUNK_BYTES
    if not buf:
        return False, None
    if has_delete_markers(buf):
        return True, None
    hashes: list = []
    total_ids = 0
    merge = native.DenseMerge()
    for chunk in native._line_aligned_chunks(buf, chunk_bytes):
        scanned = native.scan_events(chunk)
        # same predicate as _clean_scan_check, but uniqueness runs over
        # native 64-bit span hashes — no per-id Python strings (millions
        # per chunk); a collision can only over-flag (harmless compact)
        ids_off = scanned.offs[:, native.F_EVENT_ID]
        has_id = ids_off >= 0
        n_with_id = int(has_id.sum())
        n_lines = int((scanned.flags & native.FLAG_EMPTY == 0).sum())
        if n_with_id < n_lines:
            return True, None  # unscannable / id-less line
        h = native.hash64_spans(
            chunk, ids_off, scanned.lens[:, native.F_EVENT_ID]
        )[has_id]
        if len(np.unique(h)) < n_with_id:
            return True, None  # intra-chunk duplicate
        total_ids += n_with_id
        hashes.append(h)
        if filters is None:
            continue
        merge.add(
            *native.load_ratings_jsonl(chunk, scanned=scanned, **filters)
        )
    if total_ids:
        all_hashes = np.concatenate(hashes)
        if len(np.unique(all_hashes)) < total_ids:
            return True, None  # cross-chunk duplicate (or hash collision)
    if filters is None:
        return False, None
    return False, merge.result()


class JSONLStorageClient:
    def __init__(self, config: dict | None = None):
        self.config = config or {}
        self.base_path = Path(
            self.config.get("path", "~/.pio_tpu/events")
        ).expanduser()
        self.base_path.mkdir(parents=True, exist_ok=True)
        self.lock = threading.RLock()
        # (mtime_ns, size) of logs last proven replay-clean (no delete
        # markers / duplicate ids): lets scan_ratings skip the uniqueness
        # pass — and, in degraded no-native mode, avoid re-compacting —
        # until the file changes
        self.clean_stat: dict[Path, tuple[int, int]] = {}
        # stricter cache for verbatim exports: proven clean AND free of
        # blank lines (clean_stat alone tolerates blanks)
        self.export_clean_stat: dict[Path, tuple[int, int]] = {}
        # per-file fsync group commit (see groupcommit.py): concurrent
        # ingest requests share fsyncs instead of paying one each. The
        # `sync` source property picks the durability mode: "always"
        # (default — ack after covering fsync) or "interval[:ms]" (ack
        # after flush, background fsync each interval — the reference's
        # HBase-WAL-hflush durability, lifting fsync-bound single-event
        # ingest)
        from predictionio_tpu.data.storage.groupcommit import (
            CoalescerMap,
            parse_sync_mode,
        )

        self.sync_interval = parse_sync_mode(self.config.get("sync"))
        self.committers = CoalescerMap(self.sync_interval)
        # cached append-side file handles (data log opened "ab", lock
        # sidecar): reopening all three files per single-event insert
        # cost ~200us/event; entries revalidate by inode under the flock
        # (compact replaces the data file, remove unlinks the sidecar).
        # LRU-capped so a server hosting many apps/channels cannot crawl
        # toward the fd ulimit (eviction closes; revalidation reopens)
        self.append_fds: dict[str, object] = {}
        self.lock_fds: dict[str, object] = {}
        self.fd_cache_cap = int(self.config.get("fd_cache_cap", 128))

    def cache_fd(self, cache: dict, key: str, f) -> None:
        """Insert with LRU eviction (dicts iterate in insertion order;
        hits re-insert to refresh recency). Caller holds ``self.lock``."""
        cache.pop(key, None)
        cache[key] = f
        if len(cache) > self.fd_cache_cap:
            old_key = next(iter(cache))
            if old_key != key:
                old = cache.pop(old_key)
                try:
                    old.close()
                except OSError:  # pragma: no cover
                    pass

    def close(self) -> None:
        """Stop the interval syncer and drop cached handles (Storage.close)."""
        self.committers.stop()
        with self.lock:
            for cache in (self.append_fds, self.lock_fds):
                for f in cache.values():
                    try:
                        f.close()
                    except OSError:  # pragma: no cover
                        pass
                cache.clear()


class JSONLEvents(base.Events):
    supports_columnar_cache = True

    def __init__(self, client: JSONLStorageClient):
        self._c = client

    def _file(self, app_id: int, channel_id: int | None) -> Path:
        name = f"events_{app_id}" + (
            f"_{channel_id}" if channel_id is not None else ""
        )
        return self._c.base_path / f"{name}.jsonl"

    @contextlib.contextmanager
    def _locked(self, app_id: int, channel_id: int | None):
        """Thread lock + cross-process flock on a sidecar ``.lock`` file.

        Two processes sharing one event dir (event server + trainer) must
        serialize append vs compact: a record appended mid-compact by
        another process would be dropped by the rewrite. The lock file is
        separate from the data file because ``compact`` atomically
        replaces the data file (a lock on the replaced inode would guard
        nothing).

        The sidecar handle is CACHED (open+flock+close per insert cost
        ~90us on the single-event hot path) and revalidated by inode
        after each acquisition: if another process ``remove``d the
        namespace (unlinking the sidecar), our lock is on a dead inode
        and a writer flocking the recreated file would run concurrently
        — detected by the stat mismatch, handle reopened, retried.
        """
        path = self._file(app_id, channel_id)
        with self._c.lock:
            if fcntl is None:
                yield path
                return
            lockpath = path.with_suffix(".jsonl.lock")
            key = str(lockpath)
            while True:
                lf = self._c.lock_fds.get(key)
                if lf is None:
                    lf = open(lockpath, "w")
                self._c.cache_fd(self._c.lock_fds, key, lf)
                fcntl.flock(lf, fcntl.LOCK_EX)
                try:
                    if os.stat(lockpath).st_ino == os.fstat(lf.fileno()).st_ino:
                        break
                except OSError:
                    pass
                fcntl.flock(lf, fcntl.LOCK_UN)
                lf.close()
                self._c.lock_fds.pop(key, None)
            try:
                yield path
            finally:
                try:
                    fcntl.flock(lf, fcntl.LOCK_UN)
                except (OSError, ValueError):
                    # evicted+closed by a nested _locked hitting the LRU
                    # cap: close already released the flock
                    pass

    def _append_fd(self, path: Path):
        """Cached ``"ab"`` handle for the data log, revalidated by inode
        (compact atomically replaces the file; a stale fd would append
        to the dead inode). Caller holds ``_locked``."""
        key = str(path)
        f = self._c.append_fds.get(key)
        if f is not None:
            try:
                if os.fstat(f.fileno()).st_ino == os.stat(key).st_ino:
                    self._c.cache_fd(self._c.append_fds, key, f)  # refresh
                    return f
            except (OSError, ValueError):
                pass
            self._c.append_fds.pop(key, None)
            try:
                f.close()
            except OSError:  # pragma: no cover
                pass
        # first open of this log in this process: recover from a torn
        # tail left by a crashed writer before any new bytes land
        truncate_torn_tail(path)
        f = open(path, "ab")
        self._c.cache_fd(self._c.append_fds, key, f)
        return f

    def _replay(self, app_id: int, channel_id: int | None) -> dict[str, Event]:
        """Fold the log: last record per event id wins."""
        table: dict[str, Event] = {}
        fold_jsonl_file(self._file(app_id, channel_id), table)
        return table

    def tail_files(
        self, app_id: int, channel_id: int | None = None
    ) -> list[Path]:
        """Log files a byte-offset tailer should follow, in replay order.
        One append-only log here; the file may not exist yet."""
        return [self._file(app_id, channel_id)]

    def change_token(
        self, app_id: int, channel_id: int | None = None
    ) -> object | None:
        """One stat: appends/compactions change (inode, mtime_ns, size),
        including writes by other processes sharing the directory."""
        try:
            st = self._file(app_id, channel_id).stat()
        except OSError:
            return ("absent",)
        return (st.st_ino, st.st_mtime_ns, st.st_size)

    def _append(self, app_id: int, channel_id: int | None, record: dict) -> None:
        self._append_group_committed(
            app_id, channel_id, (json.dumps(record) + "\n").encode()
        )

    def _append_group_committed(
        self, app_id: int, channel_id: int | None, blob: bytes
    ) -> None:
        """Append + flush under the lock, take a commit sequence, then
        wait for a covering fsync OUTSIDE the lock — so concurrent
        writers coalesce onto one fsync (ack still strictly after the
        bytes are durable). Safe across compact/remove: compact rewrites
        a fsync'ed replacement containing every locked-in append, and a
        removed file makes durability moot (see groupcommit.py)."""
        with self._locked(app_id, channel_id) as path:
            f = self._append_fd(path)
            # the buffer is empty here (every success path flushes), so
            # the on-disk size is the true pre-append length
            pre_size = os.fstat(f.fileno()).st_size
            try:
                faults.fault_point("storage.write")
                f.write(blob)
                f.flush()
            except Exception:
                # a failed write/flush can leave this blob (or a torn
                # prefix of it) buffered or partially on disk; a later
                # flush would resurrect an event the client saw FAIL,
                # and a torn tail line would corrupt replay. Evict the
                # handle, let close flush whatever it can, then roll the
                # log back to its pre-append length under the flock.
                self._c.append_fds.pop(str(path), None)
                try:
                    f.close()
                except (OSError, ValueError):
                    pass
                try:
                    with open(path, "ab") as g:
                        g.truncate(pre_size)
                except OSError:  # pragma: no cover - disk fully failed
                    logger.exception("could not roll back torn append")
                raise
            committer = self._c.committers.get(path)
            seq = committer.note_write()
        if self._c.sync_interval is None:
            committer.wait_durable(seq, path)
        # interval mode: the bytes are flushed to the page cache (they
        # survive a process crash — the reference's hflush durability);
        # the CoalescerMap's background thread fsyncs within one interval

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._locked(app_id, channel_id) as path:
            path.touch()
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._locked(app_id, channel_id) as path:
            existed = path.exists()
            path.unlink(missing_ok=True)
            columnar_cache.drop(path)
            f = self._c.append_fds.pop(str(path), None)
            if f is not None:
                f.close()
        # drop the lock sidecar too (after releasing the flock) so a
        # deleted app/channel leaves nothing behind. The cached-handle
        # eviction must run under the client lock: a concurrent _locked
        # in another thread may already hold this very handle, and
        # closing it out from under them would drop their flock
        # mid-append (later _locked calls detect the dead inode anyway)
        lockpath = self._file(app_id, channel_id).with_suffix(".jsonl.lock")
        with self._c.lock:
            lf = self._c.lock_fds.pop(str(lockpath), None)
            if lf is not None:
                lf.close()
            lockpath.unlink(missing_ok=True)
        return existed

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        event_id = event.event_id or uuid.uuid4().hex
        e = event.with_event_id(event_id)
        # for_api=False: keep creationTime and microsecond timestamps so
        # the replayed event is byte-identical to the inserted one
        self._append(app_id, channel_id, e.to_dict(for_api=False))
        return event_id

    def batch_insert(
        self, events, app_id: int, channel_id: int | None = None
    ) -> list[str]:
        """Bulk append: one lock acquisition, one write, one fsync for the
        whole batch — the import fast path (per-event fsync at 10^7-event
        scale would dominate the entire import)."""
        ids: list[str] = []
        lines: list[str] = []
        for event in events:
            event_id = event.event_id or uuid.uuid4().hex
            e = event.with_event_id(event_id)
            ids.append(event_id)
            lines.append(json.dumps(e.to_dict(for_api=False)))
        if not lines:
            return ids
        self._append_group_committed(
            app_id, channel_id, ("\n".join(lines) + "\n").encode()
        )
        return ids

    def commit_backlog(self) -> int:
        """Group-commit queue depth: appends flushed but not yet covered
        by an fsync (the event server's backpressure/stats probe)."""
        return self._c.committers.backlog()

    def sync_commits(self) -> None:
        """Force-fsync every open log now (drain-time flush)."""
        self._c.committers.sync_all()

    def append_jsonl(
        self, blob: bytes, app_id: int, channel_id: int | None = None
    ) -> None:
        """Append pre-rendered JSONL records in one locked write+fsync —
        the import splice-through fast path (cli/commands.import_events):
        the wire format IS the storage format, so validated lines skip the
        Event-object round trip entirely. Callers are responsible for
        per-line validity and eventId/creationTime presence."""
        if not blob:
            return
        if not blob.endswith(b"\n"):
            blob += b"\n"
        self._append_group_committed(app_id, channel_id, blob)

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        with self._locked(app_id, channel_id):
            return self._replay(app_id, channel_id).get(event_id)

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        with self._locked(app_id, channel_id) as path:
            if event_id not in self._replay(app_id, channel_id):
                return False
            # append inline (not via _append): the flock is not reentrant
            # across two opens of the lock file in the same process
            with open(path, "a") as f:
                f.write(json.dumps({"$delete": event_id}) + "\n")
                f.flush()
                os.fsync(f.fileno())
            return True

    def _compact_locked(self, app_id: int, channel_id: int | None, path: Path) -> int:
        """Replay + rewrite + atomic replace. Caller holds ``_locked``."""
        table = self._replay(app_id, channel_id)
        tmp = path.with_suffix(".jsonl.tmp")
        with open(tmp, "w") as f:
            for e in table.values():
                f.write(json.dumps(e.to_dict(for_api=False)) + "\n")
            f.flush()
            # fsync BEFORE replace: previously-acked (durable) records
            # are being rewritten — replacing them with an unsynced file
            # would un-durable them for a crash window
            faults.fault_point("storage.fsync")
            os.fsync(f.fileno())
        faults.fault_point("storage.rename")
        tmp.replace(path)
        # the replaced log has a new (mtime_ns, size) so a cached
        # columnar block could never serve stale — dropping it just
        # reclaims the disk immediately
        columnar_cache.drop(path)
        return len(table)

    def compact(self, app_id: int, channel_id: int | None = None) -> int:
        """Rewrite the log to its live records; returns the live count.

        Holds the cross-process lock across replay+rewrite+replace so a
        concurrent writer in another process cannot append a record that
        the rewrite would drop.
        """
        with self._locked(app_id, channel_id) as path:
            return self._compact_locked(app_id, channel_id, path)

    def export_jsonl(self, app_id: int, channel_id: int | None, out) -> int:
        """Export splice-through: the storage format IS the wire format,
        so a replay-clean log streams to ``out`` verbatim (compacting
        first when it isn't) — no per-event Python objects, the inverse
        of ``append_jsonl``. Returns the record count."""
        def _stat(path: Path) -> tuple[int, int]:
            st = path.stat()
            return (st.st_mtime_ns, st.st_size)

        # snapshot under the lock; prove OUTSIDE it (the proof of an
        # immutable snapshot needs no lock, and a multi-GB chunked proof
        # under the client-wide lock would stall every ingest request —
        # the same pattern as scan_ratings' big path)
        with self._locked(app_id, channel_id) as path:
            buf = path.read_bytes() if path.exists() else b""
            if not buf:
                return 0
            snap_stat = _stat(path)
        if self._c.export_clean_stat.get(path) == snap_stat:
            needs_compact = False  # proven clean AND blank-free, unchanged
        else:
            if len(buf) > SCAN_CHUNK_BYTES:
                needs_compact, _ = prove_clean_chunked(buf)
            else:
                needs_compact, _ = prove_clean(buf)
            # the clean proof tolerates blank lines; a verbatim export
            # must not (they'd inflate the record count)
            if not needs_compact and _maybe_blank_lines(buf):
                needs_compact = True
        if needs_compact:
            with self._locked(app_id, channel_id) as path:
                if not path.exists():
                    # remove() interleaved while we proved outside the
                    # lock; compacting would resurrect an empty file for
                    # the deleted app
                    return 0
                self._compact_locked(app_id, channel_id, path)
                buf = path.read_bytes()
                if buf:
                    snap_stat = _stat(path)
        if buf:
            # compact output is clean and blank-free by construction
            self._c.export_clean_stat[path] = snap_stat
            self._c.clean_stat[path] = snap_stat
        out.write(buf)
        n_records = buf.count(b"\n")
        if buf and not buf.endswith(b"\n"):
            out.write(b"\n")
            n_records += 1
        return n_records

    def scan_ratings(
        self,
        app_id: int,
        channel_id: int | None = None,
        *,
        event_names=None,
        entity_type: str | None = None,
        target_entity_type: str | None = None,
        rating_key: str | None = "rating",
        default_ratings: dict[str, float] | None = None,
        override_ratings: dict[str, float] | None = None,
    ) -> base.RatingsBatch:
        """Columnar fast path: native byte scan of the raw log — no Python
        Event objects (the HBase-analog bulk training read; reference
        HBPEvents TableInputFormat scan, storage/hbase/.../HBPEvents.scala).

        Log semantics (last-write-wins per event id, ``$delete`` records)
        are restored by compacting first when the log isn't already
        append-only-unique; the common import->train flow appends unique
        inserts only, so the precondition is one cheap byte/span pass
        (reused for the ratings extraction — single scan when no
        compaction is needed).

        A columnar cache (see columnar_cache.py) sits in front of the
        whole path: a warm scan mmaps packed column blocks keyed by the
        log's (mtime_ns, size) and never reads the row log at all; a
        miss runs the row path below (the correctness oracle) and then
        publishes fresh blocks for the next scan.
        """
        from predictionio_tpu import native

        # one lock acquisition across check + compact + re-read: releasing
        # between them would let a concurrent writer append a replacement
        # the re-read then double-counts
        def _stat(path: Path) -> tuple[int, int]:
            st = path.stat()
            return (st.st_mtime_ns, st.st_size)

        filters = dict(
            event_names=list(event_names) if event_names is not None else None,
            rating_key=rating_key,
            default_ratings=default_ratings,
            entity_type=entity_type,
            target_entity_type=target_entity_type,
            override_ratings=override_ratings,
        )
        use_cache = columnar_cache.enabled(self._c.config)
        if use_cache:
            # probe under the lock (stat + mmap are cheap); decode
            # outside it — the mapping snapshots the inode, so a
            # concurrent compact replacing the file can't corrupt us,
            # and its new stat just makes the next probe miss
            with self._locked(app_id, channel_id) as path:
                cb = None
                if path.exists():
                    st = _stat(path)
                    if st[1] > 0:
                        cb = columnar_cache.load(columnar_cache.cache_path(path))
                        if cb is not None and not cb.valid_for(st):
                            cb = None
            if cb is not None:
                try:
                    hit = cb.ratings(**filters)
                except Exception:  # corrupt payload bytes: fall back
                    logger.warning(
                        "columnar cache decode failed; using row scan",
                        exc_info=True,
                    )
                    hit = None
                if hit is not None:
                    users, items, rows, cols, vals = hit
                    return base.RatingsBatch(
                        entity_ids=users, target_ids=items,
                        rows=rows, cols=cols, vals=vals,
                    )
        served_stat = None
        with self._locked(app_id, channel_id) as path:
            buf = path.read_bytes() if path.exists() else b""
            snap_stat = _stat(path) if buf else None
            served_stat = snap_stat
            # multi-GB logs prove cleanliness and extract in line-aligned
            # chunks OUTSIDE the lock: whole-buffer span tables
            # (~176 B/line) would rival the 20M-event e2e's entire RSS
            # budget. The snapshot is immutable, so proof + extraction
            # of it are race-free; small logs keep the single-lock flow.
            scanned = None
            big = len(buf) > SCAN_CHUNK_BYTES
            if big:
                clean_cached = self._c.clean_stat.get(path) == snap_stat
            else:
                scanned = None
                if buf and self._c.clean_stat.get(path) == snap_stat:
                    needs_compact = False  # unchanged since proven clean
                else:
                    needs_compact, scanned = prove_clean(buf)
                if needs_compact:
                    # compact inline: the flock is not reentrant, so
                    # reuse the under-lock body, not compact()
                    self._compact_locked(app_id, channel_id, path)
                    buf = path.read_bytes()
                    scanned = None  # buf changed; rescan below
                if buf:
                    # post-compact (or just-proven-clean) logs stay
                    # clean until the file changes; record the stat so
                    # the next read skips the uniqueness pass
                    served_stat = _stat(path)
                    self._c.clean_stat[path] = served_stat
        if big:
            if clean_cached:
                res = native.load_ratings_jsonl_chunked(
                    buf, chunk_bytes=SCAN_CHUNK_BYTES, **filters
                )
            else:
                # ONE fused pass: per-chunk clean check + extraction on
                # the same span scans (the whole-buffer path's
                # single-scan property)
                dirty, res = _chunked_clean_extract(
                    buf, filters, SCAN_CHUNK_BYTES
                )
                if dirty:
                    with self._locked(app_id, channel_id) as path:
                        self._compact_locked(app_id, channel_id, path)
                        buf = path.read_bytes()
                        if buf:
                            served_stat = _stat(path)
                            self._c.clean_stat[path] = served_stat
                    # compact output is unique by construction
                    res = native.load_ratings_jsonl_chunked(
                        buf, chunk_bytes=SCAN_CHUNK_BYTES, **filters
                    )
                else:
                    self._c.clean_stat[path] = snap_stat
            users, items, rows, cols, vals = res
        else:
            users, items, rows, cols, vals = native.load_ratings_jsonl(
                buf, scanned=scanned, **filters
            )
        if use_cache and buf and served_stat is not None:
            # publish column blocks for the bytes just served, keyed by
            # the stat captured under the same lock as those bytes — a
            # concurrent append after release changes the stat, so the
            # pairing can never serve stale. Best-effort: a failed build
            # only costs the next scan its shortcut.
            try:
                blocks = columnar_cache.build_blocks(
                    buf, rating_key,
                    scanned=None if big else scanned,
                    chunk_bytes=SCAN_CHUNK_BYTES,
                )
                if blocks is not None:
                    columnar_cache.store(
                        columnar_cache.cache_path(path), served_stat, blocks
                    )
            except Exception:  # pragma: no cover - cache is optional
                logger.warning("columnar cache build failed", exc_info=True)
        return base.RatingsBatch(
            entity_ids=users, target_ids=items, rows=rows, cols=cols, vals=vals
        )

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed_order: bool = False,
    ) -> list[Event]:
        with self._locked(app_id, channel_id):
            events = list(self._replay(app_id, channel_id).values())
        return query_events(
            events,
            start_time,
            until_time,
            entity_type,
            entity_id,
            event_names,
            target_entity_type,
            target_entity_id,
            limit,
            reversed_order,
        )
