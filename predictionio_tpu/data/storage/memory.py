"""In-memory storage backend — the test-mode client.

Analog of the reference's test-mode storage clients
(StorageClientConfig.test, data/.../storage/Storage.scala:78): every DAO is
a plain dict behind a lock, suitable for unit tests and ephemeral runs.
"""

from __future__ import annotations

import copy
import itertools
import threading
import uuid
from datetime import datetime
from typing import Sequence

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base


class MemoryStorageClient:
    """Holds the shared dicts so all DAOs of one source see the same data."""

    def __init__(self, config: dict | None = None):
        self.config = config or {}
        self.lock = threading.RLock()
        self.apps: dict[int, base.App] = {}
        self.access_keys: dict[str, base.AccessKey] = {}
        self.channels: dict[int, base.Channel] = {}
        self.engine_instances: dict[str, base.EngineInstance] = {}
        self.evaluation_instances: dict[str, base.EvaluationInstance] = {}
        self.models: dict[str, base.Model] = {}
        # (app_id, channel_id) -> event_id -> Event
        self.events: dict[tuple[int, int | None], dict[str, Event]] = {}
        # (app_id, channel_id) -> write counter (Events.change_token)
        self.events_version: dict[tuple[int, int | None], int] = {}
        # (app_id, channel_id) -> [(seq, event_id)] insertion log; seq is
        # the events_version at insert time (Events.tail_events cursor)
        self.tail_logs: dict[tuple[int, int | None], list[tuple[int, str]]] = {}
        self._app_seq = itertools.count(1)
        self._channel_seq = itertools.count(1)
        self._event_seq = itertools.count(1)


class MemoryApps(base.Apps):
    def __init__(self, client: MemoryStorageClient):
        self._c = client

    def insert(self, app: base.App) -> int | None:
        with self._c.lock:
            if app.id != 0:
                app_id = app.id
            else:
                app_id = next(self._c._app_seq)
                while app_id in self._c.apps:
                    app_id = next(self._c._app_seq)
            if app_id in self._c.apps or self.get_by_name(app.name) is not None:
                return None
            self._c.apps[app_id] = base.App(app_id, app.name, app.description)
            return app_id

    def get(self, app_id: int) -> base.App | None:
        with self._c.lock:
            return self._c.apps.get(app_id)

    def get_by_name(self, name: str) -> base.App | None:
        with self._c.lock:
            for app in self._c.apps.values():
                if app.name == name:
                    return app
            return None

    def get_all(self) -> list[base.App]:
        with self._c.lock:
            return sorted(self._c.apps.values(), key=lambda a: a.id)

    def update(self, app: base.App) -> bool:
        with self._c.lock:
            if app.id not in self._c.apps:
                return False
            self._c.apps[app.id] = app
            return True

    def delete(self, app_id: int) -> bool:
        with self._c.lock:
            return self._c.apps.pop(app_id, None) is not None


class MemoryAccessKeys(base.AccessKeys):
    def __init__(self, client: MemoryStorageClient):
        self._c = client

    def insert(self, access_key: base.AccessKey) -> str | None:
        with self._c.lock:
            key = access_key.key or base.generate_access_key()
            if key in self._c.access_keys:
                return None
            self._c.access_keys[key] = base.AccessKey(
                key, access_key.appid, list(access_key.events)
            )
            return key

    def get(self, key: str) -> base.AccessKey | None:
        with self._c.lock:
            return self._c.access_keys.get(key)

    def get_all(self) -> list[base.AccessKey]:
        with self._c.lock:
            return list(self._c.access_keys.values())

    def get_by_appid(self, appid: int) -> list[base.AccessKey]:
        with self._c.lock:
            return [k for k in self._c.access_keys.values() if k.appid == appid]

    def update(self, access_key: base.AccessKey) -> bool:
        with self._c.lock:
            if access_key.key not in self._c.access_keys:
                return False
            self._c.access_keys[access_key.key] = access_key
            return True

    def delete(self, key: str) -> bool:
        with self._c.lock:
            return self._c.access_keys.pop(key, None) is not None


class MemoryChannels(base.Channels):
    def __init__(self, client: MemoryStorageClient):
        self._c = client

    def insert(self, channel: base.Channel) -> int | None:
        if not base.Channel.is_valid_name(channel.name):
            return None
        with self._c.lock:
            for ch in self._c.channels.values():
                if ch.appid == channel.appid and ch.name == channel.name:
                    return None
            if channel.id != 0:
                channel_id = channel.id
            else:
                channel_id = next(self._c._channel_seq)
                while channel_id in self._c.channels:
                    channel_id = next(self._c._channel_seq)
            if channel_id in self._c.channels:
                return None
            self._c.channels[channel_id] = base.Channel(
                channel_id, channel.name, channel.appid
            )
            return channel_id

    def get(self, channel_id: int) -> base.Channel | None:
        with self._c.lock:
            return self._c.channels.get(channel_id)

    def get_by_appid(self, appid: int) -> list[base.Channel]:
        with self._c.lock:
            return [c for c in self._c.channels.values() if c.appid == appid]

    def delete(self, channel_id: int) -> bool:
        with self._c.lock:
            return self._c.channels.pop(channel_id, None) is not None


class MemoryEngineInstances(base.EngineInstances):
    def __init__(self, client: MemoryStorageClient):
        self._c = client

    def insert(self, instance: base.EngineInstance) -> str:
        with self._c.lock:
            instance_id = instance.id or uuid.uuid4().hex
            instance.id = instance_id
            self._c.engine_instances[instance_id] = copy.deepcopy(instance)
            return instance_id

    def get(self, instance_id: str) -> base.EngineInstance | None:
        with self._c.lock:
            return copy.deepcopy(self._c.engine_instances.get(instance_id))

    def get_all(self) -> list[base.EngineInstance]:
        with self._c.lock:
            return [copy.deepcopy(i) for i in self._c.engine_instances.values()]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[base.EngineInstance]:
        with self._c.lock:
            instances = [copy.deepcopy(i) for i in self._c.engine_instances.values()]
        out = [
            i
            for i in instances
            if i.status == base.EngineInstanceStatus.COMPLETED
            and i.engine_id == engine_id
            and i.engine_version == engine_version
            and i.engine_variant == engine_variant
        ]
        return sorted(out, key=lambda i: i.start_time, reverse=True)

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> base.EngineInstance | None:
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def update(self, instance: base.EngineInstance) -> bool:
        with self._c.lock:
            if instance.id not in self._c.engine_instances:
                return False
            self._c.engine_instances[instance.id] = copy.deepcopy(instance)
            return True

    def delete(self, instance_id: str) -> bool:
        with self._c.lock:
            return self._c.engine_instances.pop(instance_id, None) is not None


class MemoryEvaluationInstances(base.EvaluationInstances):
    def __init__(self, client: MemoryStorageClient):
        self._c = client

    def insert(self, instance: base.EvaluationInstance) -> str:
        with self._c.lock:
            instance_id = instance.id or uuid.uuid4().hex
            instance.id = instance_id
            self._c.evaluation_instances[instance_id] = copy.deepcopy(instance)
            return instance_id

    def get(self, instance_id: str) -> base.EvaluationInstance | None:
        with self._c.lock:
            return copy.deepcopy(self._c.evaluation_instances.get(instance_id))

    def get_all(self) -> list[base.EvaluationInstance]:
        with self._c.lock:
            return [copy.deepcopy(i) for i in self._c.evaluation_instances.values()]

    def get_completed(self) -> list[base.EvaluationInstance]:
        with self._c.lock:
            instances = [copy.deepcopy(i) for i in self._c.evaluation_instances.values()]
        out = [
            i
            for i in instances
            if i.status == base.EvaluationInstanceStatus.EVALCOMPLETED
        ]
        return sorted(out, key=lambda i: i.start_time, reverse=True)

    def update(self, instance: base.EvaluationInstance) -> bool:
        with self._c.lock:
            if instance.id not in self._c.evaluation_instances:
                return False
            self._c.evaluation_instances[instance.id] = copy.deepcopy(instance)
            return True

    def delete(self, instance_id: str) -> bool:
        with self._c.lock:
            return self._c.evaluation_instances.pop(instance_id, None) is not None


class MemoryModels(base.Models):
    def __init__(self, client: MemoryStorageClient):
        self._c = client

    def insert(self, model: base.Model) -> None:
        with self._c.lock:
            self._c.models[model.id] = model

    def get(self, model_id: str) -> base.Model | None:
        with self._c.lock:
            return self._c.models.get(model_id)

    def delete(self, model_id: str) -> bool:
        with self._c.lock:
            return self._c.models.pop(model_id, None) is not None


class MemoryEvents(base.Events):
    def __init__(self, client: MemoryStorageClient):
        self._c = client

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._c.lock:
            self._c.events.setdefault((app_id, channel_id), {})
            return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        with self._c.lock:
            self._bump_locked(app_id, channel_id)
            self._c.tail_logs.pop((app_id, channel_id), None)
            return self._c.events.pop((app_id, channel_id), None) is not None

    def _bump_locked(self, app_id: int, channel_id: int | None) -> None:
        key = (app_id, channel_id)
        self._c.events_version[key] = self._c.events_version.get(key, 0) + 1

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        with self._c.lock:
            table = self._c.events.setdefault((app_id, channel_id), {})
            event_id = event.event_id or f"{next(self._c._event_seq):012x}"
            table[event_id] = event.with_event_id(event_id)
            self._bump_locked(app_id, channel_id)
            self._c.tail_logs.setdefault((app_id, channel_id), []).append(
                (self._c.events_version[(app_id, channel_id)], event_id)
            )
            return event_id

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        with self._c.lock:
            return self._c.events.get((app_id, channel_id), {}).get(event_id)

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        with self._c.lock:
            table = self._c.events.get((app_id, channel_id), {})
            self._bump_locked(app_id, channel_id)
            return table.pop(event_id, None) is not None

    def change_token(
        self, app_id: int, channel_id: int | None = None
    ) -> object | None:
        with self._c.lock:
            return self._c.events_version.get((app_id, channel_id), 0)

    def tail_end(
        self, app_id: int, channel_id: int | None = None
    ) -> object | None:
        with self._c.lock:
            return self._c.events_version.get((app_id, channel_id), 0)

    def tail_events(
        self,
        app_id: int,
        channel_id: int | None = None,
        after: object | None = None,
        limit: int | None = None,
    ) -> tuple[list[Event], object]:
        """Replay the insertion log past ``after`` (an events_version
        value). Deleted events are skipped; replaced events are returned
        in their CURRENT state (last write wins, like the stores)."""
        cursor = int(after or 0)
        out: list[Event] = []
        with self._c.lock:
            log = self._c.tail_logs.get((app_id, channel_id), [])
            table = self._c.events.get((app_id, channel_id), {})
            for seq, event_id in log:
                if seq <= cursor:
                    continue
                cursor = seq
                e = table.get(event_id)
                if e is not None:
                    out.append(e)
                if limit is not None and limit > 0 and len(out) >= limit:
                    break
        return out, cursor

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed_order: bool = False,
    ) -> list[Event]:
        with self._c.lock:
            events = list(self._c.events.get((app_id, channel_id), {}).values())
        return query_events(
            events,
            start_time,
            until_time,
            entity_type,
            entity_id,
            event_names,
            target_entity_type,
            target_entity_id,
            limit,
            reversed_order,
        )


def query_events(
    events: list[Event],
    start_time=None,
    until_time=None,
    entity_type=None,
    entity_id=None,
    event_names=None,
    target_entity_type=...,
    target_entity_id=...,
    limit=None,
    reversed_order=False,
) -> list[Event]:
    """Shared filter/sort/limit for in-memory event lists (used by the
    memory and jsonl backends; semantics of LEvents.futureFind)."""
    out = [
        e
        for e in events
        if _matches(
            e,
            start_time,
            until_time,
            entity_type,
            entity_id,
            event_names,
            target_entity_type,
            target_entity_id,
        )
    ]
    out.sort(key=lambda e: e.event_time, reverse=reversed_order)
    if limit is not None and limit >= 0:
        out = out[:limit]
    return out


def _matches(
    e: Event,
    start_time,
    until_time,
    entity_type,
    entity_id,
    event_names,
    target_entity_type,
    target_entity_id,
) -> bool:
    if start_time is not None and e.event_time < start_time:
        return False
    if until_time is not None and e.event_time >= until_time:
        return False
    if entity_type is not None and e.entity_type != entity_type:
        return False
    if entity_id is not None and e.entity_id != entity_id:
        return False
    if event_names is not None and e.event not in event_names:
        return False
    if target_entity_type is not ... and e.target_entity_type != target_entity_type:
        return False
    if target_entity_id is not ... and e.target_entity_id != target_entity_id:
        return False
    return True
