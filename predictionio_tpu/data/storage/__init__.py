"""Storage registry: env-var-driven backend selection and DAO factory.

Capability parity with the reference ``Storage`` object
(data/.../storage/Storage.scala:120-435):

- sources configured via ``PIO_STORAGE_SOURCES_<NAME>_TYPE`` plus free-form
  per-source properties (``PIO_STORAGE_SOURCES_<NAME>_<KEY>``),
- three logical repositories — METADATA / EVENTDATA / MODELDATA — bound to
  sources via ``PIO_STORAGE_REPOSITORIES_<REPO>_{NAME,SOURCE}``
  (Storage.scala:146-148),
- backend registry with per-backend capability subsets (sqlite: everything;
  localfs: models only; memory: everything, test mode),
- ``test_mode`` analog of ``StorageClientConfig.test`` (Storage.scala:78),
- ``verify_all_data_objects`` health check (Storage.scala:341).

Zero-config default: an embedded sqlite file + localfs model store under
``PIO_FS_BASEDIR`` (default ``~/.pio_tpu``).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (  # noqa: F401 (public re-exports)
    AccessKey,
    AccessKeys,
    App,
    Apps,
    Channel,
    Channels,
    EngineInstance,
    EngineInstanceStatus,
    EngineInstances,
    EvaluationInstance,
    EvaluationInstanceStatus,
    EvaluationInstances,
    Events,
    Model,
    Models,
    generate_access_key,
)

METADATA = "METADATA"
EVENTDATA = "EVENTDATA"
MODELDATA = "MODELDATA"
REPOSITORIES = (METADATA, EVENTDATA, MODELDATA)


class StorageError(RuntimeError):
    pass


class _Backend:
    """A registered backend type: client factory + DAO factories."""

    def __init__(
        self,
        client_factory: Callable[[dict], Any],
        daos: dict[str, Callable[[Any], Any]],
    ):
        self.client_factory = client_factory
        self.daos = daos


def _sqlite_backend() -> _Backend:
    from predictionio_tpu.data.storage import sqlite as sq

    return _Backend(
        client_factory=lambda cfg: sq.SQLiteStorageClient(cfg),
        daos={
            "Apps": sq.SQLiteApps,
            "AccessKeys": sq.SQLiteAccessKeys,
            "Channels": sq.SQLiteChannels,
            "EngineInstances": sq.SQLiteEngineInstances,
            "EvaluationInstances": sq.SQLiteEvaluationInstances,
            "Models": sq.SQLiteModels,
            "Events": sq.SQLiteEvents,
        },
    )


def _memory_backend() -> _Backend:
    from predictionio_tpu.data.storage import memory as mem

    return _Backend(
        client_factory=lambda cfg: mem.MemoryStorageClient(cfg),
        daos={
            "Apps": mem.MemoryApps,
            "AccessKeys": mem.MemoryAccessKeys,
            "Channels": mem.MemoryChannels,
            "EngineInstances": mem.MemoryEngineInstances,
            "EvaluationInstances": mem.MemoryEvaluationInstances,
            "Models": mem.MemoryModels,
            "Events": mem.MemoryEvents,
        },
    )


def _localfs_backend() -> _Backend:
    from predictionio_tpu.data.storage import localfs as lf

    return _Backend(
        client_factory=lambda cfg: lf.LocalFSStorageClient(cfg),
        daos={"Models": lf.LocalFSModels},
    )


def _jsonl_backend() -> _Backend:
    from predictionio_tpu.data.storage import jsonl as jl

    return _Backend(
        client_factory=lambda cfg: jl.JSONLStorageClient(cfg),
        daos={"Events": jl.JSONLEvents},
    )


def _hdfs_backend() -> _Backend:
    from predictionio_tpu.data.storage import objectstore as obj

    return _Backend(
        client_factory=lambda cfg: obj.dfs_storage_client(cfg),
        daos={"Models": obj.dfs_models},
    )


def _s3_backend() -> _Backend:
    from predictionio_tpu.data.storage import objectstore as obj

    return _Backend(
        client_factory=lambda cfg: obj.S3StorageClient(cfg),
        daos={"Models": obj.S3Models},
    )


def _http_backend() -> _Backend:
    from predictionio_tpu.data.storage import httpstorage as hs

    return _Backend(
        client_factory=lambda cfg: hs.HTTPStorageClient(cfg),
        daos=dict(hs.DAOS),
    )


def _partitioned_backend() -> _Backend:
    from predictionio_tpu.data.storage import partitioned as pt

    return _Backend(
        client_factory=lambda cfg: pt.PartitionedStorageClient(cfg),
        daos={"Events": pt.PartitionedEvents},
    )


def _search_backend() -> _Backend:
    from predictionio_tpu.data.storage import searchstore as ss

    return _Backend(
        client_factory=lambda cfg: ss.SearchStorageClient(cfg),
        daos=dict(ss.DAOS),
    )


def _postgres_backend() -> _Backend:
    # psycopg2-gated (like the boto3-gated s3 backend): the import error
    # surfaces at first use with a clear message
    from predictionio_tpu.data.storage import postgres as pg

    return _Backend(
        client_factory=lambda cfg: pg.PostgresStorageClient(cfg),
        daos=dict(pg.DAOS),
    )


_BACKEND_TYPES: dict[str, Callable[[], _Backend]] = {
    "sqlite": _sqlite_backend,
    "memory": _memory_backend,
    "localfs": _localfs_backend,
    "jsonl": _jsonl_backend,
    "partitioned": _partitioned_backend,
    "hdfs": _hdfs_backend,
    "s3": _s3_backend,
    "http": _http_backend,
    "search": _search_backend,
    "postgres": _postgres_backend,
}

# which repositories each backend type can serve (capability subsets,
# reference SURVEY §2.3: jdbc=all, hbase=events, localfs/hdfs/s3=models;
# http = the client-server backend, jdbc's role: all three repos)
_TYPE_CAPABILITIES: dict[str, tuple[str, ...]] = {
    "sqlite": REPOSITORIES,
    "memory": REPOSITORIES,
    "localfs": (MODELDATA,),
    "jsonl": (EVENTDATA,),
    "partitioned": (EVENTDATA,),
    "hdfs": (MODELDATA,),
    "s3": (MODELDATA,),
    "http": REPOSITORIES,
    "search": REPOSITORIES,
    "postgres": REPOSITORIES,
}


def register_backend_type(
    name: str,
    factory: Callable[[], _Backend],
    capabilities: tuple[str, ...] | None = None,
) -> None:
    """Extension point for additional backends (the reflective-load analog).

    ``capabilities`` lists the repositories the backend can serve
    (default: all three).
    """
    _BACKEND_TYPES[name] = factory
    _TYPE_CAPABILITIES[name] = (
        tuple(capabilities) if capabilities is not None else REPOSITORIES
    )


class Storage:
    """The storage registry. Usually used via the module-level singleton."""

    def __init__(self, env: dict[str, str] | None = None):
        self.env = dict(env) if env is not None else dict(os.environ)
        self._lock = threading.RLock()
        self._clients: dict[str, Any] = {}
        self._backends: dict[str, _Backend] = {}
        self._source_types: dict[str, str] = {}
        self._source_configs: dict[str, dict] = {}
        self._repo_to_source: dict[str, str] = {}
        self._parse_config()

    # -- config parsing (Storage.scala:130-199) ---------------------------
    def _parse_config(self) -> None:
        base_dir = os.path.expanduser(
            self.env.get("PIO_FS_BASEDIR", os.path.join("~", ".pio_tpu"))
        )
        prefix = "PIO_STORAGE_SOURCES_"
        sources: dict[str, dict] = {}
        # Source names may contain underscores: anchor on the *_TYPE keys to
        # learn the names, then assign remaining props by longest-name match.
        source_keys = [k for k in self.env if k.startswith(prefix)]
        names = sorted(
            (k[len(prefix):-len("_TYPE")] for k in source_keys if k.endswith("_TYPE")),
            key=len,
            reverse=True,
        )
        for name in names:
            sources[name] = {"type": self.env[f"{prefix}{name}_TYPE"]}
        for k in source_keys:
            if k.endswith("_TYPE"):
                continue
            rest = k[len(prefix):]
            owner = next((n for n in names if rest.startswith(n + "_")), None)
            if owner is None:
                raise StorageError(
                    f"cannot match env var {k} to a configured source "
                    f"(known sources: {sorted(names)}); did you set "
                    f"{prefix}<NAME>_TYPE?"
                )
            prop = rest[len(owner) + 1 :]
            sources[owner][prop.lower()] = self.env[k]
        if not sources:
            sources = {
                "SQLITE": {"type": "sqlite", "path": os.path.join(base_dir, "pio.db")},
                "LOCALFS": {
                    "type": "localfs",
                    "path": os.path.join(base_dir, "models"),
                },
            }
        for name, cfg in sources.items():
            source_type = cfg.pop("type", None)
            if source_type is None:
                raise StorageError(f"source {name} has no TYPE")
            self._source_types[name] = source_type
            self._source_configs[name] = cfg

        # Default bindings prefer capability-appropriate sources (each
        # backend implements a subset of the DAOs, like the reference's
        # backends — SURVEY §2.3); an explicit *_SOURCE binding always wins.
        def first_capable(repo: str) -> str:
            capable = [
                n
                for n, t in self._source_types.items()
                if repo in _TYPE_CAPABILITIES.get(t, ())
            ]
            if capable:
                # most specialized wins: a models-only source (localfs/
                # hdfs/s3) beats the general SQL source for MODELDATA
                return min(
                    capable,
                    key=lambda n: len(
                        _TYPE_CAPABILITIES.get(self._source_types[n], REPOSITORIES)
                    ),
                )
            return next(iter(self._source_types))

        default_repos = {repo: first_capable(repo) for repo in REPOSITORIES}
        for repo in REPOSITORIES:
            src = self.env.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE")
            if src is None:
                src = default_repos[repo]
            if src not in self._source_types:
                raise StorageError(
                    f"repository {repo} references unknown source {src}"
                )
            self._repo_to_source[repo] = src

    # -- client/DAO resolution --------------------------------------------
    def _backend(self, source_name: str) -> _Backend:
        with self._lock:
            if source_name not in self._backends:
                source_type = self._source_types[source_name]
                if source_type not in _BACKEND_TYPES:
                    raise StorageError(f"unknown storage backend type {source_type}")
                self._backends[source_name] = _BACKEND_TYPES[source_type]()
            return self._backends[source_name]

    def _client(self, source_name: str) -> Any:
        with self._lock:
            if source_name not in self._clients:
                backend = self._backend(source_name)
                self._clients[source_name] = backend.client_factory(
                    self._source_configs[source_name]
                )
            return self._clients[source_name]

    def _dao(self, repo: str, dao_name: str) -> Any:
        source_name = self._repo_to_source[repo]
        backend = self._backend(source_name)
        if dao_name not in backend.daos:
            raise StorageError(
                f"backend {self._source_types[source_name]} (source {source_name}) "
                f"does not support {dao_name}"
            )
        return backend.daos[dao_name](self._client(source_name))

    # -- public accessors (Storage.scala:366-422) -------------------------
    def get_metadata_apps(self) -> Apps:
        return self._dao(METADATA, "Apps")

    def get_metadata_access_keys(self) -> AccessKeys:
        return self._dao(METADATA, "AccessKeys")

    def get_metadata_channels(self) -> Channels:
        return self._dao(METADATA, "Channels")

    def get_metadata_engine_instances(self) -> EngineInstances:
        return self._dao(METADATA, "EngineInstances")

    def get_metadata_evaluation_instances(self) -> EvaluationInstances:
        return self._dao(METADATA, "EvaluationInstances")

    def get_events(self) -> Events:
        return self._dao(EVENTDATA, "Events")

    def get_model_data_models(self) -> Models:
        return self._dao(MODELDATA, "Models")

    def verify_all_data_objects(self) -> bool:
        """Instantiate every repository's DAOs (Storage.scala:341-363)."""
        self.get_metadata_apps()
        self.get_metadata_access_keys()
        self.get_metadata_channels()
        self.get_metadata_engine_instances()
        self.get_metadata_evaluation_instances()
        self.get_events()
        self.get_model_data_models()
        return True

    def repository_source(self, repo: str) -> tuple[str, str]:
        """(source name, backend type) bound to a repository."""
        src = self._repo_to_source[repo]
        return src, self._source_types[src]

    def close(self) -> None:
        with self._lock:
            for client in self._clients.values():
                close = getattr(client, "close", None)
                if close:
                    close()
            self._clients.clear()


# -- module-level singleton ------------------------------------------------
_instance: Storage | None = None
_instance_lock = threading.Lock()


def get_storage(refresh: bool = False) -> Storage:
    global _instance
    with _instance_lock:
        if _instance is None or refresh:
            _instance = Storage()
        return _instance


def set_storage(storage: Storage | None) -> None:
    """Install a specific Storage (tests; the test-mode client analog)."""
    global _instance
    with _instance_lock:
        _instance = storage


def test_storage() -> Storage:
    """A fully in-memory Storage (analog of StorageClientConfig.test)."""
    return Storage(
        env={
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        }
    )
