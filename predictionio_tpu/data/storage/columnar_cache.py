"""Binary columnar segment cache for the file-backed event stores.

The jsonl/partitioned backends replay JSON rows; their ``scan_ratings``
fast path already parses the raw log natively, but every training read
still re-parses every byte. This module persists the *parse result* —
packed, dictionary-encoded numpy column blocks — next to each row log,
so a re-scan goes mmap -> arrays with zero per-event work (the ALX /
ads-infra observation: at 10^7-event scale the host input pipeline, not
the accelerator, bounds training wall-clock).

Design:

- One ``<log>.colcache`` file per source log file (a jsonl namespace
  log, a sealed partition segment, or a partition's active log). The
  row log stays the source of truth — the cache is derived data,
  rebuilt at will, and **durability is unchanged**: appends go to the
  row log first exactly as before; the cache is only ever written
  AFTER a scan proved the log replay-clean.
- Columns are filter-agnostic: entity/target/event-name/entity-type
  codes into per-file dictionaries, event times as int64 microseconds,
  and the ``rating_key`` property as float32 (NaN = absent). Only the
  rating column depends on a parameter, so the cache records which
  ``rating_key`` it extracted; a scan with a different key is a miss.
- Invalidation key: the source file's ``(mtime_ns, size)``, captured
  under the same lock as the bytes the blocks were built from. Any
  append (including a ``$delete`` marker) or compaction changes the
  stat, so a stale cache can never serve — no coordination needed.
- Publication is atomic (tmp + rename) and loads validate the magic,
  header, and block bounds; any corruption or truncation makes
  :func:`load` return None and the caller falls back to the row scan.
- Logs containing scanner-fallback lines (escaped ids, odd syntax) are
  not cached (:func:`build_blocks` returns None): the fallback rows
  would need per-line json anyway, and bailing keeps the cached path
  exactly equivalent to the vectorized native scan.

The decode in :meth:`ColumnarBlocks.ratings` reproduces
``native.load_ratings_jsonl`` semantics bit-for-bit on clean buffers
(same keep-mask, same default/override resolution in float64, same
first-appearance dense id order), so the row scan remains the
correctness oracle and the parity tests can require array equality.
"""

from __future__ import annotations

import json
import logging
import mmap
import os
from pathlib import Path

import numpy as np

from predictionio_tpu import faults
from predictionio_tpu.data.storage import colspans

logger = logging.getLogger(__name__)

MAGIC = b"PIOCOLC1"
SUFFIX = ".colcache"
_ALIGN = 64
# int64-microsecond sentinel for rows without a parseable eventTime
# (defined in colspans — the shared decoder — and re-exported here)
TIME_ABSENT = colspans.TIME_ABSENT

_FALSEY = ("0", "false", "no", "off")


def enabled(config: dict | None = None) -> bool:
    """Cache on/off: the ``columnar_cache`` storage-source property
    (``PIO_STORAGE_SOURCES_<NAME>_COLUMNAR_CACHE``), with the
    ``PIO_COLUMNAR_CACHE`` env var as a global kill switch."""
    env = os.environ.get("PIO_COLUMNAR_CACHE")
    if env is not None and env.strip().lower() in _FALSEY:
        return False
    v = (config or {}).get("columnar_cache")
    if v is None:
        return True
    return str(v).strip().lower() not in _FALSEY


def cache_path(source: Path) -> Path:
    return source.with_name(source.name + SUFFIX)


def drop(source: Path) -> None:
    """Remove the cache for a source log (compaction/remove hook)."""
    try:
        cache_path(source).unlink(missing_ok=True)
    except OSError:  # pragma: no cover - unlink race
        pass


def move(src: Path, dst: Path) -> None:
    """Carry a cache across a source rename (segment sealing renames
    ``active.jsonl`` to ``seg_NNNNNN.jsonl`` without changing its bytes,
    mtime, or size — the cache stays valid under its new name)."""
    try:
        cpath = cache_path(src)
        if cpath.exists():
            cpath.rename(cache_path(dst))
    except OSError:  # pragma: no cover - rename race
        drop(src)


# --------------------------------------------------------------------------
# build
# --------------------------------------------------------------------------

_ROW_BLOCKS = (
    ("ent_code", np.int32),
    ("tgt_code", np.int32),
    ("ev_code", np.int32),
    ("etype_code", np.int32),
    ("ttype_code", np.int32),
    ("rating", np.float32),
    ("time_us", np.int64),
)


def _build_chunk(buf: bytes, rating_key: str | None, scanned=None):
    """Columns for one scanned buffer, or None when any line needs the
    json fallback (the cache only ever holds fully span-decodable logs).
    The decode itself lives in :func:`colspans.decode_columns` — the
    same implementation the tailer's columnar poll and ``pio import``
    route through, so one set of parity tests covers all three."""
    return colspans.decode_columns(buf, rating_key, scanned=scanned)


def build_blocks(
    buf: bytes,
    rating_key: str | None = "rating",
    scanned=None,
    chunk_bytes: int | None = None,
):
    """Dictionary-encoded column blocks for a replay-clean log buffer.

    Returns ``{"n", "rating_key", columns..., "<dict>_ids": [str]}`` or
    None when the buffer can't be cached (fallback lines present — which
    includes degraded no-native mode, where every line is flagged).
    Large buffers build per line-aligned chunk so span tables stay
    O(chunk); chunk dictionaries merge through shared maps.
    """
    from predictionio_tpu import native

    if chunk_bytes is None:
        chunk_bytes = native.SCAN_CHUNK_BYTES
    if len(buf) <= chunk_bytes:
        built = _build_chunk(buf, rating_key, scanned=scanned)
        if built is None:
            return None
        cols, names = built
        blocks = {"n": len(cols["ent_code"]), "rating_key": rating_key}
        blocks.update(cols)
        for d, ids in names.items():
            blocks[f"{d}_ids"] = ids
        return blocks

    maps: dict[str, dict[str, int]] = {
        d: {} for d in ("ent", "tgt", "ev", "etype", "ttype")
    }
    parts: dict[str, list[np.ndarray]] = {name: [] for name, _ in _ROW_BLOCKS}
    for chunk in native._line_aligned_chunks(buf, chunk_bytes):
        built = _build_chunk(chunk, rating_key)
        if built is None:
            return None
        cols, names = built
        for (col, d) in (
            ("ent_code", "ent"), ("tgt_code", "tgt"), ("ev_code", "ev"),
            ("etype_code", "etype"), ("ttype_code", "ttype"),
        ):
            m = maps[d]
            local = names[d]
            lut = np.fromiter(
                (m.setdefault(s, len(m)) for s in local),
                np.int32, len(local),
            )
            code = cols[col]
            if len(lut):
                cols[col] = np.where(
                    code >= 0, lut[np.clip(code, 0, None)], np.int32(-1)
                ).astype(np.int32)
        for name, _ in _ROW_BLOCKS:
            parts[name].append(cols[name])
    blocks = {"n": 0, "rating_key": rating_key}
    for name, dtype in _ROW_BLOCKS:
        blocks[name] = (
            np.concatenate(parts[name]).astype(dtype, copy=False)
            if parts[name] else np.empty(0, dtype)
        )
    blocks["n"] = len(blocks["ent_code"])
    for d, m in maps.items():
        blocks[f"{d}_ids"] = list(m)
    return blocks


def _encode_ids(ids: list[str]) -> tuple[np.ndarray, np.ndarray]:
    """utf-8 blob + [n+1] int64 offsets for one string dictionary."""
    enc = [s.encode("utf-8") for s in ids]
    offs = np.zeros(len(enc) + 1, dtype=np.int64)
    if enc:
        np.cumsum([len(b) for b in enc], out=offs[1:])
    blob = np.frombuffer(b"".join(enc), dtype=np.uint8).copy()
    return blob, offs


def store(
    path: Path,
    source_stat: tuple[int, int],
    blocks: dict,
) -> bool:
    """Atomically publish column blocks keyed by the source file's
    ``(mtime_ns, size)``. Best-effort: any OS error just means no cache
    (the row log stays authoritative)."""
    arrays: dict[str, np.ndarray] = {
        name: np.ascontiguousarray(blocks[name]) for name, _ in _ROW_BLOCKS
    }
    for d in ("ent", "tgt"):
        blob, offs = _encode_ids(blocks[f"{d}_ids"])
        arrays[f"{d}_blob"] = blob
        arrays[f"{d}_offs"] = offs
    header = {
        "mtime_ns": int(source_stat[0]),
        "size": int(source_stat[1]),
        "rating_key": blocks["rating_key"],
        "n": int(blocks["n"]),
        "ev_names": blocks["ev_ids"],
        "etype_names": blocks["etype_ids"],
        "ttype_names": blocks["ttype_ids"],
        "blocks": {},
    }
    offset = 0

    def _aligned(off: int) -> int:
        return (off + _ALIGN - 1) // _ALIGN * _ALIGN

    # lay out payload offsets relative to the end of the header; the
    # header's own length shifts them, so compute sizes first
    layout: list[tuple[str, np.ndarray, int]] = []
    for name, arr in arrays.items():
        offset = _aligned(offset)
        layout.append((name, arr, offset))
        offset += arr.nbytes
    for name, arr, off in layout:
        header["blocks"][name] = {
            "dtype": arr.dtype.str,
            "count": int(arr.size),
            "offset": off,  # relative; absolute = payload_base + offset
        }
    hdr = json.dumps(header).encode("utf-8")
    payload_base = _aligned(len(MAGIC) + 8 + len(hdr))
    tmp = path.with_name(path.name + f".tmp.{os.getpid()}")
    try:
        faults.fault_point("colcache.store")
        with open(tmp, "wb") as f:
            f.write(MAGIC)
            f.write(len(hdr).to_bytes(8, "little"))
            f.write(hdr)
            for name, arr, off in layout:
                f.seek(payload_base + off)
                f.write(arr.tobytes())
            f.flush()
            faults.fault_point("storage.fsync")
            os.fsync(f.fileno())
        faults.fault_point("storage.rename")
        tmp.replace(path)
        return True
    except OSError as e:  # pragma: no cover - disk full / perms
        logger.info("columnar cache not written (%s): %s", path, e)
        try:
            tmp.unlink(missing_ok=True)
        except OSError:
            pass
        return False


# --------------------------------------------------------------------------
# load + decode
# --------------------------------------------------------------------------


class ColumnarBlocks:
    """A loaded (mmap-backed) cache file. Arrays are read-only views
    into the mapping; the mapping stays valid even if the cache file is
    replaced on disk (rename keeps the mapped inode alive)."""

    def __init__(self, header: dict, mm, payload_base: int):
        self._header = header
        self._mm = mm
        self._base = payload_base
        self.n = int(header["n"])
        self.rating_key = header["rating_key"]
        self.ev_names: list[str] = header["ev_names"]
        self.etype_names: list[str] = header["etype_names"]
        self.ttype_names: list[str] = header["ttype_names"]

    def valid_for(self, source_stat: tuple[int, int]) -> bool:
        return (
            int(self._header["mtime_ns"]) == int(source_stat[0])
            and int(self._header["size"]) == int(source_stat[1])
        )

    def _arr(self, name: str) -> np.ndarray:
        spec = self._header["blocks"][name]
        return np.frombuffer(
            self._mm,
            dtype=np.dtype(spec["dtype"]),
            count=spec["count"],
            offset=self._base + spec["offset"],
        )

    def _decode_ids(self, d: str, codes: np.ndarray) -> list[str]:
        blob = self._arr(f"{d}_blob")
        offs = self._arr(f"{d}_offs")
        return [
            bytes(blob[offs[c]:offs[c + 1]]).decode("utf-8") for c in codes
        ]

    @staticmethod
    def _dense(codes: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """First-appearance dense remap — the same assignment order
        ``native.index_spans`` produces over the kept lines."""
        uniq, first, inv = np.unique(
            codes, return_index=True, return_inverse=True
        )
        order = np.argsort(first, kind="stable")
        rank = np.empty(len(uniq), dtype=np.int32)
        rank[order] = np.arange(len(uniq), dtype=np.int32)
        return rank[inv].astype(np.int32, copy=False), uniq[order]

    def _type_mask(self, col: str, names: list[str], wanted: str):
        code = self._arr(col)
        try:
            c = names.index(wanted)
        except ValueError:
            return np.zeros(self.n, dtype=bool)
        return code == c

    def ratings(
        self,
        event_names=None,
        entity_type: str | None = None,
        target_entity_type: str | None = None,
        rating_key: str | None = "rating",
        default_ratings: dict[str, float] | None = None,
        override_ratings: dict[str, float] | None = None,
    ):
        """Filtered ``(user_ids, item_ids, rows, cols, vals)`` from the
        blocks — semantics in lockstep with ``native.load_ratings_jsonl``.
        Returns None when the cache can't serve this ``rating_key``."""
        if rating_key is not None and rating_key != self.rating_key:
            return None
        ent = self._arr("ent_code")
        tgt = self._arr("tgt_code")
        ev = self._arr("ev_code")
        keep = (ent >= 0) & (tgt >= 0)
        if entity_type is not None:
            keep &= self._type_mask("etype_code", self.etype_names, entity_type)
        if target_entity_type is not None:
            keep &= self._type_mask(
                "ttype_code", self.ttype_names, target_entity_type
            )
        if event_names is not None:
            wanted = set(event_names)
            allowed = np.array(
                [name in wanted for name in self.ev_names], dtype=bool
            )
            if len(allowed):
                keep &= (ev >= 0) & allowed[np.clip(ev, 0, None)]
            else:
                keep &= False
        if rating_key is None:
            ratings = np.full(self.n, np.nan, dtype=np.float64)
        else:
            ratings = self._arr("rating").astype(np.float64)
        ratings = colspans.resolve_ratings(
            ratings, ev, self.ev_names, default_ratings, override_ratings
        )
        keep &= ~np.isnan(ratings)

        kept = np.flatnonzero(keep)
        rows, ucodes = self._dense(ent[kept])
        cols, icodes = self._dense(tgt[kept])
        return (
            self._decode_ids("ent", ucodes),
            self._decode_ids("tgt", icodes),
            rows,
            cols,
            ratings[kept].astype(np.float32),
        )


def load(path: Path) -> ColumnarBlocks | None:
    """mmap + validate a cache file; None on absence or any corruption
    (truncated payload, bad magic, unparseable header, out-of-bounds
    blocks) — the caller then falls back to the row scan."""
    try:
        with open(path, "rb") as f:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    except (OSError, ValueError):
        return None
    try:
        total = len(mm)
        if total < len(MAGIC) + 8 or mm[: len(MAGIC)] != MAGIC:
            raise ValueError("bad magic")
        hlen = int.from_bytes(mm[len(MAGIC):len(MAGIC) + 8], "little")
        if hlen <= 0 or len(MAGIC) + 8 + hlen > total:
            raise ValueError("bad header length")
        header = json.loads(mm[len(MAGIC) + 8:len(MAGIC) + 8 + hlen])
        payload_base = (
            (len(MAGIC) + 8 + hlen + _ALIGN - 1) // _ALIGN * _ALIGN
        )
        n = int(header["n"])
        specs = header["blocks"]
        for name, _ in _ROW_BLOCKS:
            if name not in specs or int(specs[name]["count"]) != n:
                raise ValueError(f"missing/short column {name}")
        for d in ("ent", "tgt"):
            if f"{d}_blob" not in specs or f"{d}_offs" not in specs:
                raise ValueError(f"missing dictionary {d}")
        for spec in specs.values():
            end = (
                payload_base
                + int(spec["offset"])
                + int(spec["count"]) * np.dtype(spec["dtype"]).itemsize
            )
            if end > total:
                raise ValueError("block out of bounds")
        return ColumnarBlocks(header, mm, payload_base)
    except (ValueError, KeyError, TypeError) as e:
        logger.info("columnar cache unreadable (%s): %s", path, e)
        mm.close()
        return None
