"""Hash-partitioned, segment-rotated event backend — the scalable event store.

The reference's big-data event path is HBase: one table per (app, channel),
row key = MD5(entityType+entityId) hash prefix + eventTime + uuid-low so
writes spread across regions, point gets address one region directly, and
scans prune by key/time range (reference
storage/hbase/src/main/scala/org/apache/predictionio/data/storage/hbase/HBEventsUtil.scala:54-133,
HBLEvents.scala:37, HBPEvents.scala:31-88). This backend keeps those scale
properties on a filesystem (local disk or a mounted DFS) with no region
servers:

- **Hash-spread writes.** Each (app, channel) namespace is split into P
  independent partition logs. Generated event ids embed their partition
  (``<pp>-<uuid>`` with pp = FNV-1a("entityType:entityId") % P), so an
  entity's generated events co-locate (the HBase row-prefix rule) and every
  point op addresses exactly one partition; ingest across entities fans out
  over P uncontended locks. Explicit foreign ids route by FNV-1a of the id
  itself (``native.route_id_bytes``; the hash is recorded in ``_meta.json``
  and verified on open), so a replacement always lands in the same
  partition as the original.
- **Segment rotation + time-pruned scans.** Each partition is an append-only
  ``active.jsonl`` sealed into an immutable ``seg_NNNNNN.jsonl`` at a size
  threshold. Sealing records the segment's [min, max] event-time (native
  span scan, no Python parse) in a sidecar, so time-windowed ``find``s skip
  disjoint segments wholesale — the analog of HBase's eventTime range scan.
- **Supersede-aware pruning.** Skipping a segment is only sound if nothing
  in it replaces or deletes a record in an earlier segment. Explicit-id
  inserts and deletes log their ids to a per-partition ``supersede.log``;
  sealing folds that list into the segment sidecar, and a pruned segment
  still *applies* its supersede set during replay (pops without parsing).
  Bulk ``append_jsonl`` into a non-empty partition cannot know what it
  replaces, so the segment it seals into is marked opaque = never pruned;
  ``compact`` rewrites partitions into exact, fully-prunable segments.
- **Parallel bulk reads.** ``find`` replays partitions on a thread pool;
  ``scan_ratings`` concatenates the partition logs and runs the native
  columnar codec once — the TableInputFormat-split analog feeding arrays,
  not per-record Python objects.

The partition count is fixed at namespace creation (persisted in
``_meta.json``; the stored value wins over config thereafter) because id
routing must stay stable for the life of the data.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import shutil
import threading
import uuid
from concurrent.futures import ThreadPoolExecutor
from datetime import datetime
from pathlib import Path
from typing import Sequence

import numpy as np

try:  # advisory cross-process locks; Unix-only (this framework targets Linux)
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX fallback: thread lock only
    fcntl = None

from predictionio_tpu import faults
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base, columnar_cache
from predictionio_tpu.data.storage.jsonl import (
    SCAN_CHUNK_BYTES,
    _chunked_clean_extract,
    fold_jsonl_file,
    has_delete_markers,
    prove_clean,
    prove_clean_chunked,
    truncate_torn_tail,
)
from predictionio_tpu.data.storage.memory import query_events

_SEG_RE = re.compile(r"^seg_(\d{6})\.jsonl$")
MAX_PARTITIONS = 256  # two hex digits embed the partition in the event id


def _mkdir_racing(d: Path) -> None:
    """mkdir -p that tolerates a concurrent remove(): pathlib's exist_ok
    check itself races (os.mkdir raises FileExistsError, then is_dir()
    sees the dir already deleted again); retry until one state sticks."""
    for _ in range(20):
        try:
            d.mkdir(parents=True, exist_ok=True)
            return
        except (FileExistsError, FileNotFoundError):
            continue
    raise RuntimeError(  # pragma: no cover - pathological remove() storm
        f"could not create {d}: concurrent removals kept deleting it"
    )


class PartitionedStorageClient:
    def __init__(self, config: dict | None = None):
        self.config = dict(config or {})
        self.base_path = Path(
            self.config.get("path", "~/.pio_tpu/events_partitioned")
        ).expanduser()
        self.base_path.mkdir(parents=True, exist_ok=True)
        self.partitions = int(self.config.get("partitions", 8))
        if not 1 <= self.partitions <= MAX_PARTITIONS:
            raise ValueError(
                f"partitions must be in [1, {MAX_PARTITIONS}], "
                f"got {self.partitions}"
            )
        self.segment_bytes = int(
            self.config.get("segment_bytes", 64 * 1024 * 1024)
        )
        self.lock = threading.RLock()
        # per-partition-dir thread locks (cross-process safety comes from
        # the flock; a global lock here would serialize the parallel scans)
        self.path_locks: dict[str, threading.RLock] = {}
        # per-active-log fsync group commit (see groupcommit.py); the
        # `sync` source property selects always-fsync acks (default) or
        # interval mode (flush-acked, background fsync — the reference's
        # HBase-WAL-hflush durability)
        from predictionio_tpu.data.storage.groupcommit import (
            CoalescerMap,
            parse_sync_mode,
        )

        self.sync_interval = parse_sync_mode(self.config.get("sync"))
        self.committers = CoalescerMap(self.sync_interval)
        # namespace dir -> (partition count, meta-file (inode, mtime_ns))
        # — the count is immutable for one life of the namespace; the
        # identity pair detects a remove()+recreate by another process
        self.ns_partitions: dict[str, tuple[int, tuple[int, int]]] = {}
        # namespace dir -> tuple of (path, mtime_ns, size) last proven
        # replay-clean (unique ids, no delete markers): lets scan_ratings
        # skip the uniqueness pass until any file changes
        self.clean_stat: dict[Path, tuple] = {}
        # active logs already checked for a torn tail this process life —
        # crash recovery runs once per log, before its first append
        self.torn_checked: set[str] = set()

    def close(self) -> None:
        """Stop the interval syncer thread (Storage.close)."""
        self.committers.stop()


class PartitionedEvents(base.Events):
    """Events DAO over hash-partitioned segment logs (capability subset:
    events only — like hbase in the reference, SURVEY §2.3)."""

    supports_columnar_cache = True

    def __init__(self, client: PartitionedStorageClient):
        self._c = client

    # -- layout ------------------------------------------------------------

    def _ns_dir(self, app_id: int, channel_id: int | None) -> Path:
        name = f"events_{app_id}" + (
            f"_{channel_id}" if channel_id is not None else ""
        )
        return self._c.base_path / name

    ROUTING_HASH = "fnv1a32"  # must match native.route_id_bytes

    def _publish_meta(self, ns: Path, n: int) -> tuple[int, tuple[int, int]]:
        """Atomically create ``_meta.json`` with count ``n`` unless one
        already exists; returns (winning count, meta-file identity). The
        identity pair (inode, mtime_ns) is fstat'ed from the same open
        fd the count is read from, so it describes exactly the file that
        produced the count — a caller caching (count, identity) can't
        pair a stale count with a newer file. The routing hash is
        recorded alongside the partition count and verified on read —
        opening a store routed by a different hash must fail loudly, not
        silently misroute point ops (export + re-import migrates)."""
        meta = ns / "_meta.json"
        for _ in range(20):
            if not meta.exists():
                _mkdir_racing(ns)
                # per-process-unique temp name: a shared name would let
                # two first-initializers publish each other's
                # half-written file
                tmp = ns / f"_meta.json.tmp.{os.getpid()}.{uuid.uuid4().hex}"
                try:
                    tmp.write_text(
                        json.dumps(
                            {"partitions": n, "hash": self.ROUTING_HASH}
                        )
                    )
                    # atomic create-if-absent: a concurrent process may
                    # have written meta between the check and now —
                    # theirs wins
                    os.link(tmp, meta)
                except FileExistsError:
                    pass
                except FileNotFoundError:
                    # a concurrent remove() rmtree'd the dir (and our
                    # tmp with it) mid-publish; recreate and retry
                    continue
                finally:
                    tmp.unlink(missing_ok=True)
            try:
                with open(meta, "rb") as f:
                    st = os.fstat(f.fileno())
                    side = json.loads(f.read())
                ident = (st.st_ino, st.st_mtime_ns)
                break
            except FileNotFoundError:
                # a concurrent remove() deleted the namespace between
                # publish and read; republish for its new life
                continue
        else:  # pragma: no cover - pathological remove() storm
            raise RuntimeError(
                f"could not publish _meta.json for {ns.name}: "
                "concurrent removals kept deleting it"
            )
        stored_hash = side.get("hash", "<none>")
        if stored_hash != self.ROUTING_HASH:
            raise RuntimeError(
                f"event namespace {ns.name} was created with routing hash "
                f"{stored_hash!r}; this build routes with "
                f"{self.ROUTING_HASH!r} — export from a matching build and "
                "re-import to migrate"
            )
        return int(side["partitions"]), ident

    def _n_partitions(self, ns: Path) -> int:
        """Partition count for a namespace: the persisted value wins.

        Cached per client keyed by the meta file's identity (inode +
        mtime), so the hot write/read paths cost one stat and no client
        lock — and a cross-process remove()+recreate with a DIFFERENT
        count is detected (new meta file = new inode) instead of routing
        by the stale cached count."""
        meta = ns / "_meta.json"
        cached = self._c.ns_partitions.get(str(ns))
        if cached is not None:
            n, ident = cached
            try:
                st = meta.stat()
            except OSError:
                # namespace removed: the cached count must not let writes
                # recreate data dirs without a meta file (the slow path
                # re-publishes meta first, so first-writer-wins holds for
                # the new life)
                st = None
            if st is not None and (st.st_ino, st.st_mtime_ns) == ident:
                return n
            with self._c.lock:
                self._c.ns_partitions.pop(str(ns), None)
        with self._c.lock:
            n, ident = self._publish_meta(ns, self._c.partitions)
            self._c.ns_partitions[str(ns)] = (n, ident)
            return n

    def _ensure_meta_locked(self, ns: Path, n: int) -> None:
        """Write-site guard, called under the partition lock: a remove()
        that raced in between routing and locking left no ``_meta.json``
        — republish it with the count THIS write routed by, so the
        namespace's new life keeps a meta consistent with its first
        record. If another writer republished a different count first,
        our routing is stale: refuse rather than misroute."""
        won, _ = self._publish_meta(ns, n)
        if won != n:
            with self._c.lock:
                self._c.ns_partitions.pop(str(ns), None)
            raise RuntimeError(
                f"event namespace {ns.name} was recreated with "
                f"{won} partitions while a write routed by {n} was in "
                "flight; retry the write"
            )

    def _pdir(self, ns: Path, pp: int) -> Path:
        d = ns / f"p{pp:02x}"
        _mkdir_racing(d)
        return d

    def _tlock(self, pdir: Path) -> threading.RLock:
        with self._c.lock:
            return self._c.path_locks.setdefault(
                str(pdir), threading.RLock()
            )

    @contextlib.contextmanager
    def _locked(self, pdir: Path):
        """Per-partition thread lock + cross-process flock on the
        partition's sidecar lock file (append vs seal vs compact must
        serialize; the lock file is separate from the data because
        seal/compact replace inodes). Per-partition, not client-global, so
        scans of different partitions proceed in parallel."""
        with self._tlock(pdir):
            if fcntl is None:  # pragma: no cover - non-POSIX
                yield
                return
            lock_path = pdir / ".lock"
            for _ in range(100):
                # a remove() may have rmtree'd the dir between our _pdir
                # mkdir and this open (we were blocked on the thread lock
                # it held, or a cross-process remover's); recreate and
                # retry — the namespace's new life starts with whoever
                # acquires the lock next
                try:
                    lf = open(lock_path, "w")
                except FileNotFoundError:
                    _mkdir_racing(pdir)
                    continue
                fcntl.flock(lf, fcntl.LOCK_EX)
                # a cross-process remove() can unlink the lock file while
                # we block in flock: our lock is then on a dead inode and
                # a later writer flocking the RECREATED file would run
                # concurrently with us — verify the path still names our
                # inode before trusting the lock
                try:
                    st_path = os.stat(lock_path)
                except FileNotFoundError:
                    st_path = None
                st_fd = os.fstat(lf.fileno())
                if st_path is None or (
                    (st_path.st_dev, st_path.st_ino)
                    != (st_fd.st_dev, st_fd.st_ino)
                ):
                    fcntl.flock(lf, fcntl.LOCK_UN)
                    lf.close()
                    continue
                try:
                    yield
                    return
                finally:
                    fcntl.flock(lf, fcntl.LOCK_UN)
                    lf.close()
            raise RuntimeError(  # pragma: no cover - remove() storm
                f"could not acquire partition lock {lock_path}: "
                "concurrent removals kept deleting it"
            )

    @contextlib.contextmanager
    def _locked_all(self, ns: Path, n: int):
        """All partition locks, acquired in ascending order (deadlock-free
        against any other ordered acquirer) — the cross-partition snapshot
        for bulk reads."""
        with contextlib.ExitStack() as stack:
            for pp in range(n):
                stack.enter_context(self._locked(self._pdir(ns, pp)))
            yield

    @staticmethod
    def _segments(pdir: Path) -> list[Path]:
        return sorted(
            (p for p in pdir.iterdir() if _SEG_RE.match(p.name)),
            key=lambda p: p.name,
        )

    # -- routing -----------------------------------------------------------

    @staticmethod
    def _hash_pp(key: str, n: int) -> int:
        from predictionio_tpu import native

        return native.fnv1a32(key.encode("utf-8")) % n

    @staticmethod
    def _route(event_id: str, n: int) -> int:
        """Partition of an event id — deterministic from the id alone, so
        gets, deletes, and replacements always address the same log.
        The rule (embedded ``<pp>-`` prefix else FNV-1a 32) is shared
        with the native bulk router (``native.route_id_bytes``)."""
        from predictionio_tpu import native

        return native.route_id_bytes(event_id.encode("utf-8"), n)

    # -- sealing -----------------------------------------------------------

    def _read_supersedes(self, pdir: Path) -> list[str]:
        """Pending supersede ids for the active segment: ("X <id>" explicit
        insert | "D <id>" delete) per line."""
        log = pdir / "supersede.log"
        if not log.exists():
            return []
        ids: list[str] = []
        for line in log.read_text().splitlines():
            if line:
                ids.append(line.partition(" ")[2])
        return ids

    def _seal_locked(self, pdir: Path) -> None:
        """Rotate active into an immutable segment + sidecar. Caller holds
        the partition lock."""
        from predictionio_tpu import native

        active = pdir / "active.jsonl"
        buf = active.read_bytes() if active.exists() else b""
        if not buf:
            return
        logged = self._read_supersedes(pdir)
        opaque = (pdir / "active.opaque").exists()
        scanned = native.scan_events(buf)
        nonempty = (scanned.flags & native.FLAG_EMPTY) == 0
        has_deletes = has_delete_markers(buf)
        # Validate logged supersede entries against the segment's actual
        # content: writes log the id BEFORE appending the record, so a
        # crash between the two leaves an orphan entry; folding it into
        # the sidecar unvalidated would pop a LIVE older version whenever
        # this segment is pruned. An entry counts only if its record (or
        # its delete marker) really is in the segment. The validation scan
        # runs only when there is something to validate — the bulk-ingest
        # path (no explicit ids, no deletes) skips it entirely.
        delete_idx: list[int] = []
        supersedes: list[str] = []
        if logged or has_deletes:
            delete_ids: set[str] = set()
            present: set[str] = set()
            lines = buf.split(b"\n")
            for i in range(len(scanned.flags)):
                if not nonempty[i]:
                    continue
                line = lines[i]
                eid = None
                if not line.startswith(b'{"$delete"'):
                    eid = scanned.field_str(i, native.F_EVENT_ID)
                if eid is None:
                    # one json.loads serves both probes: delete-marker
                    # detection (incl. markers the byte-prefix check
                    # missed, e.g. re-serialized with spaces) and the
                    # eventId of a line the span scanner couldn't decode
                    try:
                        rec = json.loads(line)
                    except ValueError:  # pragma: no cover - corrupt line
                        continue
                    if "$delete" in rec:
                        delete_ids.add(rec["$delete"])
                        delete_idx.append(i)
                        continue
                    eid = rec.get("eventId")
                if eid is not None:
                    present.add(eid)
            supersedes = sorted(
                {s for s in logged if s in present or s in delete_ids}
                | delete_ids
            )
        min_ts = max_ts = None
        if not opaque:
            times = native.parse_times(
                scanned.buf,
                scanned.offs[:, native.F_EVENT_TIME],
                scanned.lens[:, native.F_EVENT_TIME],
            )
            valid = nonempty & ~np.isnan(times)
            # lines without a parseable eventTime are either delete
            # markers (accounted: their ids are in the sidecar supersede
            # set, which a pruned segment still applies) or foreign
            # records we can't bound — any unaccounted one makes the
            # segment unprunable
            n_nan = int(nonempty.sum()) - int(valid.sum())
            if valid.any() and n_nan <= len(delete_idx):
                min_ts = float(times[valid].min())
                max_ts = float(times[valid].max())
            else:
                opaque = True
        segs = self._segments(pdir)
        n = (int(_SEG_RE.match(segs[-1].name).group(1)) + 1) if segs else 1
        seg = pdir / f"seg_{n:06d}.jsonl"
        side = {
            "min_ts": min_ts,
            "max_ts": max_ts,
            "supersedes": supersedes,
            "opaque": opaque,
        }
        # make the sealed bytes durable BEFORE the rename: group-committed
        # appends may still be awaiting their fsync, and once renamed
        # their coalescer would fsync a different (fresh) active file
        with open(active, "rb") as f:
            faults.fault_point("storage.fsync")
            os.fsync(f.fileno())
        faults.fault_point("storage.rename")
        active.rename(seg)
        # the rename preserves the file's bytes, size, and mtime, so a
        # columnar cache built for the active log stays valid — carry it
        # to the segment's name instead of rebuilding on the next scan
        columnar_cache.move(active, seg)
        self._c.committers.get(active).mark_all_durable()
        # atomic: a torn sidecar would otherwise poison every windowed
        # find of this partition (replay parses it)
        self._write_atomic(
            pdir / f"seg_{n:06d}.meta.json", json.dumps(side).encode()
        )
        (pdir / "supersede.log").unlink(missing_ok=True)
        (pdir / "active.opaque").unlink(missing_ok=True)

    def _maybe_seal_locked(self, pdir: Path) -> None:
        active = pdir / "active.jsonl"
        if active.exists() and active.stat().st_size >= self._c.segment_bytes:
            self._seal_locked(pdir)

    # -- replay ------------------------------------------------------------

    @staticmethod
    def _fold_file(path: Path, table: dict[str, Event]) -> None:
        fold_jsonl_file(path, table)

    def _replay_partition(
        self, pdir: Path, window: tuple[float | None, float | None] | None
    ) -> dict[str, Event]:
        """Fold one partition's logs, pruning sealed segments disjoint from
        ``window`` (epoch-seconds [start, until)); a pruned segment still
        applies its supersede set so replacements/deletes that were sealed
        past the window can't resurrect stale versions."""
        table: dict[str, Event] = {}
        for seg in self._segments(pdir):
            pruned = False
            if window is not None:
                side_path = pdir / (seg.stem + ".meta.json")
                side = None
                if side_path.exists():
                    try:
                        side = json.loads(side_path.read_text())
                    except ValueError:
                        # torn sidecar (pre-atomic-write data, or a torn
                        # filesystem): degrade to folding the segment —
                        # correct, just unpruned
                        side = None
                if side is not None:
                    if (
                        not side.get("opaque")
                        and side.get("min_ts") is not None
                        and side.get("max_ts") is not None
                    ):
                        qs, qu = window
                        disjoint = (
                            qu is not None and side["min_ts"] >= qu
                        ) or (qs is not None and side["max_ts"] < qs)
                        if disjoint:
                            for sid in side.get("supersedes", ()):
                                table.pop(sid, None)
                            pruned = True
            if not pruned:
                self._fold_file(seg, table)
        self._fold_file(pdir / "active.jsonl", table)
        return table

    # -- DAO contract ------------------------------------------------------

    def init(self, app_id: int, channel_id: int | None = None) -> bool:
        ns = self._ns_dir(app_id, channel_id)
        n = self._n_partitions(ns)
        for pp in range(n):
            self._pdir(ns, pp)
        return True

    def remove(self, app_id: int, channel_id: int | None = None) -> bool:
        ns = self._ns_dir(app_id, channel_id)
        # resolve the partition count READ-ONLY, without holding the
        # client lock across the partition-lock acquisition below: every
        # other path orders partition-lock -> client-lock (_tlock between
        # partitions in _locked_all, the clean_stat update in
        # scan_ratings), so a remover holding the client lock while
        # acquiring partition locks would invert the order and deadlock
        # against a concurrent scan. And a remover must never go through
        # _n_partitions/_publish_meta — that would RECREATE the meta a
        # concurrent remover just deleted, making both return True and
        # leaving a phantom namespace behind.
        try:
            n = int(json.loads((ns / "_meta.json").read_text())["partitions"])
        except (OSError, ValueError, KeyError, TypeError):
            return False  # no (readable) meta: nothing to remove
        # hold every partition lock so an in-flight writer can't recreate
        # files mid-rmtree; a writer arriving AFTER the remove recreates
        # the namespace by design (insert auto-creates, republishing
        # _meta.json first). _locked_all itself recreates the partition
        # dirs, so "did it exist" is answered by the meta file, not the
        # directory — which also serializes concurrent removers: the
        # second one finds the meta gone and returns False.
        with self._locked_all(ns, n):
            had_meta = (ns / "_meta.json").exists()
            # writers mkdir their partition dir BEFORE blocking on its
            # lock (_pdir then _locked), so a racing insert can recreate
            # an (empty — the locks keep data out) dir mid-rmtree; retry
            # until the walk completes
            for _ in range(20):
                try:
                    shutil.rmtree(ns)
                    break
                except FileNotFoundError:
                    break
                except OSError:
                    continue
            else:
                shutil.rmtree(ns, ignore_errors=True)
            with self._c.lock:
                self._c.clean_stat.pop(ns, None)
                self._c.ns_partitions.pop(str(ns), None)
        return had_meta

    def _recover_torn_locked(self, pdir: Path) -> None:
        """Once per process per active log (caller holds the partition
        lock): drop a torn tail left by a crashed writer before the
        first new append lands after it."""
        key = str(pdir / "active.jsonl")
        if key not in self._c.torn_checked:
            self._c.torn_checked.add(key)
            truncate_torn_tail(Path(key))

    def _append_locked(self, pdir: Path, blob: bytes) -> None:
        self._recover_torn_locked(pdir)
        with open(pdir / "active.jsonl", "ab") as f:
            faults.fault_point("storage.write")
            f.write(blob)
            f.flush()
            faults.fault_point("storage.fsync")
            os.fsync(f.fileno())

    def _log_supersede_locked(
        self, pdir: Path, tag: str, eids: Sequence[str]
    ) -> None:
        """One write+fsync for the whole entry batch."""
        with open(pdir / "supersede.log", "a") as f:
            f.write("".join(f"{tag} {eid}\n" for eid in eids))
            f.flush()
            # fsync BEFORE the data append's fsync: if the record survives
            # a crash its supersede entry must too, or a later sealed
            # segment would be marked prunable without it and windowed
            # reads could resurrect the stale older version (the inverse
            # crash — entry without record — is validated away at seal)
            os.fsync(f.fileno())

    def insert(
        self, event: Event, app_id: int, channel_id: int | None = None
    ) -> str:
        ns = self._ns_dir(app_id, channel_id)
        n = self._n_partitions(ns)
        explicit = bool(event.event_id)
        if explicit:
            event_id = event.event_id
            pp = self._route(event_id, n)
        else:
            pp = self._hash_pp(f"{event.entity_type}:{event.entity_id}", n)
            event_id = f"{pp:02x}-{uuid.uuid4().hex}"
        e = event.with_event_id(event_id)
        pdir = self._pdir(ns, pp)
        line = (json.dumps(e.to_dict(for_api=False)) + "\n").encode()
        if explicit:
            # strict path: the supersede entry must be durable BEFORE the
            # record (ordering across two files — a coalesced fsync of
            # the data log could otherwise land first)
            with self._locked(pdir):
                self._ensure_meta_locked(ns, n)
                self._log_supersede_locked(pdir, "X", [event_id])
                self._append_locked(pdir, line)
                self._maybe_seal_locked(pdir)
            return event_id
        # generated-id hot path (the event server's single-event ingest):
        # append+flush under the lock, fsync via group commit outside it
        with self._locked(pdir):
            self._ensure_meta_locked(ns, n)
            committer, seq, active = self._append_group_committed_locked(
                pdir, line
            )
            self._maybe_seal_locked(pdir)
        if self._c.sync_interval is None:
            committer.wait_durable(seq, active)
        # interval mode: flushed to the page cache; the background
        # syncer makes it disk-durable within one interval
        return event_id

    def _append_group_committed_locked(
        self, pdir: Path, blob: bytes
    ) -> tuple:
        """Append + flush to the partition's active log under the (held)
        partition lock and take a commit sequence; returns (committer,
        seq, path) for the caller to ``wait_durable`` OUTSIDE the lock.
        The flush-before-note_write ordering and the outside-the-lock
        wait are the group-commit protocol's invariants (groupcommit.py);
        every group-committed append must go through here."""
        self._recover_torn_locked(pdir)
        active = pdir / "active.jsonl"
        with open(active, "ab") as f:
            faults.fault_point("storage.write")
            f.write(blob)
            f.flush()
        committer = self._c.committers.get(active)
        return committer, committer.note_write(), active

    def batch_insert(
        self, events, app_id: int, channel_id: int | None = None
    ) -> list[str]:
        """Bulk append: one lock acquisition + write + fsync per touched
        partition (the ingest fast path; per-event fsync would dominate)."""
        ns = self._ns_dir(app_id, channel_id)
        n = self._n_partitions(ns)
        ids: list[str] = []
        per_part: dict[int, list[bytes]] = {}
        per_part_x: dict[int, list[str]] = {}
        for event in events:
            explicit = bool(event.event_id)
            if explicit:
                event_id = event.event_id
                pp = self._route(event_id, n)
                per_part_x.setdefault(pp, []).append(event_id)
            else:
                pp = self._hash_pp(
                    f"{event.entity_type}:{event.entity_id}", n
                )
                event_id = f"{pp:02x}-{uuid.uuid4().hex}"
            ids.append(event_id)
            per_part.setdefault(pp, []).append(
                (json.dumps(
                    event.with_event_id(event_id).to_dict(for_api=False)
                ) + "\n").encode()
            )
        waits = []
        for pp, lines in per_part.items():
            pdir = self._pdir(ns, pp)
            xids = per_part_x.get(pp)
            if xids:
                # explicit ids: strict ordered fsyncs (see insert)
                with self._locked(pdir):
                    self._ensure_meta_locked(ns, n)
                    self._log_supersede_locked(pdir, "X", xids)
                    self._append_locked(pdir, b"".join(lines))
                    self._maybe_seal_locked(pdir)
                continue
            with self._locked(pdir):
                self._ensure_meta_locked(ns, n)
                waits.append(
                    self._append_group_committed_locked(pdir, b"".join(lines))
                )
                self._maybe_seal_locked(pdir)
        if self._c.sync_interval is None:
            for committer, seq, active in waits:
                committer.wait_durable(seq, active)
        return ids

    def commit_backlog(self) -> int:
        """Group-commit queue depth across partitions: appends flushed
        but not yet covered by an fsync (backpressure/stats probe)."""
        return self._c.committers.backlog()

    def append_jsonl(
        self, blob: bytes, app_id: int, channel_id: int | None = None
    ) -> None:
        """Import splice fast path: route pre-rendered JSONL lines to their
        partitions with one native span scan (no per-record Python objects)
        and one locked write+fsync per partition. Lines must each carry an
        eventId (cli import validates). A partition that already holds data
        gets its in-flight segment marked opaque — the import may replace
        ids we can't enumerate cheaply, so that segment is never pruned
        (``compact`` restores exact prunable segments)."""
        from predictionio_tpu import native

        if not blob:
            return
        if not blob.endswith(b"\n"):
            blob += b"\n"
        ns = self._ns_dir(app_id, channel_id)
        n = self._n_partitions(ns)
        scanned = native.scan_events(blob)
        # line byte spans, vectorized (ends at each newline)
        ends = (
            np.flatnonzero(np.frombuffer(blob, np.uint8) == ord("\n")) + 1
        )
        starts = np.empty_like(ends)
        starts[0] = 0
        starts[1:] = ends[:-1]
        # one native pass routes every id span; fallback-flagged lines
        # (escaped ids, odd syntax) MUST take the json path — their raw
        # span bytes differ from the decoded id, so routing by the span
        # would diverge from get()/delete()'s routing of the decoded id
        routes = native.route_ids(
            blob,
            scanned.offs[:, native.F_EVENT_ID],
            scanned.lens[:, native.F_EVENT_ID],
            n,
        )
        routes[(scanned.flags & native.FLAG_FALLBACK) != 0] = -1
        empty = (scanned.flags & native.FLAG_EMPTY) != 0
        per_part: dict[int, list[bytes]] = {}
        for i in np.flatnonzero((routes < 0) & ~empty):
            rec = json.loads(blob[starts[i]:ends[i]])
            eid = rec.get("eventId")
            if eid is None:
                raise ValueError(
                    "append_jsonl line missing eventId "
                    "(required for partition routing)"
                )
            routes[i] = self._route(eid, n)
        # group line indexes by partition with one stable sort (O(n log n);
        # a flatnonzero per partition would rescan routes up to 256 times)
        order = np.argsort(routes, kind="stable")
        sorted_routes = routes[order]
        uniq, first = np.unique(sorted_routes, return_index=True)
        bounds = np.append(first, len(order))
        for k, pp in enumerate(uniq):
            if pp < 0:
                continue  # empty lines
            idx = order[bounds[k]:bounds[k + 1]]
            per_part[int(pp)] = [
                blob[starts[i]:ends[i]] for i in idx
            ]
        def write_part(pp: int, lines: list[bytes]) -> None:
            pdir = self._pdir(ns, pp)
            with self._locked(pdir):
                self._ensure_meta_locked(ns, n)
                active = pdir / "active.jsonl"
                nonempty = (
                    active.exists() and active.stat().st_size > 0
                ) or bool(self._segments(pdir))
                if nonempty:
                    (pdir / "active.opaque").touch()
                self._append_locked(pdir, b"".join(lines))
                self._maybe_seal_locked(pdir)

        if len(per_part) > 1:
            # fan the per-partition appends out on threads: each append
            # fsyncs its own active log, and P serial fsyncs (not the
            # byte writes) dominate bulk-import wall clock. Partition
            # locks keep each append's durability semantics identical
            # to the sequential loop; list() re-raises worker errors.
            with ThreadPoolExecutor(
                max_workers=min(len(per_part), os.cpu_count() or 4)
            ) as pool:
                list(
                    pool.map(lambda kv: write_part(*kv), per_part.items())
                )
        else:
            for pp, lines in per_part.items():
                write_part(pp, lines)

    def tail_files(
        self, app_id: int, channel_id: int | None = None
    ) -> list[Path]:
        """Log files a byte-offset tailer should follow: per partition the
        sealed segments (immutable once named ``seg_*``) then the active
        log. A seal moves bytes from active to a new segment path — the
        tailer sees the active file shrink (lineage break, re-read) and
        the new segment appear; its watermark dedupe skips the re-read of
        already-delivered records."""
        ns = self._ns_dir(app_id, channel_id)
        if not (ns / "_meta.json").exists():
            return []
        n = self._n_partitions(ns)
        out: list[Path] = []
        for pp in range(n):
            pdir = ns / f"p{pp:02x}"
            if not pdir.is_dir():
                continue
            out.extend(self._segments(pdir))
            out.append(pdir / "active.jsonl")
        return out

    def change_token(
        self, app_id: int, channel_id: int | None = None
    ) -> object | None:
        """Two stats per partition, no directory listings: the active
        log's (mtime_ns, size) sees appends, the partition dir's mtime
        sees seals/compactions/imports (they create or rename files)."""
        ns = self._ns_dir(app_id, channel_id)
        try:
            n = int(json.loads((ns / "_meta.json").read_text())["partitions"])
        except (OSError, ValueError, KeyError, TypeError):
            return ("absent",)
        toks: list = []
        for pp in range(n):
            pdir = ns / f"p{pp:02x}"
            try:
                st_d = pdir.stat()
                toks.append(st_d.st_mtime_ns)
            except OSError:
                toks.append(None)
                continue
            try:
                st_a = (pdir / "active.jsonl").stat()
                toks.append((st_a.st_mtime_ns, st_a.st_size))
            except OSError:
                toks.append(None)
        return tuple(toks)

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Event | None:
        ns = self._ns_dir(app_id, channel_id)
        if not ns.exists():
            return None
        pdir = self._pdir(ns, self._route(event_id, self._n_partitions(ns)))
        with self._locked(pdir):
            return self._replay_partition(pdir, None).get(event_id)

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        ns = self._ns_dir(app_id, channel_id)
        if not ns.exists():
            return False
        n = self._n_partitions(ns)
        pdir = self._pdir(ns, self._route(event_id, n))
        with self._locked(pdir):
            if event_id not in self._replay_partition(pdir, None):
                return False
            self._ensure_meta_locked(ns, n)
            self._log_supersede_locked(pdir, "D", [event_id])
            self._append_locked(
                pdir, (json.dumps({"$delete": event_id}) + "\n").encode()
            )
        return True

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: datetime | None = None,
        until_time: datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: Sequence[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed_order: bool = False,
    ) -> list[Event]:
        ns = self._ns_dir(app_id, channel_id)
        if not ns.exists():
            return []
        n = self._n_partitions(ns)
        window = None
        if start_time is not None or until_time is not None:
            window = (
                start_time.timestamp() if start_time is not None else None,
                until_time.timestamp() if until_time is not None else None,
            )

        def scan(pp: int) -> dict[str, Event]:
            pdir = self._pdir(ns, pp)
            with self._locked(pdir):
                return self._replay_partition(pdir, window)

        events: list[Event] = []
        if n == 1:
            events = list(scan(0).values())
        else:
            with ThreadPoolExecutor(
                max_workers=min(n, os.cpu_count() or 4)
            ) as pool:
                for table in pool.map(scan, range(n)):
                    events.extend(table.values())
        return query_events(
            events,
            start_time,
            until_time,
            entity_type,
            entity_id,
            event_names,
            target_entity_type,
            target_entity_id,
            limit,
            reversed_order,
        )

    @staticmethod
    def _write_atomic(path: Path, blob: bytes) -> None:
        tmp = path.with_name(path.name + ".tmp")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        tmp.replace(path)

    def _compact_partition_locked(self, pdir: Path) -> int:
        """Rewrite one partition to its live records in exact, bounded,
        supersede-free segments; returns the live count. Caller holds the
        partition lock.

        Crash-safe in two phases. Phase 1 publishes the COMPLETE live set
        (plus tombstones for ids whose final state is deleted, since the
        old segments still exist) into ``active.jsonl`` via tmp+rename —
        from that commit point, replay over [old segments + new active]
        is correct under any crash, because active folds last. Phase 2
        removes the old segments and re-establishes bounded sealed
        segments, each published via its own tmp+rename (a torn write
        never enters replay), truncating active only after every segment
        is durable; in every intermediate state replay sees either the
        full copy in active, or segments plus a redundant identical copy
        (which the next scan's uniqueness check compacts away)."""
        table: dict[str, Event] = {}
        deleted: set[str] = set()
        segs = self._segments(pdir)
        for seg in segs:
            fold_jsonl_file(seg, table, deleted)
        active = pdir / "active.jsonl"
        fold_jsonl_file(active, table, deleted)
        if not table and not deleted and not segs:
            return 0  # untouched partition: nothing to rewrite

        lines: dict[str, bytes] = {}
        times: dict[str, float] = {}
        for eid, e in table.items():
            lines[eid] = (json.dumps(e.to_dict(for_api=False)) + "\n").encode()
            times[eid] = e.event_time.timestamp()

        # phase 1 — commit point
        full = b"".join(
            (json.dumps({"$delete": eid}) + "\n").encode()
            for eid in sorted(deleted)
        ) + b"".join(lines.values())
        self._write_atomic(active, full)

        for seg in self._segments(pdir):
            (pdir / (seg.stem + ".meta.json")).unlink(missing_ok=True)
            columnar_cache.drop(seg)
            seg.unlink()
        (pdir / "supersede.log").unlink(missing_ok=True)
        (pdir / "active.opaque").unlink(missing_ok=True)

        # phase 2 — re-segment; full chunks become sealed segments, the
        # tail stays in active
        seg_n = 0
        chunk: list[str] = []
        size = 0

        def seal_chunk() -> None:
            nonlocal seg_n, chunk, size
            seg_n += 1
            seg = pdir / f"seg_{seg_n:06d}.jsonl"
            self._write_atomic(seg, b"".join(lines[eid] for eid in chunk))
            ts = [times[eid] for eid in chunk]
            self._write_atomic(
                pdir / f"seg_{seg_n:06d}.meta.json",
                json.dumps({
                    "min_ts": min(ts),
                    "max_ts": max(ts),
                    "supersedes": [],
                    "opaque": False,
                }).encode(),
            )
            chunk, size = [], 0

        for eid, line in lines.items():
            chunk.append(eid)
            size += len(line)
            if size >= self._c.segment_bytes:
                seal_chunk()
        self._write_atomic(
            active, b"".join(lines[eid] for eid in chunk)
        )
        # the rewritten active's columnar blocks (if any) describe the
        # pre-compaction bytes; the fresh (mtime_ns, size) could never
        # serve them stale, so dropping just reclaims the disk now
        columnar_cache.drop(active)
        # every live record is now in a fsync'ed file (segments + active
        # via _write_atomic): release any group-commit waiters
        self._c.committers.get(active).mark_all_durable()
        return len(table)

    def compact(self, app_id: int, channel_id: int | None = None) -> int:
        """Rewrite every partition to its live records; returns the live
        count."""
        ns = self._ns_dir(app_id, channel_id)
        if not ns.exists():
            return 0
        n = self._n_partitions(ns)
        total = 0
        for pp in range(n):
            pdir = self._pdir(ns, pp)
            with self._locked(pdir):
                total += self._compact_partition_locked(pdir)
        with self._c.lock:
            self._c.clean_stat.pop(ns, None)
        return total

    def export_jsonl(self, app_id: int, channel_id: int | None, out) -> int:
        """Export splice-through (see jsonl.export_jsonl): each partition
        streams its segments+active verbatim once proven replay-clean
        (compacted otherwise). Partition order is the export order —
        arbitrary, like the reference's RDD part files.

        Proven and streamed ONE PARTITION AT A TIME: each partition's
        lock is held only for its own read/prove/compact, and its buffer
        is written to ``out`` before the next partition is touched — so
        a multi-GB namespace stalls concurrent ingest on at most one
        partition at a time and peak RSS is one partition, not the
        store (the per-partition proofs are each sound on their own:
        ids route to exactly one partition, so replay-cleanliness is a
        per-partition property). Returns the record count."""
        ns = self._ns_dir(app_id, channel_id)
        if not ns.exists():
            return 0
        n = self._n_partitions(ns)
        total = 0
        for pp in range(n):
            buf = self._proven_clean_partition(ns, pp)
            if buf:
                out.write(buf)
                total += buf.count(b"\n")
        return total

    def _proven_clean_partition(self, ns: Path, pp: int) -> bytes:
        """One partition's buffer proven replay-clean and blank-line
        free (compacted under that partition's lock when the proof
        fails or is unavailable). Unlike ``_proven_clean_buffers_locked``
        this takes only the single partition lock and leaves the
        namespace-level clean_stat cache alone (the next scan_ratings
        re-proves from its own snapshot)."""
        from predictionio_tpu import native
        from predictionio_tpu.data.storage.jsonl import _maybe_blank_lines

        pdir = self._pdir(ns, pp)
        with self._locked(pdir):
            buf, _ = self._read_partition_locked(pdir)
            if not buf:
                return b""
            needs, _scan = (
                prove_clean(buf)
                if native.native_available()
                else (True, None)  # unprovable: compact
            )
            if not needs:
                needs = _maybe_blank_lines(buf)
            if needs:
                self._compact_partition_locked(pdir)
                buf, _ = self._read_partition_locked(pdir)
        return buf

    @staticmethod
    def _read_partition_locked(pdir: Path) -> tuple[bytes, list]:
        """Concatenated newline-normalized segment+active bytes plus the
        per-file pieces ``(path, mtime_ns, size, start, end)`` — stat for
        clean_stat / columnar-cache keys, [start, end) the file's span in
        the returned buffer; caller holds the partition lock. The
        replay-order invariant (segments sorted, active last) lives
        ONLY here — scan_ratings and export both read through it."""
        parts: list[bytes] = []
        stats: list = []
        pos = 0
        files = list(PartitionedEvents._segments(pdir))
        active = pdir / "active.jsonl"
        if active.exists():
            files.append(active)
        for path in files:
            b = path.read_bytes()
            if b and not b.endswith(b"\n"):
                b += b"\n"
            st = path.stat()
            stats.append(
                (str(path), st.st_mtime_ns, st.st_size, pos, pos + len(b))
            )
            pos += len(b)
            parts.append(b)
        return b"".join(parts), stats

    def _proven_clean_buffers_locked(
        self, ns: Path, n: int, forbid_blank_lines: bool = False
    ) -> tuple[list[bytes], list]:
        """Per-partition buffers proven replay-clean (dirty partitions
        compacted first), with the proof recorded in the clean_stat
        cache. Caller holds EVERY partition lock (_locked_all) for the
        whole prove -> compact -> re-read sequence: a writer cannot slip
        a duplicate id or delete marker between the compaction and the
        snapshot the cache trusts — which also makes trusting the
        post-compact state sound in degraded no-native mode, where
        uniqueness is unprovable but compaction just restored it by
        construction.

        ``forbid_blank_lines``: additionally compact partitions whose
        buffers may contain empty/whitespace lines (the clean proof
        tolerates them; a verbatim export must not, or its record count
        and output would include non-records). Returns (pbufs, scans,
        pieces) where scans[pp] is a reusable span scan or None and
        pieces[pp] lists the partition's per-file
        ``(path, mtime_ns, size, start, end)`` spans — the keys the
        columnar cache is addressed by."""
        from predictionio_tpu import native
        from predictionio_tpu.data.storage.jsonl import _maybe_blank_lines

        def read_all() -> tuple[list[bytes], list[list], tuple]:
            pbufs: list[bytes] = []
            pieces: list[list] = []
            stats: list = []
            for pp in range(n):
                buf, st = self._read_partition_locked(self._pdir(ns, pp))
                pbufs.append(buf)
                pieces.append(st)
                stats.extend(st)
            return pbufs, pieces, tuple(stats)

        pbufs, pieces, stat_key = read_all()
        scans: list = [None] * n
        if not any(pbufs):
            return pbufs, scans, pieces
        dirty_blanks = forbid_blank_lines and any(
            _maybe_blank_lines(b) for b in pbufs if b
        )
        if self._c.clean_stat.get(ns) != stat_key or dirty_blanks:
            compacted = False
            for pp in range(n):
                if not pbufs[pp]:
                    continue
                if not native.native_available():
                    needs, scans[pp] = True, None  # unprovable: compact
                elif len(pbufs[pp]) > SCAN_CHUNK_BYTES:
                    # big partitions prove in O(chunk) memory; the span
                    # scan is not retained (scan_ratings re-extracts
                    # through the chunked path)
                    needs, scans[pp] = prove_clean_chunked(pbufs[pp])
                else:
                    needs, scans[pp] = prove_clean(pbufs[pp])
                if forbid_blank_lines and not needs:
                    needs = _maybe_blank_lines(pbufs[pp])
                if needs:
                    self._compact_partition_locked(self._pdir(ns, pp))
                    compacted = True
            if compacted:
                pbufs, pieces, stat_key = read_all()
                scans = [None] * n
        with self._c.lock:
            self._c.clean_stat[ns] = stat_key
        return pbufs, scans, pieces

    # -- columnar bulk read ------------------------------------------------

    def scan_ratings(
        self,
        app_id: int,
        channel_id: int | None = None,
        *,
        event_names=None,
        entity_type: str | None = None,
        target_entity_type: str | None = None,
        rating_key: str | None = "rating",
        default_ratings: dict[str, float] | None = None,
        override_ratings: dict[str, float] | None = None,
    ) -> base.RatingsBatch:
        """Columnar fast path: scan every partition's log IN PARALLEL
        with the native codec (the ctypes call releases the GIL, so
        partitions parse on real threads — the TableInputFormat-split
        analog), then merge the per-partition dense id spaces.

        Soundness: each partition is proven replay-clean (unique ids, no
        delete markers; dirty partitions are compacted first, under every
        partition lock so no writer can race the proof), and ids route
        deterministically to exactly one partition — enforced at the
        write sites via the ``_meta.json`` guard — so the per-partition
        record sets are disjoint and the merge is a plain
        concatenation-with-remap, no cross-partition last-write-wins
        needed."""
        from predictionio_tpu import native

        ns = self._ns_dir(app_id, channel_id)
        if not ns.exists():
            return base.RatingsBatch.empty()
        n = self._n_partitions(ns)

        use_cache = columnar_cache.enabled(self._c.config)
        with self._locked_all(ns, n):
            pbufs, scans, pieces = self._proven_clean_buffers_locked(ns, n)
        if not any(pbufs):
            return base.RatingsBatch.empty()
        # buffers are immutable snapshots: parse outside the locks
        live = [pp for pp in range(n) if pbufs[pp]]

        filters = dict(
            event_names=(
                list(event_names) if event_names is not None else None
            ),
            rating_key=rating_key,
            default_ratings=default_ratings,
            entity_type=entity_type,
            target_entity_type=target_entity_type,
            override_ratings=override_ratings,
        )

        def load_one(pp: int, n_threads: int = 0):
            buf = pbufs[pp]
            try:
                if use_cache:
                    return load_one_cached(pp, buf, n_threads)
                if scans[pp] is None and len(buf) > SCAN_CHUNK_BYTES:
                    # big partition: extract through line-aligned chunks
                    # so the span arrays are O(chunk), not O(partition)
                    # — with all partitions parsing in PARALLEL,
                    # whole-buffer spans multiplied to ~9 GB at the 20M
                    # north-star scale (measured round 5)
                    dirty, result = _chunked_clean_extract(buf, filters)
                    if not dirty:
                        return result
                    # freshly-compacted data flagged dirty can only be
                    # a hash collision: fall through to the exact path
                return native.load_ratings_jsonl(
                    buf, scanned=scans[pp], n_threads=n_threads, **filters
                )
            finally:
                # the snapshot is parsed; release it before the other
                # partitions finish (bounds peak RSS to live buffers)
                pbufs[pp] = None

        def load_one_cached(pp: int, buf: bytes, n_threads: int):
            """Per-FILE columnar cache: segments are immutable, so a
            sealed segment's blocks survive appends to active and only a
            compaction (which rewrites the files) invalidates them. The
            partition was just proven replay-clean as a whole, so each
            file's records are a plain unique set and merging the
            per-file results in replay order (segments sorted, active
            last) reproduces the whole-buffer scan's first-appearance
            dense id order exactly."""
            merge_p = native.DenseMerge()
            for (fpath, mtime_ns, size, s, e) in pieces[pp]:
                if s == e:
                    continue
                piece_stat = (mtime_ns, size)
                cpath = columnar_cache.cache_path(Path(fpath))
                res = None
                cb = columnar_cache.load(cpath)
                if cb is not None and cb.valid_for(piece_stat):
                    try:
                        res = cb.ratings(**filters)
                    except Exception:  # corrupt payload: row scan below
                        res = None
                if res is None:
                    piece = buf[s:e]
                    if len(piece) > SCAN_CHUNK_BYTES:
                        res = native.load_ratings_jsonl_chunked(
                            piece, chunk_bytes=SCAN_CHUNK_BYTES,
                            n_threads=n_threads, **filters
                        )
                    else:
                        res = native.load_ratings_jsonl(
                            piece, n_threads=n_threads, **filters
                        )
                    try:
                        blocks = columnar_cache.build_blocks(
                            piece, rating_key, chunk_bytes=SCAN_CHUNK_BYTES
                        )
                        if blocks is not None:
                            columnar_cache.store(cpath, piece_stat, blocks)
                    except Exception:  # pragma: no cover - cache optional
                        pass
                merge_p.add(*res)
            return merge_p.result()

        if len(live) == 1:
            results = [load_one(live[0])]
        else:
            # one native-scanner thread per pooled worker: the scanner is
            # itself multithreaded for big buffers, and cores x 8 threads
            # would thrash the parallelism this pool provides (passed as
            # an explicit argument — mutating the process environment from
            # here would race getenv in concurrent native scans, which is
            # undefined behavior in glibc)
            with ThreadPoolExecutor(
                max_workers=min(len(live), os.cpu_count() or 4)
            ) as pool:
                results = list(
                    pool.map(lambda pp: load_one(pp, n_threads=1), live)
                )

        merge = native.DenseMerge()
        for result in results:
            merge.add(*result)
        users, items, rows, cols, vals = merge.result()
        return base.RatingsBatch(
            entity_ids=users,
            target_ids=items,
            rows=rows,
            cols=cols,
            vals=vals,
        )

