"""Length-prefixed binary framing for wire-speed batch ingest.

``POST /batch/events.bin`` carries a stream of independent FRAMES, each
a columnar group of events, so the event server can commit group by
group as the bytes arrive instead of materializing one giant JSON body:

    body  := magic(4, b"PIF1") frame*
    frame := u32le payload_len | payload
    payload := u32le n_events | strcol * 10

A ``strcol`` is one string column over all n events — ONE length header
and ONE blob per column instead of one JSON key/value pair per event
(the per-event dict churn the JSON batch path pays):

    strcol := u32le blob_len | u32le * n cumulative end offsets | blob

Column order (empty string = absent):

    0 event            required
    1 entityType       required
    2 entityId         required
    3 targetEntityType
    4 targetEntityId
    5 eventTime        ISO-8601; empty -> server receive stamp
    6 eventId          empty -> server-generated hex id
    7 creationTime     ISO-8601; empty -> server receive stamp
    8 properties       JSON object bytes; empty -> {}
    9 extras           wire.py typed-codec JSON for the rare per-event
                       fields (tags, prId); empty -> none

The server decodes a frame straight into the columnar layout
``batch_insert``/group-commit already wants: :meth:`FrameBatch.render_jsonl`
emits storage-format JSONL byte-identical to
``json.dumps(Event.to_dict(for_api=False))`` for the jsonl/partitioned
splice-through path (one lock+append+fsync per frame), and
:meth:`FrameBatch.to_events` builds Event objects for every other
backend. Validation mirrors ``data/event.validate`` exactly; a frame is
all-or-nothing (validate everything, then commit once), and a torn or
oversized frame raises :class:`FrameError` before any byte reaches
storage. ``faults.fault_point("http.frame")`` fires per frame read so
the chaos matrix can tear or kill mid-stream.
"""

from __future__ import annotations

import binascii
import json
import os
import re
import struct
from json.encoder import encode_basestring_ascii as _esc
from typing import Any, Iterable, Mapping, Sequence

import numpy as np

from predictionio_tpu import faults
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import (
    BUILTIN_ENTITY_TYPES,
    SPECIAL_EVENTS,
    Event,
    EventValidationError,
    format_time,
    parse_time,
)
from predictionio_tpu.data.storage import wire

MAGIC = b"PIF1"
N_COLUMNS = 10
(
    COL_EVENT,
    COL_ENTITY_TYPE,
    COL_ENTITY_ID,
    COL_TARGET_ENTITY_TYPE,
    COL_TARGET_ENTITY_ID,
    COL_EVENT_TIME,
    COL_EVENT_ID,
    COL_CREATION_TIME,
    COL_PROPERTIES,
    COL_EXTRAS,
) = range(N_COLUMNS)

_U32 = struct.Struct("<I")

# a storage-canonical timestamp (format_time(dt, "us")) is embedded
# verbatim after a validity parse; anything else is re-rendered
_CANON_TIME = re.compile(
    r"\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{6}"
    r"(?:Z|[+-]\d{2}:\d{2}(?::\d{2})?)"
)


def max_frame_bytes() -> int:
    """Per-frame payload cap (``PIO_FRAME_MAX_MB``, default 32)."""
    try:
        mb = float(os.environ.get("PIO_FRAME_MAX_MB", "32") or 32)
    except ValueError:
        mb = 32.0
    return max(1, int(mb * (1 << 20)))


class FrameError(ValueError):
    """Malformed framing — the whole request is rejected atomically
    (already-committed earlier frames stay committed; the erroring frame
    never reaches storage). ``code`` is the stable machine-readable name
    surfaced in the HTTP error body."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


class FrameEventError(FrameError):
    """An event inside an otherwise well-formed frame failed validation;
    ``index`` is its position within the frame."""

    def __init__(self, index: int, message: str):
        super().__init__("InvalidEvent", f"event[{index}]: {message}")
        self.index = index


# -- client side -------------------------------------------------------------


def encode_frame(events: Sequence[Mapping[str, Any]]) -> bytes:
    """One length-prefixed frame from API-shaped event dicts (the same
    JSON objects ``POST /batch/events.json`` takes). Timestamps are
    canonicalized client-side so the server's embed-verbatim fast path
    hits; tags/prId ride the wire.py typed codec in the extras column."""
    n = len(events)
    cols: list[list[bytes]] = [[] for _ in range(N_COLUMNS)]
    for d in events:
        cols[COL_EVENT].append(str(d.get("event", "")).encode())
        cols[COL_ENTITY_TYPE].append(str(d.get("entityType", "")).encode())
        cols[COL_ENTITY_ID].append(str(d.get("entityId", "")).encode())
        cols[COL_TARGET_ENTITY_TYPE].append(
            str(d.get("targetEntityType") or "").encode()
        )
        cols[COL_TARGET_ENTITY_ID].append(
            str(d.get("targetEntityId") or "").encode()
        )
        for col, key in (
            (COL_EVENT_TIME, "eventTime"),
            (COL_CREATION_TIME, "creationTime"),
        ):
            t = d.get(key)
            cols[col].append(
                format_time(parse_time(t), "us").encode() if t else b""
            )
        cols[COL_EVENT_ID].append(str(d.get("eventId") or "").encode())
        props = d.get("properties")
        if isinstance(props, DataMap):
            props = props.to_dict()
        cols[COL_PROPERTIES].append(
            json.dumps(props, separators=(",", ":")).encode()
            if props
            else b""
        )
        extras = {
            k: d[k] for k in ("tags", "prId") if d.get(k)
        }
        cols[COL_EXTRAS].append(wire.dumps(extras) if extras else b"")
    parts = [_U32.pack(n)]
    for items in cols:
        blob = b"".join(items)
        ends = np.cumsum(
            np.fromiter((len(b) for b in items), np.uint32, count=n),
            dtype=np.uint32,
        )
        parts.append(_U32.pack(len(blob)))
        parts.append(ends.astype("<u4").tobytes())
        parts.append(blob)
    payload = b"".join(parts)
    return _U32.pack(len(payload)) + payload


def encode_body(
    events: Sequence[Mapping[str, Any]], frame_events: int = 2000
) -> bytes:
    """A full request body: magic + one frame per ``frame_events`` chunk
    (each frame is one group commit on the server)."""
    parts = [MAGIC]
    for lo in range(0, len(events), frame_events):
        parts.append(encode_frame(events[lo : lo + frame_events]))
    return b"".join(parts)


# -- server side -------------------------------------------------------------


def _read_exact(stream, n: int, what: str) -> bytes:
    data = stream.read(n)
    while len(data) < n:
        more = stream.read(n - len(data))
        if not more:
            raise FrameError(
                "TornFrame",
                f"body ended mid-{what} ({len(data)}/{n} bytes)",
            )
        data += more
    return data


def read_frames(stream, limit: int | None = None) -> Iterable[bytes]:
    """Yield frame payloads incrementally off a request body stream
    (anything with ``read(n)`` and optionally ``remaining``). Raises
    :class:`FrameError` on bad magic, an oversized length header, or a
    frame torn by the body ending early."""
    limit = max_frame_bytes() if limit is None else limit
    magic = _read_exact(stream, len(MAGIC), "magic")
    if magic != MAGIC:
        raise FrameError("BadMagic", f"expected {MAGIC!r}, got {magic!r}")
    while getattr(stream, "remaining", 1) > 0:
        faults.fault_point("http.frame")
        hdr = stream.read(4)
        if not hdr:
            return  # clean end between frames (no remaining attr)
        if len(hdr) < 4:
            raise FrameError("TornFrame", "body ended mid-frame header")
        (size,) = _U32.unpack(hdr)
        if size < 4:
            raise FrameError("BadFrame", f"frame payload of {size} bytes")
        if size > limit:
            raise FrameError(
                "FrameTooLarge",
                f"frame of {size} bytes exceeds the {limit}-byte cap "
                "(PIO_FRAME_MAX_MB)",
            )
        remaining = getattr(stream, "remaining", None)
        if remaining is not None and size > remaining:
            raise FrameError(
                "TornFrame",
                f"frame declares {size} bytes but only {remaining} remain",
            )
        yield _read_exact(stream, size, "frame payload")


def decode_frame(payload: bytes) -> "FrameBatch":
    """Payload bytes -> :class:`FrameBatch`. Structural validation only
    (offsets in bounds, monotone, no trailing junk); event-level rules
    run in render_jsonl/to_events."""
    total = len(payload)
    if total < 4:
        raise FrameError("BadFrame", "frame shorter than its event count")
    (n,) = _U32.unpack_from(payload, 0)
    # 10 columns, each at least a 4-byte blob_len + 4n of offsets
    if n > (total - 4) // max(1, N_COLUMNS * 4):
        raise FrameError("BadFrame", f"event count {n} exceeds payload size")
    pos = 4
    cols: list[tuple[bytes, list[int]]] = []
    for _ in range(N_COLUMNS):
        if pos + 4 + 4 * n > total:
            raise FrameError("BadFrame", "truncated column header")
        (blob_len,) = _U32.unpack_from(payload, pos)
        pos += 4
        ends = np.frombuffer(payload, "<u4", count=n, offset=pos)
        pos += 4 * n
        if pos + blob_len > total:
            raise FrameError("BadFrame", "column blob overruns the frame")
        if n and (
            int(ends[-1]) != blob_len
            or bool((ends[1:] < ends[:-1]).any())
        ):
            raise FrameError("BadFrame", "non-monotone column offsets")
        cols.append((payload[pos : pos + blob_len], ends.tolist()))
        pos += blob_len
    if pos != total:
        raise FrameError("BadFrame", f"{total - pos} trailing bytes")
    return FrameBatch(n, cols)


def _split_bytes(blob: bytes, ends: list[int]) -> list[bytes]:
    out = []
    s = 0
    for e in ends:
        out.append(blob[s:e])
        s = e
    return out


def _split_str(blob: bytes, ends: list[int], col: int) -> list[str]:
    try:
        if blob.isascii():
            # byte offsets == char offsets: one decode, str slices
            text = blob.decode("ascii")
            out = []
            s = 0
            for e in ends:
                out.append(text[s:e])
                s = e
            return out
        return [b.decode("utf-8") for b in _split_bytes(blob, ends)]
    except UnicodeDecodeError as e:
        raise FrameError("BadFrame", f"column {col} is not UTF-8: {e}") from e


def _gen_ids(count: int) -> list[str]:
    """Bulk random 32-hex event ids (one urandom call, not per-uuid)."""
    if not count:
        return []
    pool = binascii.hexlify(os.urandom(16 * count)).decode("ascii")
    return [pool[i : i + 32] for i in range(0, 32 * count, 32)]


def _canon_time(s: str, i: int) -> str:
    """Validate an ISO-8601 timestamp and return its storage-canonical
    form (``format_time(..., "us")``). Already-canonical strings (the
    cooperating-client fast path) embed verbatim after a validity
    parse — a regex match alone would store impossible dates that break
    replay."""
    try:
        if _CANON_TIME.fullmatch(s):
            if s[-1] == "Z":
                parse_time(s)
                return s
            dt = parse_time(s)
            # a numeric zero offset renders as Z canonically
            return s if dt.utcoffset() else format_time(dt, "us")
        return format_time(parse_time(s), "us")
    except EventValidationError as e:
        raise FrameEventError(i, str(e)) from e


def _is_reserved(name: str) -> bool:
    return name[0] == "$" or name.startswith("pio_")


class FrameBatch:
    """One decoded frame: event count + the ten raw columns. The two
    exits — :meth:`render_jsonl` (splice backends) and :meth:`to_events`
    (everything else) — share the validation rules of
    ``data/event.validate`` and are all-or-nothing: any invalid event
    rejects the whole frame before a byte reaches storage."""

    __slots__ = ("n", "_cols")

    def __init__(self, n: int, cols: list[tuple[bytes, list[int]]]):
        self.n = n
        self._cols = cols

    def column_bytes(self, col: int) -> list[bytes]:
        return _split_bytes(*self._cols[col])

    def column_str(self, col: int) -> list[str]:
        blob, ends = self._cols[col]
        return _split_str(blob, ends, col)

    # -- shared validation pieces ------------------------------------------

    def _check_combo(
        self, i: int, ev: str, et: str, tet: str,
        allowed: frozenset | None,
    ) -> None:
        """The name checks that depend only on (event, entityType,
        targetEntityType) — memoizable per distinct combo."""
        if not ev:
            raise FrameEventError(i, "event must not be empty.")
        if allowed is not None and ev not in allowed:
            raise FrameEventError(
                i, f"event {ev} is not allowed by this access key"
            )
        if _is_reserved(ev) and ev not in SPECIAL_EVENTS:
            raise FrameEventError(
                i, f"{ev} is not a supported reserved event name."
            )
        if not et:
            raise FrameEventError(i, "entityType must not be empty string.")
        if _is_reserved(et) and et not in BUILTIN_ENTITY_TYPES:
            raise FrameEventError(
                i,
                f"The entityType {et} is not allowed. "
                "'pio_' is a reserved name prefix.",
            )
        if tet:
            if ev in SPECIAL_EVENTS:
                raise FrameEventError(
                    i, f"Reserved event {ev} cannot have targetEntity"
                )
            if _is_reserved(tet) and tet not in BUILTIN_ENTITY_TYPES:
                raise FrameEventError(
                    i,
                    f"The targetEntityType {tet} is not allowed. "
                    "'pio_' is a reserved name prefix.",
                )

    def _check_names(
        self, i: int, ev: str, et: str, eid: str, tet: str, tid: str,
        allowed: frozenset | None,
    ) -> None:
        self._check_combo(i, ev, et, tet, allowed)
        if not eid:
            raise FrameEventError(i, "entityId must not be empty string.")
        if bool(tet) != bool(tid):
            raise FrameEventError(
                i,
                "targetEntityType and targetEntityId must be "
                "specified together.",
            )

    def _props(self, i: int, raw: bytes) -> tuple[dict | None, str]:
        """properties column bytes -> (parsed dict or None, canonical
        JSON text). Always re-rendered through json.dumps: that both
        validates the client bytes ARE a JSON object and canonicalizes
        the rendering to the byte layout ``batch_insert`` produces."""
        if not raw or raw == b"{}":
            return None, "{}"
        try:
            # decode before loads: bytes input pays a per-call encoding
            # sniff inside the json module (UnicodeDecodeError is a
            # ValueError, so a bad encoding lands in the same except)
            obj = json.loads(raw.decode("utf-8"))
        except ValueError as e:
            raise FrameEventError(i, f"properties must be valid JSON: {e}")
        if not isinstance(obj, dict):
            raise FrameEventError(i, "properties must be a JSON object")
        for k in obj:
            if _is_reserved(k):
                raise FrameEventError(
                    i,
                    f"The property {k} is not allowed. "
                    "'pio_' is a reserved name prefix.",
                )
        return obj, json.dumps(obj)

    def _extras(self, i: int, raw: bytes) -> tuple[tuple, str | None]:
        try:
            x = wire.loads(raw)
            if not isinstance(x, dict):
                raise ValueError("extras must decode to an object")
            tags = tuple(x.get("tags") or ())
            pr_id = x.get("prId")
            if pr_id is not None and not isinstance(pr_id, str):
                raise ValueError("prId must be a string")
            if not all(isinstance(t, str) for t in tags):
                raise ValueError("tags must be strings")
        except (ValueError, TypeError) as e:
            raise FrameEventError(i, f"bad extras column: {e}")
        return tags, pr_id

    # -- exits --------------------------------------------------------------

    def render_jsonl(
        self,
        allowed_events: frozenset | None,
        stamp_iso: str,
    ) -> tuple[bytes, list[str], list[str]]:
        """Validate every event and render the storage-format JSONL blob
        for ``append_jsonl`` splice-through (byte-identical to what
        ``batch_insert`` would store). Returns (blob, event_ids,
        event_names); raises :class:`FrameEventError` on the first
        invalid event — nothing is returned for a partially-valid frame.
        ``stamp_iso`` fills missing eventTime/creationTime (one receive
        stamp per request, already storage-canonical)."""
        ev_l = self.column_str(COL_EVENT)
        et_l = self.column_str(COL_ENTITY_TYPE)
        eid_l = self.column_str(COL_ENTITY_ID)
        tet_l = self.column_str(COL_TARGET_ENTITY_TYPE)
        tid_l = self.column_str(COL_TARGET_ENTITY_ID)
        t_l = self.column_str(COL_EVENT_TIME)
        xid_l = self.column_str(COL_EVENT_ID)
        ct_l = self.column_str(COL_CREATION_TIME)
        props_l = self.column_bytes(COL_PROPERTIES)
        extras_l = self.column_bytes(COL_EXTRAS)
        fresh = iter(_gen_ids(sum(1 for x in xid_l if not x)))
        lines: list[str] = []
        ids: list[str] = []
        # per-frame memo caches: real batches repeat event names, entity
        # types, timestamps, and property shapes heavily, so each
        # DISTINCT value is validated/canonicalized once — this is what
        # holds the splice path at wire speed (a per-event json.loads +
        # parse_time would triple the cost of this loop)
        combo_memo: dict = {}
        props_memo: dict = {}
        time_memo: dict = {}
        stamp_esc = _esc(stamp_iso)
        for i in range(self.n):
            ev = ev_l[i]
            et = et_l[i]
            eid = eid_l[i]
            tet = tet_l[i]
            tid = tid_l[i]
            combo = (ev, et, tet)
            frag = combo_memo.get(combo)
            if frag is None:
                self._check_combo(i, ev, et, tet, allowed_events)
                head = (
                    '{"event": ' + _esc(ev)
                    + ', "entityType": ' + _esc(et)
                    + ', "entityId": '
                )
                tfrag = (
                    ', "targetEntityType": ' + _esc(tet)
                    + ', "targetEntityId": '
                ) if tet else None
                frag = combo_memo[combo] = (head, tfrag)
            head, tfrag = frag
            if not eid:
                raise FrameEventError(
                    i, "entityId must not be empty string."
                )
            if bool(tet) != bool(tid):
                raise FrameEventError(
                    i,
                    "targetEntityType and targetEntityId must be "
                    "specified together.",
                )
            raw = props_l[i]
            hit = props_memo.get(raw)
            if hit is None:
                obj, props_json = self._props(i, raw)
                hit = props_memo[raw] = (not obj, props_json)
            empty_props, props_json = hit
            if empty_props and ev == "$unset":
                raise FrameEventError(
                    i, "properties cannot be empty for $unset event"
                )
            t = t_l[i]
            if t:
                te = time_memo.get(t)
                if te is None:
                    te = time_memo[t] = _esc(_canon_time(t, i))
                t = te
            else:
                t = stamp_esc
            ct = ct_l[i]
            if ct:
                cte = time_memo.get(ct)
                if cte is None:
                    cte = time_memo[ct] = _esc(_canon_time(ct, i))
                ct = cte
            else:
                ct = stamp_esc
            xid = xid_l[i]
            if xid:
                xj = _esc(xid)
            else:
                xid = next(fresh)
                xj = '"' + xid + '"'  # generated ids are hex: no escaping
            # key order and ", "/": " separators match
            # json.dumps(Event.to_dict(for_api=False)) exactly — the
            # byte-parity contract with batch_insert's rendering
            # (t/ct/head/tfrag are pre-escaped via the memos above)
            if extras_l[i]:
                parts = [
                    head, _esc(eid),
                    ', "properties": ', props_json,
                    ', "eventTime": ', t,
                    ', "eventId": ', xj,
                ]
                if tfrag is not None:
                    parts += [tfrag, _esc(tid)]
                tags, pr_id = self._extras(i, extras_l[i])
                if tags:
                    parts += [', "tags": ', json.dumps(list(tags))]
                if pr_id is not None:
                    parts += [', "prId": ', _esc(pr_id)]
                parts += [', "creationTime": ', ct, "}"]
                lines.append("".join(parts))
            else:
                tail = tfrag + _esc(tid) if tfrag is not None else ""
                lines.append(
                    head + _esc(eid)
                    + ', "properties": ' + props_json
                    + ', "eventTime": ' + t
                    + ', "eventId": ' + xj
                    + tail
                    + ', "creationTime": ' + ct + "}"
                )
            ids.append(xid)
        blob = ("\n".join(lines) + "\n").encode() if lines else b""
        return blob, ids, ev_l

    def to_events(
        self,
        allowed_events: frozenset | None,
        stamp_iso: str,
    ) -> tuple[list[Event], list[str]]:
        """Validate and build Event objects for backends without an
        ``append_jsonl`` splice path (sqlite, memory, ...). Same rules
        and all-or-nothing semantics as :meth:`render_jsonl`."""
        ev_l = self.column_str(COL_EVENT)
        et_l = self.column_str(COL_ENTITY_TYPE)
        eid_l = self.column_str(COL_ENTITY_ID)
        tet_l = self.column_str(COL_TARGET_ENTITY_TYPE)
        tid_l = self.column_str(COL_TARGET_ENTITY_ID)
        t_l = self.column_str(COL_EVENT_TIME)
        xid_l = self.column_str(COL_EVENT_ID)
        ct_l = self.column_str(COL_CREATION_TIME)
        props_l = self.column_bytes(COL_PROPERTIES)
        extras_l = self.column_bytes(COL_EXTRAS)
        stamp = parse_time(stamp_iso)
        fresh = iter(_gen_ids(sum(1 for x in xid_l if not x)))
        events: list[Event] = []
        ids: list[str] = []
        for i in range(self.n):
            ev = ev_l[i]
            et = et_l[i]
            eid = eid_l[i]
            tet = tet_l[i]
            tid = tid_l[i]
            self._check_names(i, ev, et, eid, tet, tid, allowed_events)
            obj, _ = self._props(i, props_l[i])
            if ev == "$unset" and not obj:
                raise FrameEventError(
                    i, "properties cannot be empty for $unset event"
                )
            tags: tuple = ()
            pr_id = None
            if extras_l[i]:
                tags, pr_id = self._extras(i, extras_l[i])
            try:
                t = parse_time(t_l[i]) if t_l[i] else stamp
                ct = parse_time(ct_l[i]) if ct_l[i] else stamp
            except EventValidationError as e:
                raise FrameEventError(i, str(e)) from e
            xid = xid_l[i] or next(fresh)
            events.append(
                Event(
                    event=ev,
                    entity_type=et,
                    entity_id=eid,
                    target_entity_type=tet or None,
                    target_entity_id=tid or None,
                    properties=DataMap(obj or {}),
                    event_time=t,
                    tags=tags,
                    pr_id=pr_id,
                    creation_time=ct,
                    event_id=xid,
                )
            )
            ids.append(xid)
        return events, ids
