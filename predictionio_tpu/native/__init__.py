"""ctypes bindings for the native event codec (native/pio_native.cpp).

The reference delegates bulk event IO and id indexing to Spark executors
(JDBCPEvents reads, FileToEvents/EventsToFile, BiMap.stringInt —
data/.../storage/BiMap.scala:96-110); here those host-side hot loops run
in a small C++ library. Public API:

- :func:`scan_events` — columnar field spans for a JSONL event buffer,
- :func:`index_spans` — dense string-id indexing over spans (BiMap build),
- :func:`parse_times` / :func:`extract_number` — vectorized field decode,
- :func:`load_ratings_jsonl` — one-call file -> (user_ids, item_ids,
  rows, cols, ratings) training-array loader,
- :func:`parse_events_jsonl` — JSONL -> list[Event] with the native
  scanner for well-formed lines and the Python json fallback otherwise.

Everything degrades to pure Python when the shared library can't be
built (``native_available()`` reports which path is active); the library
auto-compiles from source on first use when a C++ toolchain is present.
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
import subprocess
import threading
from typing import Iterable, Sequence

import numpy as np

logger = logging.getLogger(__name__)

# field slots — keep in sync with PioField in native/pio_native.cpp
F_EVENT = 0
F_ENTITY_TYPE = 1
F_ENTITY_ID = 2
F_TARGET_ENTITY_TYPE = 3
F_TARGET_ENTITY_ID = 4
F_PROPERTIES = 5
F_EVENT_TIME = 6
F_PR_ID = 7
F_EVENT_ID = 8
F_TAGS = 9
F_CREATION_TIME = 10
N_FIELDS = 11

FLAG_FALLBACK = 1
FLAG_EMPTY = 2

_REPO_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_tried = False


def _build(src: str, out: str) -> bool:
    try:
        proc = subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
             "-o", out, src],
            capture_output=True,
            text=True,
            timeout=120,
        )
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.info("native codec build unavailable: %s", e)
        return False
    if proc.returncode != 0:
        logger.warning("native codec build failed:\n%s", proc.stderr)
        return False
    return True


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_tried
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        src = os.path.join(_REPO_NATIVE_DIR, "pio_native.cpp")
        so = os.path.join(_REPO_NATIVE_DIR, "libpio_native.so")
        try:
            stale = not os.path.exists(so) or (
                os.path.exists(src)
                and os.path.getmtime(src) > os.path.getmtime(so)
            )
            if stale:
                if not os.path.exists(src) or not _build(src, so):
                    return None
            lib = ctypes.CDLL(so)
        except OSError as e:
            logger.info("native codec not loaded: %s", e)
            return None

        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        lib.pio_scan_events.restype = ctypes.c_long
        lib.pio_scan_events.argtypes = [
            ctypes.c_char_p, ctypes.c_long, i64p, i64p, u8p, ctypes.c_long,
            ctypes.c_long,
        ]
        lib.pio_index_spans.restype = ctypes.c_long
        lib.pio_index_spans.argtypes = [
            ctypes.c_char_p, i64p, i64p, ctypes.c_long, i32p, i64p,
        ]
        lib.pio_parse_times.restype = None
        lib.pio_parse_times.argtypes = [
            ctypes.c_char_p, i64p, i64p, ctypes.c_long, f64p,
        ]
        lib.pio_extract_number.restype = None
        lib.pio_extract_number.argtypes = [
            ctypes.c_char_p, i64p, i64p, ctypes.c_long, ctypes.c_char_p, f64p,
        ]
        lib.pio_route_ids.restype = None
        lib.pio_route_ids.argtypes = [
            ctypes.c_char_p, i64p, i64p, ctypes.c_long, ctypes.c_int32, i32p,
        ]
        lib.pio_splice_lines.restype = ctypes.c_long
        lib.pio_splice_lines.argtypes = [
            ctypes.c_char_p, i64p, i64p, ctypes.c_long, u8p, u8p,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_long, u8p,
        ]
        u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
        lib.pio_hash64_spans.restype = None
        lib.pio_hash64_spans.argtypes = [
            ctypes.c_char_p, i64p, i64p, ctypes.c_long, u64p,
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


class ScannedEvents:
    """Columnar view of one scanned JSONL buffer: (offset, length) spans
    per line per field, plus per-line flags."""

    def __init__(self, buf: bytes, offs: np.ndarray, lens: np.ndarray,
                 flags: np.ndarray):
        self.buf = buf
        self.offs = offs  # [n, N_FIELDS] int64, -1 = absent
        self.lens = lens  # [n, N_FIELDS] int64
        self.flags = flags  # [n] uint8

    def __len__(self) -> int:
        return len(self.flags)

    def field_bytes(self, line: int, field: int) -> bytes | None:
        off = int(self.offs[line, field])
        if off < 0:
            return None
        return self.buf[off : off + int(self.lens[line, field])]

    def field_str(self, line: int, field: int) -> str | None:
        b = self.field_bytes(line, field)
        return None if b is None else b.decode("utf-8")


def scan_events(buf: bytes, n_threads: int = 0) -> ScannedEvents:
    """Scan a newline-delimited JSON event buffer into field spans.
    Lines needing the full json parser carry FLAG_FALLBACK.
    ``n_threads`` > 0 pins the native scanner's thread count (callers
    that already parallelize across buffers pass 1); 0 = auto."""
    n_lines = buf.count(b"\n") + (0 if buf.endswith(b"\n") or not buf else 1)
    n_lines = max(n_lines, 1)
    offs = np.empty((n_lines, N_FIELDS), dtype=np.int64)
    lens = np.empty((n_lines, N_FIELDS), dtype=np.int64)
    flags = np.empty(n_lines, dtype=np.uint8)
    lib = _load()
    if lib is not None:
        n = lib.pio_scan_events(
            buf, len(buf), offs.reshape(-1), lens.reshape(-1), flags,
            n_lines, n_threads,
        )
        if n >= 0:
            return ScannedEvents(buf, offs[:n], lens[:n], flags[:n])
    # pure-Python fallback: flag every non-empty line for the json path
    lines = buf.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    n = len(lines)
    offs = np.full((n, N_FIELDS), -1, dtype=np.int64)
    lens = np.zeros((n, N_FIELDS), dtype=np.int64)
    flags = np.full(n, FLAG_FALLBACK, dtype=np.uint8)
    for i, line in enumerate(lines):
        if not line.strip():
            flags[i] = FLAG_EMPTY
    return ScannedEvents(buf, offs, lens, flags)


def index_spans(
    buf: bytes, offs: np.ndarray, lens: np.ndarray
) -> tuple[np.ndarray, list[str]]:
    """Dense-index string spans (BiMap.stringInt analog). Returns
    (idx int32 [n] with -1 for absent spans, unique id strings in dense
    order)."""
    n = len(offs)
    offs = np.ascontiguousarray(offs, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    idx = np.empty(n, dtype=np.int32)
    uniq_repr = np.empty(n, dtype=np.int64)
    lib = _load()
    if lib is not None:
        n_uniq = lib.pio_index_spans(buf, offs, lens, n, idx, uniq_repr)
        ids = [
            buf[offs[r] : offs[r] + lens[r]].decode("utf-8")
            for r in uniq_repr[:n_uniq]
        ]
        return idx, ids
    mapping: dict[bytes, int] = {}
    ids = []
    for i in range(n):
        if offs[i] < 0:
            idx[i] = -1
            continue
        key = buf[offs[i] : offs[i] + lens[i]]
        j = mapping.get(key)
        if j is None:
            j = len(mapping)
            mapping[key] = j
            ids.append(key.decode("utf-8"))
        idx[i] = j
    return idx, ids


def parse_times(buf: bytes, offs: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """ISO-8601 spans -> epoch seconds (NaN when absent/unparseable)."""
    n = len(offs)
    offs = np.ascontiguousarray(offs, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    out = np.empty(n, dtype=np.float64)
    lib = _load()
    if lib is not None:
        lib.pio_parse_times(buf, offs, lens, n, out)
        return out
    from predictionio_tpu.data.event import parse_time

    for i in range(n):
        if offs[i] < 0:
            out[i] = np.nan
            continue
        try:
            out[i] = parse_time(
                buf[offs[i] : offs[i] + lens[i]].decode("utf-8")
            ).timestamp()
        except Exception:
            out[i] = np.nan
    return out


def fnv1a32(data: bytes) -> int:
    """FNV-1a 32-bit — the partition-routing hash (kept in lockstep with
    pio_route_ids in pio_native.cpp)."""
    h = 2166136261
    for b in data:
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


def route_id_bytes(s: bytes, n_partitions: int) -> int:
    """Partition of one event id: '<2 lowercase hex>-...' with value <
    n_partitions routes by the embedded partition, else FNV-1a 32 mod
    n_partitions (same rule as pio_route_ids)."""
    hexdigits = b"0123456789abcdef"
    if (
        len(s) >= 3
        and s[2:3] == b"-"
        and s[0] in hexdigits
        and s[1] in hexdigits
    ):
        pp = int(s[:2], 16)
        if pp < n_partitions:
            return pp
    return fnv1a32(s) % n_partitions


def route_ids(
    buf: bytes, offs: np.ndarray, lens: np.ndarray, n_partitions: int
) -> np.ndarray:
    """Vectorized partition routing of event-id spans; -1 for absent
    spans. The bulk-import hot loop (one native pass per blob)."""
    n = len(offs)
    offs = np.ascontiguousarray(offs, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    out = np.empty(n, dtype=np.int32)
    lib = _load()
    if lib is not None:
        lib.pio_route_ids(buf, offs, lens, n, n_partitions, out)
        return out
    for i in range(n):
        if offs[i] < 0:
            out[i] = -1
        else:
            out[i] = route_id_bytes(
                buf[offs[i] : offs[i] + lens[i]], n_partitions
            )
    return out


def hash64_spans(
    buf: bytes, offs: np.ndarray, lens: np.ndarray
) -> np.ndarray:
    """FNV-1a 64 per span (0 for absent spans). Native when available;
    the Python fallback hashes the materialized bytes (same 0-for-absent
    contract, different hash function — callers must only compare hashes
    produced by the same process)."""
    n = len(offs)
    offs = np.ascontiguousarray(offs, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    out = np.empty(n, dtype=np.uint64)
    lib = _load()
    if lib is not None:
        lib.pio_hash64_spans(buf, offs, lens, n, out)
        return out
    for i in range(n):
        if offs[i] < 0:
            out[i] = 0
        else:
            out[i] = np.uint64(
                hash(buf[offs[i] : offs[i] + lens[i]]) & 0xFFFFFFFFFFFFFFFF
            )
    return out


def splice_lines(
    buf: bytes,
    starts: np.ndarray,
    ends: np.ndarray,
    want_id: np.ndarray,
    want_ct: np.ndarray,
    ids: bytes,
    ct_tail: bytes,
) -> bytes | None:
    """Assemble the import splice blob: each selected line span gets
    ``,"eventId":"<32 hex>"`` (where ``want_id``; 32 bytes per id from
    ``ids``, in order) and/or ``ct_tail`` inserted before its closing
    brace — the per-line hot loop of ``pio import`` in one native pass.
    Returns the newline-joined blob, or None when the native library is
    unavailable or a line is malformed (caller uses its Python loop)."""
    lib = _load()
    if lib is None:
        return None
    n = len(starts)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    ends = np.ascontiguousarray(ends, dtype=np.int64)
    want_id = np.ascontiguousarray(want_id, dtype=np.uint8)
    want_ct = np.ascontiguousarray(want_ct, dtype=np.uint8)
    worst = int((ends - starts).sum()) + n * (13 + 34 + len(ct_tail) + 2) + 1
    out = np.empty(worst, dtype=np.uint8)
    wrote = lib.pio_splice_lines(
        buf, starts, ends, n, want_id, want_ct, ids, ct_tail,
        len(ct_tail), out,
    )
    if wrote < 0:
        return None
    return out[:wrote].tobytes()


def extract_number(
    buf: bytes, offs: np.ndarray, lens: np.ndarray, key: str
) -> np.ndarray:
    """Per-span numeric property extraction: value of ``key`` at the top
    level of each properties-object span (NaN when missing)."""
    n = len(offs)
    offs = np.ascontiguousarray(offs, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    out = np.empty(n, dtype=np.float64)
    lib = _load()
    if lib is not None:
        lib.pio_extract_number(buf, offs, lens, n, key.encode(), out)
        return out
    for i in range(n):
        out[i] = np.nan
        if offs[i] < 0:
            continue
        try:
            v = json.loads(buf[offs[i] : offs[i] + lens[i]]).get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[i] = float(v)
        except Exception:
            pass
    return out


def parse_events_jsonl(data: bytes, scanned: "ScannedEvents | None" = None) -> list:
    """JSONL buffer -> list[Event]: native span scan for well-formed
    lines, json fallback for flagged ones (the import-path codec).
    Pass ``scanned`` to reuse a prior :func:`scan_events` of ``data``."""
    from predictionio_tpu.data.datamap import DataMap
    from predictionio_tpu.data.event import Event, parse_time

    if scanned is None:
        scanned = scan_events(data)
    buf = scanned.buf
    # plain-list span indexing: numpy scalar getitem per field per line
    # costs more than the slice+decode it addresses; tolist() once makes
    # the hot loop pure-Python-fast (this loop is the speed layer's
    # burst ceiling — see realtime/tailer._poll_files)
    offs = scanned.offs.tolist()
    lens = scanned.lens.tolist()
    flags = scanned.flags.tolist()
    # timestamps and property shapes repeat heavily (splice batches
    # share one receive stamp; events of one kind share a schema):
    # parse each distinct string once per buffer. Safe for properties
    # because DataMap copies the top-level dict it is handed.
    tmemo: dict = {}
    pmemo: dict = {}
    events = []
    lines: list[bytes] | None = None  # lazily split, only if fallbacks occur
    for i, flag in enumerate(flags):
        if flag & FLAG_EMPTY:
            continue
        o = offs[i]
        ln = lens[i]
        if flag & FLAG_FALLBACK or o[F_EVENT] < 0 or (
            o[F_ENTITY_TYPE] < 0 or o[F_ENTITY_ID] < 0
        ):
            if lines is None:
                lines = data.split(b"\n")
            events.append(Event.from_json(lines[i].decode("utf-8")))
            continue
        po = o[F_PROPERTIES]
        props_raw = buf[po : po + ln[F_PROPERTIES]] if po >= 0 else None
        if props_raw:
            pobj = pmemo.get(props_raw)
            if pobj is None:
                pobj = pmemo[props_raw] = json.loads(
                    props_raw.decode("utf-8")
                )
        else:
            pobj = {}
        tgo = o[F_TAGS]
        tags_raw = buf[tgo : tgo + ln[F_TAGS]] if tgo >= 0 else None
        teo = o[F_TARGET_ENTITY_TYPE]
        tio = o[F_TARGET_ENTITY_ID]
        pro = o[F_PR_ID]
        ofs = o[F_EVENT]
        kwargs = dict(
            event=buf[ofs : ofs + ln[F_EVENT]].decode("utf-8"),
            entity_type=buf[
                o[F_ENTITY_TYPE] : o[F_ENTITY_TYPE] + ln[F_ENTITY_TYPE]
            ].decode("utf-8"),
            entity_id=buf[
                o[F_ENTITY_ID] : o[F_ENTITY_ID] + ln[F_ENTITY_ID]
            ].decode("utf-8"),
            target_entity_type=(
                buf[teo : teo + ln[F_TARGET_ENTITY_TYPE]].decode("utf-8")
                if teo >= 0 else None
            ),
            target_entity_id=(
                buf[tio : tio + ln[F_TARGET_ENTITY_ID]].decode("utf-8")
                if tio >= 0 else None
            ),
            properties=DataMap(pobj),
            pr_id=(
                buf[pro : pro + ln[F_PR_ID]].decode("utf-8")
                if pro >= 0 else None
            ),
            tags=tuple(json.loads(tags_raw)) if tags_raw else (),
        )
        to = o[F_EVENT_TIME]
        if to >= 0:
            t = buf[to : to + ln[F_EVENT_TIME]].decode("utf-8")
            dt = tmemo.get(t)
            if dt is None:
                dt = tmemo[t] = parse_time(t)
            kwargs["event_time"] = dt
        cto = o[F_CREATION_TIME]
        if cto >= 0:
            ct = buf[cto : cto + ln[F_CREATION_TIME]].decode("utf-8")
            dt = tmemo.get(ct)
            if dt is None:
                dt = tmemo[ct] = parse_time(ct)
            kwargs["creation_time"] = dt
        eo = o[F_EVENT_ID]
        if eo >= 0:
            kwargs["event_id"] = buf[eo : eo + ln[F_EVENT_ID]].decode(
                "utf-8"
            )
        events.append(Event(**kwargs))
    return events


def _span_type_mask(
    scanned: "ScannedEvents", field: int, wanted: str
) -> np.ndarray:
    """Boolean mask of lines whose ``field`` span equals ``wanted``,
    computed by dense-indexing the (few) distinct values."""
    idx, names = index_spans(
        scanned.buf, scanned.offs[:, field], scanned.lens[:, field]
    )
    ok = np.array([name == wanted for name in names], dtype=bool)
    if not len(ok):
        return np.zeros(len(scanned), dtype=bool)
    return (idx >= 0) & ok[np.clip(idx, 0, None)]


def load_ratings_jsonl(
    data: bytes,
    event_names: Sequence[str] | None = None,
    rating_key: str | None = "rating",
    default_ratings: dict[str, float] | None = None,
    entity_type: str | None = None,
    target_entity_type: str | None = None,
    override_ratings: dict[str, float] | None = None,
    scanned: "ScannedEvents | None" = None,
    n_threads: int = 0,
) -> tuple[list[str], list[str], np.ndarray, np.ndarray, np.ndarray]:
    """One call from a JSONL event buffer to ALS training arrays:
    (user_ids, item_ids, rows, cols, ratings) with dense indices — the
    file -> device-array boundary (reference DataSource.readTraining +
    BiMap.stringInt, examples/scala-parallel-recommendation/
    custom-prepartor/src/main/scala/DataSource.scala:35-60).

    ``default_ratings`` maps event names to implicit values used when the
    ``rating_key`` property is absent; ``override_ratings`` maps event
    names to FORCED values that beat any property (the reference's
    ``case "buy" => 4.0`` rule — DataSource.scala:55 ignores properties
    for buy events). ``entity_type``/``target_entity_type`` restrict
    lines the way the template DataSources do. Pass ``scanned`` to reuse
    a prior :func:`scan_events` of the same ``data`` (single-pass reads).
    """
    if scanned is None:
        scanned = scan_events(data, n_threads=n_threads)
    n = len(scanned)
    keep = np.ones(n, dtype=bool)
    keep &= (scanned.flags == 0) & (scanned.offs[:, F_ENTITY_ID] >= 0) & (
        scanned.offs[:, F_TARGET_ENTITY_ID] >= 0
    )
    if entity_type is not None:
        keep &= _span_type_mask(scanned, F_ENTITY_TYPE, entity_type)
    if target_entity_type is not None:
        keep &= _span_type_mask(scanned, F_TARGET_ENTITY_TYPE, target_entity_type)

    # event-name filter + implicit defaults need the event spans decoded;
    # dense-index the (few) distinct event names instead of per-line str
    ev_idx, ev_names = index_spans(
        scanned.buf, scanned.offs[:, F_EVENT], scanned.lens[:, F_EVENT]
    )
    if event_names is not None:
        allowed = np.array(
            [name in set(event_names) for name in ev_names], dtype=bool
        )
        if len(allowed):
            keep &= (ev_idx >= 0) & allowed[np.clip(ev_idx, 0, None)]
        else:
            keep &= False

    if rating_key is None:  # pure implicit: defaults only, no extraction
        ratings = np.full(n, np.nan, dtype=np.float64)
    else:
        ratings = extract_number(
            scanned.buf, scanned.offs[:, F_PROPERTIES],
            scanned.lens[:, F_PROPERTIES], rating_key,
        )
    if default_ratings and len(ev_names):
        defaults = np.array(
            [default_ratings.get(name, np.nan) for name in ev_names],
            dtype=np.float64,
        )
        line_default = np.where(
            ev_idx >= 0, defaults[np.clip(ev_idx, 0, None)], np.nan
        )
        ratings = np.where(np.isnan(ratings), line_default, ratings)
    if override_ratings and len(ev_names):
        forced = np.array(
            [override_ratings.get(name, np.nan) for name in ev_names],
            dtype=np.float64,
        )
        line_forced = np.where(
            ev_idx >= 0, forced[np.clip(ev_idx, 0, None)], np.nan
        )
        ratings = np.where(np.isnan(line_forced), ratings, line_forced)
    keep &= ~np.isnan(ratings)

    kept = np.flatnonzero(keep)
    rows, user_ids = index_spans(
        scanned.buf,
        scanned.offs[kept, F_ENTITY_ID],
        scanned.lens[kept, F_ENTITY_ID],
    )
    cols, item_ids = index_spans(
        scanned.buf,
        scanned.offs[kept, F_TARGET_ENTITY_ID],
        scanned.lens[kept, F_TARGET_ENTITY_ID],
    )
    vals = ratings[kept].astype(np.float32)

    # lines the scanner couldn't take (escaped ids etc.) go through the
    # json parser and merge into the same dense id spaces. Python-list
    # conversion happens ONLY on this rare path — at 10^7 rows the lists
    # would cost gigabytes where the arrays cost megabytes.
    fallback = np.flatnonzero(scanned.flags == FLAG_FALLBACK)
    if len(fallback):
        rows = list(rows)
        cols = list(cols)
        vals = list(vals)
        user_map = {u: i for i, u in enumerate(user_ids)}
        item_map = {it: i for i, it in enumerate(item_ids)}
        lines = data.split(b"\n")
        for i in fallback:
            try:
                d = json.loads(lines[i])
            except Exception:
                continue
            if event_names is not None and d.get("event") not in set(event_names):
                continue
            if entity_type is not None and d.get("entityType") != entity_type:
                continue
            if (
                target_entity_type is not None
                and d.get("targetEntityType") != target_entity_type
            ):
                continue
            u, it = d.get("entityId"), d.get("targetEntityId")
            if not u or not it:
                continue
            v = (override_ratings or {}).get(d.get("event"))
            if v is None:
                v = (d.get("properties") or {}).get(rating_key)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    v = (default_ratings or {}).get(d.get("event"))
            if v is None:
                continue
            rows.append(user_map.setdefault(u, len(user_map)))
            cols.append(item_map.setdefault(it, len(item_map)))
            vals.append(float(v))
        user_ids = user_ids + [u for u in user_map if user_map[u] >= len(user_ids)]
        item_ids = item_ids + [it for it in item_map if item_map[it] >= len(item_ids)]

    return (
        user_ids,
        item_ids,
        np.asarray(rows, dtype=np.int32),
        np.asarray(cols, dtype=np.int32),
        np.asarray(vals, dtype=np.float32),
    )


# chunk size for bounded-RSS bulk reads (the single definition; the
# jsonl backend aliases it): span tables cost ~176 bytes/line, so a
# whole-buffer scan of a multi-GB log rivals the log itself in RSS
SCAN_CHUNK_BYTES = 256 << 20


def _line_aligned_chunks(data: bytes, chunk_bytes: int):
    """Yield line-aligned slices of ~chunk_bytes (a line longer than the
    chunk extends its slice to the next newline)."""
    pos = 0
    n = len(data)
    while pos < n:
        end = min(pos + chunk_bytes, n)
        if end < n:
            cut = data.rfind(b"\n", pos, end)
            if cut < pos:
                nxt = data.find(b"\n", end)
                end = n if nxt < 0 else nxt + 1
            else:
                end = cut + 1
        yield data[pos:end]
        pos = end


class DenseMerge:
    """Merges per-chunk / per-partition ``(users, items, rows, cols,
    vals)`` results into ONE dense id space by remapping each piece's
    local indices — the shared merge of the chunked loader, the jsonl
    fused clean+extract read, and the partitioned store's per-partition
    concatenation. Sound whenever the pieces' (user, item) pairs are
    meant to concatenate (no cross-piece last-write-wins needed)."""

    def __init__(self) -> None:
        self.user_map: dict[str, int] = {}
        self.item_map: dict[str, int] = {}
        self._rows: list = []
        self._cols: list = []
        self._vals: list = []

    def add(self, users_p, items_p, rows_p, cols_p, vals_p) -> None:
        ulut = np.fromiter(
            (self.user_map.setdefault(u, len(self.user_map))
             for u in users_p),
            np.int32,
            len(users_p),
        )
        ilut = np.fromiter(
            (self.item_map.setdefault(t, len(self.item_map))
             for t in items_p),
            np.int32,
            len(items_p),
        )
        if len(vals_p):
            self._rows.append(ulut[rows_p])
            self._cols.append(ilut[cols_p])
            self._vals.append(vals_p)

    def result(
        self,
    ) -> tuple[list[str], list[str], np.ndarray, np.ndarray, np.ndarray]:
        if not self._vals:
            return (
                list(self.user_map),
                list(self.item_map),
                np.empty(0, np.int32),
                np.empty(0, np.int32),
                np.empty(0, np.float32),
            )
        return (
            list(self.user_map),
            list(self.item_map),
            np.concatenate(self._rows),
            np.concatenate(self._cols),
            np.concatenate(self._vals),
        )


def load_ratings_jsonl_chunked(
    data: bytes,
    chunk_bytes: int | None = None,
    **kwargs,
) -> tuple[list[str], list[str], np.ndarray, np.ndarray, np.ndarray]:
    """:func:`load_ratings_jsonl` over line-aligned chunks, merging the
    per-chunk dense id spaces — the bounded-RSS bulk training read.

    A single whole-buffer scan materializes [n_lines, 11] int64 span
    tables (~176 bytes/line: gigabytes at 10^7 events) next to the raw
    buffer; chunking keeps the span tables at O(chunk) while the merged
    outputs stay compact numpy arrays.
    """
    if chunk_bytes is None:
        chunk_bytes = SCAN_CHUNK_BYTES
    if len(data) <= chunk_bytes:
        return load_ratings_jsonl(data, **kwargs)
    merge = DenseMerge()
    for chunk in _line_aligned_chunks(data, chunk_bytes):
        merge.add(*load_ratings_jsonl(chunk, **kwargs))
    return merge.result()
