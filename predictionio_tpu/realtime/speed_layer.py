"""SpeedLayer: the loop that ties tailer + fold-in to a deployed server.

One background thread per deployed engine server: every ``interval``
seconds it polls the event tailer, folds tailed rating events into the
served ALS model, and hot-patches the server's model list under the
epoch fence. The invariants (docs/realtime.md):

- **retrain wins** — a ``/reload`` to a NEW engine instance supersedes
  all fold-in state: the tailer cursor resets to the new instance's
  train watermark (its events are in the retrain) and pending patches
  are dropped. A reload of the SAME instance likewise discards applied
  patches (the epoch fence rejects them); folded events are served
  again only after the next retrain covers them.
- **at-most-once serving staleness** — gauges (`events_behind`,
  `seconds_behind`, `foldin_epoch`) ride the engine server's
  ``/stats.json`` so operators can alert on a stuck speed layer
  (the staleness-as-first-class-metric argument of arxiv 2501.10546).
- the fold thread NEVER holds the server lock across a solve: it
  snapshots, folds off-lock, and compare-and-swaps by epoch.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from predictionio_tpu import faults
from predictionio_tpu.common.breaker import CircuitBreaker
from predictionio_tpu.data import store
from predictionio_tpu.obs import freshness as obs_freshness
from predictionio_tpu.obs import metrics as obs_metrics
from predictionio_tpu.obs import slo as obs_slo
from predictionio_tpu.obs import trace as obs_trace
from predictionio_tpu.realtime.foldin import ALSFoldIn, FoldInConfig
from predictionio_tpu.realtime.tailer import EventTailer

logger = logging.getLogger(__name__)

_m_fold = obs_metrics.histogram(
    "pio_foldin_solve_seconds",
    "Fold+patch time per speed-layer cycle that saw events",
)
_m_poll = obs_metrics.histogram(
    "pio_tailer_poll_seconds", "Event-tailer poll time per cycle"
)
_m_tailed = obs_metrics.counter(
    "pio_tailer_events_total", "Events returned by tailer polls"
)


def _is_als_model(m) -> bool:
    return all(
        hasattr(m, a)
        for a in ("user_index", "item_index", "user_factors", "item_factors")
    )


class SpeedLayer:
    """Tail the deployed app's event stream and fold into the live model.

    Derives its fold-in config from the server's deployed EngineParams
    (datasource app/event names + the algorithm's regularization), so the
    incremental solve matches the batch trainer's problem exactly.
    """

    def __init__(
        self,
        server,
        interval: float = 5.0,
        cursor_path=None,
        batch_limit: int = 5000,
        breaker: CircuitBreaker | None = None,
    ):
        self.server = server
        self.interval = float(interval)
        # trips after repeated fold-in failures so a broken fold path
        # stops consuming events (poll is gated on allow(), so events
        # stay in the log, not tailed-and-dropped); the engine keeps
        # serving the last good epoch-fenced model while open
        self.breaker = breaker or CircuitBreaker(
            "foldin", failure_threshold=3, base_backoff_s=2.0,
            max_backoff_s=60.0,
        )
        ds_params = server.engine_params.datasource[1]
        algo_params = server.engine_params.algorithms[0][1]
        self._config = FoldInConfig(
            event_names=tuple(ds_params.event_names),
            rating_key="rating",
            override_ratings={"buy": ds_params.buy_rating},
            reg=getattr(algo_params, "lambda_", 0.01),
            weighted_reg=True,
        )
        app_id, channel_id = store.app_name_to_id(
            ds_params.app_name, None, server.storage
        )
        events = server.storage.get_events()
        # columnar tail preference (docs/realtime.md "Columnar tail
        # path"): rate-shaped chunks decode straight to arrays; the
        # tailer falls back per chunk/per line on anything else.
        # PIO_TAIL_COLUMNAR=0 pins the object path.
        columnar_config = None
        if os.environ.get("PIO_TAIL_COLUMNAR", "1").strip().lower() not in (
            "0", "false", "no", "off"
        ):
            from predictionio_tpu.data.storage import colspans

            cfg = self._config
            columnar_config = colspans.DecodeConfig(
                event_names=cfg.event_names,
                rating_key=cfg.rating_key,
                default_ratings=cfg.default_ratings,
                override_ratings=cfg.override_ratings,
                entity_type=cfg.entity_type,
                target_entity_type=cfg.target_entity_type,
            )
        self.tailer = EventTailer(
            events,
            app_id,
            channel_id,
            cursor_path=cursor_path,
            batch_limit=batch_limit,
            columnar_config=columnar_config,
        )
        self.foldin = ALSFoldIn(events, app_id, channel_id, config=self._config)
        # the instance this layer's fold-in state belongs to; a snapshot
        # naming a different instance means a retrain superseded us
        self._instance_id = server.instance.id
        self._caught_up_at = time.time()
        self._last_fold_s = 0.0
        self.events_folded = 0
        self.users_touched = 0
        self.users_added = 0
        # each successful patch bumps the server epoch, which retires
        # every cached query result (server/query_cache.py) — operators
        # watch this against cache_hit_rate: a fold interval shorter
        # than the traffic's repeat window makes the cache useless
        self.cache_invalidations = 0
        self._last_fold_trace: str | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        server.speed_layer = self
        # staleness as real Prometheus gauges, not just /stats.json:
        # scrape-time callbacks read this layer's live state (the newest
        # layer wins the registration — one layer per server process per
        # SERIES; multi-tenant mounts each get their own variant= series)
        vn = getattr(server, "variant_name", None)
        labels = {"variant": vn} if vn else {}
        obs_metrics.gauge(
            "pio_realtime_events_behind",
            "Events in the log the speed layer has not folded yet",
            **labels,
        ).set_function(lambda: float(self.tailer.events_behind() or 0))
        obs_metrics.gauge(
            "pio_realtime_seconds_behind",
            "Seconds since the speed layer was last caught up",
            **labels,
        ).set_function(lambda: float(self.gauges()["seconds_behind"]))
        obs_metrics.gauge(
            "pio_realtime_foldin_epoch",
            "Fold-in patches applied since the last full reload",
            **labels,
        ).set_function(lambda: float(self.server._foldin_epoch))
        # default objectives: bounded staleness + breaker open budget
        obs_slo.install_speed_layer_slos(self)

    # -- one fold cycle -----------------------------------------------------

    def step(self) -> str:
        """One poll+fold+patch cycle; returns what happened (for tests
        and logs): "superseded" | "idle" | "patched" | "fenced" |
        "skipped" | "breaker_open" | "fold_failed".

        Each cycle that reaches the fold carries a ``speedlayer.fold``
        trace (spans: tail.poll, foldin.fold, server.patch) offered to
        the slow-trace ring, and the trace id is exported in
        :meth:`gauges` — fold latency visible in /traces.json is
        attributable to the exact cycle /stats.json reported (PR 7 left
        the fold path traceless)."""
        tr = (
            obs_trace.Trace("speedlayer.fold")
            if obs_metrics.enabled()
            else None
        )
        prev = obs_trace.current_trace()
        obs_trace.set_current_trace(tr)
        try:
            outcome = self._step(tr)
        finally:
            obs_trace.set_current_trace(prev)
        if tr is not None and outcome in ("patched", "fold_failed", "fenced"):
            tr.finish(200 if outcome == "patched" else 500)
            obs_trace.TRACES.offer(tr)
            self._last_fold_trace = tr.trace_id
        return outcome

    def _step(self, tr) -> str:
        inst_id, models, epoch = self.server.model_snapshot()
        if inst_id != self._instance_id:
            # retrain won: the new instance's training read covered the
            # log up to its own watermark — restart tailing from now
            logger.info(
                "speed layer superseded by instance %s (was %s); "
                "resetting cursor to the new train watermark",
                inst_id,
                self._instance_id,
            )
            self._instance_id = inst_id
            self.tailer.reset()
            self.foldin.cold_items.clear()
            self._caught_up_at = time.time()
            return "superseded"

        if not self.breaker.allow():
            # open breaker: don't poll — a poll persists the cursor, so
            # tailing events we then can't fold would silently drop them
            return "breaker_open"

        t_p0 = time.perf_counter()
        batch = self.tailer.poll_columnar()
        t_p1 = time.perf_counter()
        _m_poll.observe(t_p1 - t_p0)
        if tr is not None:
            tr.add_span("tail.poll", t_p0, t_p1)
        n_events = batch.n_events
        if not n_events:
            if (self.tailer.events_behind() or 0) == 0:
                self._caught_up_at = time.time()
            return "idle"
        _m_tailed.inc(n_events)

        t0 = time.perf_counter()
        for _attempt in range(3):
            patched_any = False
            new_models = []
            stats = None
            for m in models:
                if _is_als_model(m):
                    try:
                        faults.fault_point("foldin.fold")
                        t_f0 = time.perf_counter()
                        patched, stats = self.foldin.fold_in_columnar(
                            m, batch
                        )
                        if tr is not None:
                            tr.add_span(
                                "foldin.fold", t_f0, time.perf_counter()
                            )
                    except Exception:
                        # the poll already persisted the cursor, so this
                        # batch is lost to fold-in (at-most-once; the
                        # next retrain covers it) — count the failure
                        # and let the breaker decide whether to keep
                        # attempting future batches
                        self.breaker.record_failure()
                        self._last_fold_s = time.perf_counter() - t0
                        logger.exception(
                            "fold-in failed (%d events not folded; "
                            "breaker %s)", n_events, self.breaker.state,
                        )
                        return "fold_failed"
                    if patched is not None:
                        new_models.append(patched)
                        patched_any = True
                        continue
                new_models.append(m)
            self.breaker.record_success()
            if not patched_any:
                self._last_fold_s = time.perf_counter() - t0
                return "skipped"  # no foldable events for any model
            t_a0 = time.perf_counter()
            applied = self.server.apply_patch(new_models, epoch)
            if tr is not None:
                tr.add_span("server.patch", t_a0, time.perf_counter())
            if applied:
                # the epoch bump just swept the query cache (the
                # fold-in hook mirrors /reload exactly)
                if self.server.query_cache is not None:
                    self.cache_invalidations += 1
                self._last_fold_s = time.perf_counter() - t0
                _m_fold.observe(self._last_fold_s)
                # freshness lineage: these events are servable as of
                # THIS fenced commit — ingest stamp to now is the true
                # ingest-to-servable latency (an event that waited out a
                # breaker or lost fences shows every second of it)
                with self.server._lock:
                    foldin_epoch = self.server._foldin_epoch
                obs_freshness.observe_commit(
                    batch.creation_timestamps(),
                    kind="patch",
                    epoch=epoch + 1,
                    foldin_epoch=foldin_epoch,
                )
                if stats is not None:
                    self.events_folded += stats.rating_events
                    self.users_touched += stats.users_touched
                    self.users_added += stats.users_added
                if (self.tailer.events_behind() or 0) == 0:
                    self._caught_up_at = time.time()
                return "patched"
            # fence lost: someone swapped models since our snapshot
            inst_id, models, epoch = self.server.model_snapshot()
            if inst_id != self._instance_id:
                # a retrain landed mid-fold: ITS training read already
                # covers these events — drop the batch, reset forward
                self._instance_id = inst_id
                self.tailer.reset()
                self.foldin.cold_items.clear()
                self._last_fold_s = time.perf_counter() - t0
                return "superseded"
            # same instance (another patch or same-instance reload):
            # re-fold this batch against the fresh models
        self._last_fold_s = time.perf_counter() - t0
        _m_fold.observe(self._last_fold_s)
        logger.warning("speed layer lost the epoch fence 3 times; retrying next poll")
        return "fenced"

    # -- gauges -------------------------------------------------------------

    def gauges(self) -> dict:
        behind = self.tailer.events_behind()
        with self.server._lock:
            foldin_epoch = self.server._foldin_epoch
        return {
            "enabled": True,
            "interval": self.interval,
            "mode": self.tailer.mode,
            "foldin_epoch": foldin_epoch,
            "events_behind": behind,
            "seconds_behind": (
                0.0 if behind == 0 else round(time.time() - self._caught_up_at, 3)
            ),
            "events_folded": self.events_folded,
            "users_touched": self.users_touched,
            "users_added": self.users_added,
            "cold_start_items": len(self.foldin.cold_items),
            "last_fold_s": round(self._last_fold_s, 6),
            "last_fold_trace": self._last_fold_trace,
            "query_cache_invalidations": self.cache_invalidations,
            "breaker": self.breaker.snapshot(),
        }

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="speed-layer", daemon=True
        )
        self._thread.start()
        logger.info(
            "speed layer started: interval %.1fs, tail mode %s",
            self.interval,
            self.tailer.mode,
        )

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None and t.is_alive():
            t.join(timeout=10)
        # graceful-drain contract: the cursor must be on disk before the
        # process exits so the replacement instance re-attaches exactly
        # where this one left off
        try:
            self.tailer.persist()
        except OSError:  # pragma: no cover - disk error at exit
            logger.exception("tailer cursor persist on stop failed")

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:  # pragma: no cover - loop must survive
                logger.exception("speed layer fold cycle failed")
            self._stop.wait(self.interval)
