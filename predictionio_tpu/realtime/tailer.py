"""Incremental event-log follower with a durable per-source cursor.

One tailer follows one (app, channel) stream of an Events DAO and
delivers each event at most once across polls, restarts, log rotation,
and torn trailing writes. The cursor mode is picked from the backend's
capabilities:

- **files** — the backend exposes ``tail_files()`` (jsonl, partitioned):
  per-file byte offsets, each keyed by ``(inode, mtime_ns, size)``
  lineage. A compaction/rotation replaces the inode (or shrinks the
  file below our offset); that breaks lineage, so the file is re-read
  from byte 0 with watermark + seen-id dedupe suppressing records that
  were already delivered or predate the attach point.
- **seq** — the backend answers ``tail_end()`` (sqlite rowid, postgres
  creationtime, memory insertion seq): the store hands us events past an
  opaque monotone cursor; boundary re-delivery is deduped by event id.
- **generic** — neither: fall back to ``change_token`` + full ``find``
  filtered by the attach watermark. Correct but O(store) per change;
  only the capability floor, every bundled backend has a better mode.

The cursor persists as JSON (tmp + atomic replace) so a restarted
process resumes exactly where it stopped — no double-counting, no
skipping. A fresh tailer attaches AT THE END of the stream (the batch
layer owns history; the speed layer only folds what arrives after
deploy), and ``reset()`` re-attaches at the end after a retrain.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from pathlib import Path

from predictionio_tpu import faults
from predictionio_tpu.data.event import Event
from predictionio_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)

_CURSOR_VERSION = 1
# cap for the events_behind estimate scan, per file
_BEHIND_SCAN_CAP = 4 * 1024 * 1024
# cap for one poll's read of a single file: far behind a burst the
# unread remainder can dwarf what the batch limit lets one poll deliver
_READ_CAP = 16 * 1024 * 1024


@dataclasses.dataclass
class _FileCursor:
    """Byte offset into one log file plus the lineage it belongs to.

    ``offset`` is only meaningful for the file identified by ``ino``
    with a size that never went below ``offset`` — a new inode or a
    shrink means the log was rewritten and the offset is void."""

    offset: int
    ino: int
    mtime_ns: int
    size: int


def _end_offset(path: Path) -> int:
    """Offset just past the last complete line (trailing newline).

    Scans backwards in blocks so attaching to a log with a torn final
    line (a writer died mid-append) doesn't leave the cursor pointing
    into the torn bytes — the torn line re-delivers whole once the
    writer (or compaction) completes it."""
    try:
        with open(path, "rb") as f:
            size = os.fstat(f.fileno()).st_size
            pos = size
            while pos > 0:
                step = min(65536, pos)
                f.seek(pos - step)
                block = f.read(step)
                nl = block.rfind(b"\n")
                if nl >= 0:
                    return pos - step + nl + 1
                pos -= step
            return 0
    except OSError:
        return 0


class EventTailer:
    """Follow one (app, channel) event stream with a durable cursor.

    ``cursor_path=None`` keeps the cursor in memory only (tests, bench);
    otherwise every poll that moved the cursor persists it atomically.
    """

    def __init__(
        self,
        events,
        app_id: int,
        channel_id: int | None = None,
        cursor_path: str | Path | None = None,
        batch_limit: int = 5000,
    ):
        self._events = events
        self._app_id = app_id
        self._channel_id = channel_id
        self._cursor_path = Path(cursor_path) if cursor_path else None
        self._batch_limit = int(batch_limit)
        if callable(getattr(events, "tail_files", None)):
            self.mode = "files"
        elif events.tail_end(app_id, channel_id) is not None:
            self.mode = "seq"
        else:
            self.mode = "generic"
        self._files: dict[str, _FileCursor] = {}
        self._seq: object | None = None
        self._token: object | None = None
        self._watermark: float = 0.0
        self._seen: set[str] = set()
        self._dirty = False
        if not self._load():
            self.reset()

    # -- cursor lifecycle ---------------------------------------------------

    def reset(self) -> None:
        """Re-attach at the current end of the stream.

        Called at first attach and after a retrain supersedes the fold-in
        state: everything up to now is (or will be) covered by the batch
        layer, so the speed layer starts clean from here."""
        self._seen = set()
        self._watermark = time.time()
        self._files = {}
        self._token = None
        if self.mode == "files":
            for path in self._events.tail_files(self._app_id, self._channel_id):
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                self._files[str(path)] = _FileCursor(
                    _end_offset(Path(path)), st.st_ino, st.st_mtime_ns, st.st_size
                )
        elif self.mode == "seq":
            self._seq = self._events.tail_end(self._app_id, self._channel_id)
        self._dirty = True
        self._save()

    def _load(self) -> bool:
        if self._cursor_path is None or not self._cursor_path.exists():
            return False
        # any corruption — torn/truncated JSON, valid JSON with the wrong
        # structure (non-dict, missing _FileCursor fields, non-numeric
        # watermark) — degrades to False: the caller re-attaches at the
        # watermark (reset()) instead of crashing the speed layer
        try:
            state = json.loads(self._cursor_path.read_text())
            if state.get("version") != _CURSOR_VERSION or state.get("mode") != self.mode:
                logger.warning(
                    "tailer cursor %s is for mode %r (we are %r); resetting",
                    self._cursor_path,
                    state.get("mode"),
                    self.mode,
                )
                return False
            watermark = float(state.get("watermark", 0.0))
            seen = set(state.get("seen", ()))
            seq = state.get("seq")
            files = {
                p: _FileCursor(c["offset"], c["ino"], c["mtime_ns"], c["size"])
                for p, c in state.get("files", {}).items()
            }
        except (OSError, ValueError, KeyError, TypeError, AttributeError):
            logger.warning(
                "corrupt tailer cursor %s; re-attaching at the watermark",
                self._cursor_path,
            )
            obs_metrics.counter(
                "pio_tailer_cursor_recovered",
                "Tailer restarts that discarded a corrupt cursor file",
            ).inc()
            return False
        self._watermark = watermark
        self._seen = seen
        self._seq = seq
        self._token = None  # change tokens don't survive restart; re-scan
        self._files = files
        return True

    def _save(self) -> None:
        if not self._dirty:
            return
        self._dirty = False
        if self._cursor_path is None:
            return
        state = {
            "version": _CURSOR_VERSION,
            "mode": self.mode,
            "watermark": self._watermark,
            "seq": self._seq,
            "files": {
                p: dataclasses.asdict(c) for p, c in self._files.items()
            },
            "seen": sorted(self._seen),
        }
        self._cursor_path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self._cursor_path.with_name(self._cursor_path.name + ".tmp")
        tmp.write_text(json.dumps(state))
        faults.fault_point("storage.rename")
        os.replace(tmp, self._cursor_path)

    def persist(self) -> None:
        """Force the cursor to disk if it moved since the last save —
        the graceful-shutdown flush (the speed layer calls this on
        stop so a drained process re-attaches exactly where it left
        off instead of re-delivering the last batch window)."""
        self._save()

    # -- polling ------------------------------------------------------------

    def poll(self, limit: int | None = None) -> list[Event]:
        """Events appended since the last poll, at most ``limit``
        (default: the tailer's batch_limit). Persists the moved cursor
        before returning, so a crash after poll never re-delivers."""
        limit = self._batch_limit if limit is None else int(limit)
        if self.mode == "files":
            out = self._poll_files(limit)
        elif self.mode == "seq":
            out = self._poll_seq(limit)
        else:
            out = self._poll_generic(limit)
        self._save()
        return out

    def _mark_seen(self, event: Event) -> bool:
        """True if the event is new (and now remembered)."""
        eid = event.event_id
        if eid is None:
            return True
        if eid in self._seen:
            return False
        self._seen.add(eid)
        return True

    def _parse_line(self, raw: bytes) -> Event | None:
        line = raw.strip()
        if not line or line.startswith(b'{"$delete"'):
            return None
        try:
            return Event.from_json(line.decode("utf-8"))
        except (ValueError, KeyError, UnicodeDecodeError) as err:
            logger.warning("tailer: skipping unparseable log line: %s", err)
            return None

    def _poll_files(self, limit: int) -> list[Event]:
        out: list[Event] = []
        for path in self._events.tail_files(self._app_id, self._channel_id):
            if len(out) >= limit:
                break
            key = str(path)
            try:
                f = open(path, "rb")
            except OSError:
                continue
            with f:
                # fstat AFTER open: a rotation between a stat and the open
                # could otherwise pair old lineage with new bytes
                st = os.fstat(f.fileno())
                cur = self._files.get(key)
                fresh = (
                    cur is None
                    or st.st_ino != cur.ino
                    or st.st_size < cur.offset
                )
                if (
                    not fresh
                    and st.st_size == cur.size
                    and st.st_mtime_ns == cur.mtime_ns
                ):
                    continue  # unchanged since last poll
                start = 0 if fresh else cur.offset
                f.seek(start)
                # bound the read to the fstat'ed size: bytes appended
                # after the fstat belong to the next poll's lineage.
                # Also cap the read: far behind a burst, the remainder
                # can be 100s of MB while the batch limit only lets one
                # poll deliver a few MB of lines — reading it all every
                # poll would make catch-up quadratic in the backlog.
                to_read = max(0, st.st_size - start)
                capped = to_read > _READ_CAP
                buf = f.read(_READ_CAP if capped else to_read)
            consumed = 0
            truncated = capped
            # bulk fast path: hand every complete line in the buffer to
            # the native span scanner in one call (~an order of magnitude
            # cheaper than per-line Event.from_json — this is what keeps
            # seconds_behind bounded under a wire-speed ingest burst).
            # Bail to the per-line loop when the chunk carries tombstones
            # (the scanner has no $delete shape) or fails to parse.
            end = buf.rfind(b"\n") + 1
            chunk = buf[:end]
            parsed = None
            if chunk and b'"$delete"' not in chunk:
                remaining = limit - len(out)
                if chunk.count(b"\n") > remaining:
                    # trim to the remaining-limit'th newline; the rest of
                    # the buffer is re-read on the next poll
                    cut = -1
                    for _ in range(remaining):
                        cut = chunk.find(b"\n", cut + 1)
                    chunk = chunk[: cut + 1]
                    truncated = True
                try:
                    from predictionio_tpu import native

                    parsed = native.parse_events_jsonl(chunk)
                except (ValueError, KeyError, UnicodeDecodeError) as err:
                    logger.warning(
                        "tailer: bulk parse failed, falling back "
                        "per-line: %s",
                        err,
                    )
                    parsed = None
                    truncated = capped
            if parsed is not None:
                consumed = len(chunk)
                for event in parsed:
                    if (
                        fresh
                        and event.creation_time.timestamp()
                        <= self._watermark
                    ):
                        continue
                    if self._mark_seen(event):
                        out.append(event)
                self._finish_file(key, st, start + consumed, truncated)
                continue
            pos = 0
            while pos < len(buf):
                nl = buf.find(b"\n", pos)
                if nl < 0:
                    break  # torn trailing line: wait for the newline
                if len(out) >= limit:
                    truncated = True
                    break
                raw = buf[pos:nl]
                pos = nl + 1
                consumed = pos
                event = self._parse_line(raw)
                if event is None:
                    continue
                if fresh and event.creation_time.timestamp() <= self._watermark:
                    # rewrite resurfaced pre-attach history; not ours
                    continue
                if self._mark_seen(event):
                    out.append(event)
            self._finish_file(key, st, start + consumed, truncated)
        return out

    def _finish_file(self, key, st, new_offset: int, truncated: bool) -> None:
        if truncated:
            # stop mid-file: record the offset but NOT the stat, so
            # the next poll re-reads the remainder
            self._files[key] = _FileCursor(new_offset, st.st_ino, -1, -1)
        else:
            self._files[key] = _FileCursor(
                new_offset, st.st_ino, st.st_mtime_ns, st.st_size
            )
        self._dirty = True

    def _poll_seq(self, limit: int) -> list[Event]:
        got = self._events.tail_events(
            self._app_id, self._channel_id, after=self._seq, limit=limit
        )
        if got is None:  # capability vanished (shouldn't happen)
            return []
        events, cursor = got
        if cursor != self._seq:
            self._seq = cursor
            self._dirty = True
        out = [e for e in events if self._mark_seen(e)]
        if out:
            self._dirty = True
        return out

    def _poll_generic(self, limit: int) -> list[Event]:
        token = self._events.change_token(self._app_id, self._channel_id)
        if token is not None and token == self._token:
            return []
        out: list[Event] = []
        truncated = False
        for event in self._events.find(self._app_id, self._channel_id):
            if event.creation_time.timestamp() <= self._watermark:
                continue
            if not self._mark_seen(event):
                continue
            out.append(event)
            if len(out) >= limit:
                truncated = True
                break
        if not truncated:
            # only advance the token when the scan was complete —
            # otherwise the rest of the backlog would be skipped
            self._token = token
        if out:
            self._dirty = True
        return out

    # -- staleness ----------------------------------------------------------

    def events_behind(self) -> int | None:
        """Estimated undelivered events (upper bound: deletes and
        replaced records count too), or None when unknowable cheaply."""
        if self.mode == "files":
            behind = 0
            for path in self._events.tail_files(self._app_id, self._channel_id):
                cur = self._files.get(str(path))
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                if (
                    cur is not None
                    and st.st_ino == cur.ino
                    and st.st_size == cur.size
                    and st.st_mtime_ns == cur.mtime_ns
                ):
                    continue
                start = (
                    cur.offset
                    if cur is not None
                    and st.st_ino == cur.ino
                    and st.st_size >= cur.offset
                    else 0
                )
                try:
                    with open(path, "rb") as f:
                        f.seek(start)
                        behind += f.read(_BEHIND_SCAN_CAP).count(b"\n")
                except OSError:
                    continue
            return behind
        if self.mode == "seq":
            end = self._events.tail_end(self._app_id, self._channel_id)
            if isinstance(end, int) and isinstance(self._seq, int):
                return max(0, end - self._seq)
            return None  # float cursors (postgres) aren't countable
        token = self._events.change_token(self._app_id, self._channel_id)
        return 0 if token is not None and token == self._token else None
