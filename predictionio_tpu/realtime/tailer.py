"""Incremental event-log follower with a durable per-source cursor.

One tailer follows one (app, channel) stream of an Events DAO and
delivers each event at most once across polls, restarts, log rotation,
and torn trailing writes. The cursor mode is picked from the backend's
capabilities:

- **files** — the backend exposes ``tail_files()`` (jsonl, partitioned):
  per-file byte offsets, each keyed by ``(inode, mtime_ns, size)``
  lineage. A compaction/rotation replaces the inode (or shrinks the
  file below our offset); that breaks lineage, so the file is re-read
  from byte 0 with watermark + seen-id dedupe suppressing records that
  were already delivered or predate the attach point.
- **seq** — the backend answers ``tail_end()`` (sqlite rowid, postgres
  creationtime, memory insertion seq): the store hands us events past an
  opaque monotone cursor; boundary re-delivery is deduped by event id.
- **generic** — neither: fall back to ``change_token`` + full ``find``
  filtered by the attach watermark. Correct but O(store) per change;
  only the capability floor, every bundled backend has a better mode.

The cursor persists as JSON (tmp + atomic replace) so a restarted
process resumes exactly where it stopped — no double-counting, no
skipping. A fresh tailer attaches AT THE END of the stream (the batch
layer owns history; the speed layer only folds what arrives after
deploy), and ``reset()`` re-attaches at the end after a retrain.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from pathlib import Path

from predictionio_tpu import faults
from predictionio_tpu.data.event import Event
from predictionio_tpu.obs import metrics as obs_metrics
from predictionio_tpu.obs import trace as obs_trace

logger = logging.getLogger(__name__)

# columnar/object split of the columnar poll mode's decode, per line
# (docs/observability.md): lines that went straight to arrays vs lines
# routed to the per-line object parser (mixed-stream fallbacks, chunks
# that failed or were fault-injected at tail.decode)
_m_col_lines = obs_metrics.counter(
    "pio_tailer_columnar_lines_total",
    "Log lines the columnar tail path decoded straight to arrays",
)
_m_col_fallback = obs_metrics.counter(
    "pio_tailer_columnar_fallback_lines_total",
    "Log lines the columnar tail path routed to the object parser",
)

_CURSOR_VERSION = 1
# cap for the events_behind estimate scan, per file
_BEHIND_SCAN_CAP = 4 * 1024 * 1024
# cap for one poll's read of a single file: far behind a burst the
# unread remainder can dwarf what the batch limit lets one poll deliver
_READ_CAP = 16 * 1024 * 1024


@dataclasses.dataclass
class _FileCursor:
    """Byte offset into one log file plus the lineage it belongs to.

    ``offset`` is only meaningful for the file identified by ``ino``
    with a size that never went below ``offset`` — a new inode or a
    shrink means the log was rewritten and the offset is void."""

    offset: int
    ino: int
    mtime_ns: int
    size: int


def _end_offset(path: Path) -> int:
    """Offset just past the last complete line (trailing newline).

    Scans backwards in blocks so attaching to a log with a torn final
    line (a writer died mid-append) doesn't leave the cursor pointing
    into the torn bytes — the torn line re-delivers whole once the
    writer (or compaction) completes it."""
    try:
        with open(path, "rb") as f:
            size = os.fstat(f.fileno()).st_size
            pos = size
            while pos > 0:
                step = min(65536, pos)
                f.seek(pos - step)
                block = f.read(step)
                nl = block.rfind(b"\n")
                if nl >= 0:
                    return pos - step + nl + 1
                pos -= step
            return 0
    except OSError:
        return 0


class TailedBatch:
    """What one :meth:`EventTailer.poll_columnar` returned: an ordered
    list of segments, each either a ``list[Event]`` (object path) or a
    :class:`colspans.ColumnarTail` (array path). Counts and freshness
    stamps are uniform across both so the speed layer never branches."""

    __slots__ = ("segments",)

    def __init__(self, segments: list):
        self.segments = [s for s in segments if _seg_len(s)]

    @property
    def n_events(self) -> int:
        return sum(_seg_len(s) for s in self.segments)

    def creation_timestamps(self) -> list[float]:
        """Epoch creation stamps of every delivered event (absent ones
        skipped) — the freshness-lineage input for observe_commit."""
        out: list[float] = []
        for s in self.segments:
            if isinstance(s, list):
                out.extend(
                    e.creation_time.timestamp()
                    for e in s
                    if e.creation_time is not None
                )
            else:
                ts = s.creation_ts
                out.extend(ts[~_np_isnan(ts)].tolist())
        return out


def _seg_len(seg) -> int:
    return seg.n_rows if hasattr(seg, "n_rows") else len(seg)


def _np_isnan(arr):
    import numpy as np

    return np.isnan(arr)


class EventTailer:
    """Follow one (app, channel) event stream with a durable cursor.

    ``cursor_path=None`` keeps the cursor in memory only (tests, bench);
    otherwise every poll that moved the cursor persists it atomically.

    ``columnar_config`` (a :class:`colspans.DecodeConfig`) arms the
    columnar poll mode: :meth:`poll_columnar` then decodes rate-shaped
    chunks straight to arrays instead of per-line Event objects.
    """

    def __init__(
        self,
        events,
        app_id: int,
        channel_id: int | None = None,
        cursor_path: str | Path | None = None,
        batch_limit: int = 5000,
        columnar_config=None,
    ):
        self._events = events
        self._app_id = app_id
        self._channel_id = channel_id
        self._cursor_path = Path(cursor_path) if cursor_path else None
        self._batch_limit = int(batch_limit)
        self._columnar_config = columnar_config
        if callable(getattr(events, "tail_files", None)):
            self.mode = "files"
        elif events.tail_end(app_id, channel_id) is not None:
            self.mode = "seq"
        else:
            self.mode = "generic"
        self._files: dict[str, _FileCursor] = {}
        self._seq: object | None = None
        self._token: object | None = None
        self._watermark: float = 0.0
        self._seen: set[str] = set()
        self._dirty = False
        if not self._load():
            self.reset()

    # -- cursor lifecycle ---------------------------------------------------

    def reset(self) -> None:
        """Re-attach at the current end of the stream.

        Called at first attach and after a retrain supersedes the fold-in
        state: everything up to now is (or will be) covered by the batch
        layer, so the speed layer starts clean from here."""
        self._seen = set()
        self._watermark = time.time()
        self._files = {}
        self._token = None
        if self.mode == "files":
            for path in self._events.tail_files(self._app_id, self._channel_id):
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                self._files[str(path)] = _FileCursor(
                    _end_offset(Path(path)), st.st_ino, st.st_mtime_ns, st.st_size
                )
        elif self.mode == "seq":
            self._seq = self._events.tail_end(self._app_id, self._channel_id)
        self._dirty = True
        self._save()

    def _load(self) -> bool:
        if self._cursor_path is None or not self._cursor_path.exists():
            return False
        # any corruption — torn/truncated JSON, valid JSON with the wrong
        # structure (non-dict, missing _FileCursor fields, non-numeric
        # watermark) — degrades to False: the caller re-attaches at the
        # watermark (reset()) instead of crashing the speed layer
        try:
            state = json.loads(self._cursor_path.read_text())
            if state.get("version") != _CURSOR_VERSION or state.get("mode") != self.mode:
                logger.warning(
                    "tailer cursor %s is for mode %r (we are %r); resetting",
                    self._cursor_path,
                    state.get("mode"),
                    self.mode,
                )
                return False
            watermark = float(state.get("watermark", 0.0))
            seen = set(state.get("seen", ()))
            seq = state.get("seq")
            files = {
                p: _FileCursor(c["offset"], c["ino"], c["mtime_ns"], c["size"])
                for p, c in state.get("files", {}).items()
            }
        except (OSError, ValueError, KeyError, TypeError, AttributeError):
            logger.warning(
                "corrupt tailer cursor %s; re-attaching at the watermark",
                self._cursor_path,
            )
            obs_metrics.counter(
                "pio_tailer_cursor_recovered",
                "Tailer restarts that discarded a corrupt cursor file",
            ).inc()
            return False
        self._watermark = watermark
        self._seen = seen
        self._seq = seq
        self._token = None  # change tokens don't survive restart; re-scan
        self._files = files
        return True

    def _save(self) -> None:
        if not self._dirty:
            return
        self._dirty = False
        if self._cursor_path is None:
            return
        state = {
            "version": _CURSOR_VERSION,
            "mode": self.mode,
            "watermark": self._watermark,
            "seq": self._seq,
            "files": {
                p: dataclasses.asdict(c) for p, c in self._files.items()
            },
            "seen": sorted(self._seen),
        }
        self._cursor_path.parent.mkdir(parents=True, exist_ok=True)
        tmp = self._cursor_path.with_name(self._cursor_path.name + ".tmp")
        tmp.write_text(json.dumps(state))
        faults.fault_point("storage.rename")
        os.replace(tmp, self._cursor_path)

    def persist(self) -> None:
        """Force the cursor to disk if it moved since the last save —
        the graceful-shutdown flush (the speed layer calls this on
        stop so a drained process re-attaches exactly where it left
        off instead of re-delivering the last batch window)."""
        self._save()

    # -- polling ------------------------------------------------------------

    def poll(self, limit: int | None = None) -> list[Event]:
        """Events appended since the last poll, at most ``limit``
        (default: the tailer's batch_limit). Persists the moved cursor
        before returning, so a crash after poll never re-delivers."""
        limit = self._batch_limit if limit is None else int(limit)
        if self.mode == "files":
            out = self._poll_files(limit)
        elif self.mode == "seq":
            out = self._poll_seq(limit)
        else:
            out = self._poll_generic(limit)
        self._save()
        return out

    def _mark_seen(self, event: Event) -> bool:
        """True if the event is new (and now remembered)."""
        eid = event.event_id
        if eid is None:
            return True
        if eid in self._seen:
            return False
        self._seen.add(eid)
        return True

    def _parse_line(self, raw: bytes) -> Event | None:
        line = raw.strip()
        if not line or line.startswith(b'{"$delete"'):
            return None
        try:
            return Event.from_json(line.decode("utf-8"))
        except (ValueError, KeyError, UnicodeDecodeError) as err:
            logger.warning("tailer: skipping unparseable log line: %s", err)
            return None

    def _read_file(self, path):
        """Open + fstat + capped read of one tailed file. Returns
        ``(st, fresh, start, buf, capped)`` or None when the file is
        unreadable or unchanged since the last poll."""
        key = str(path)
        try:
            f = open(path, "rb")
        except OSError:
            return None
        with f:
            # fstat AFTER open: a rotation between a stat and the open
            # could otherwise pair old lineage with new bytes
            st = os.fstat(f.fileno())
            cur = self._files.get(key)
            fresh = (
                cur is None
                or st.st_ino != cur.ino
                or st.st_size < cur.offset
            )
            if (
                not fresh
                and st.st_size == cur.size
                and st.st_mtime_ns == cur.mtime_ns
            ):
                return None  # unchanged since last poll
            start = 0 if fresh else cur.offset
            f.seek(start)
            # bound the read to the fstat'ed size: bytes appended
            # after the fstat belong to the next poll's lineage.
            # Also cap the read: far behind a burst, the remainder
            # can be 100s of MB while the batch limit only lets one
            # poll deliver a few MB of lines — reading it all every
            # poll would make catch-up quadratic in the backlog.
            to_read = max(0, st.st_size - start)
            capped = to_read > _READ_CAP
            buf = f.read(_READ_CAP if capped else to_read)
        return st, fresh, start, buf, capped

    def _consume_object(
        self, key, st, buf, start, fresh, capped, remaining
    ) -> list[Event]:
        """Deliver one read buffer through the Event path and advance
        the file cursor (object poll mode, and the columnar mode's
        whole-chunk fallback)."""
        out: list[Event] = []
        consumed = 0
        truncated = capped
        # bulk fast path: hand every complete line in the buffer to
        # the native span scanner in one call (~an order of magnitude
        # cheaper than per-line Event.from_json — this is what keeps
        # seconds_behind bounded under a wire-speed ingest burst).
        # Bail to the per-line loop when the chunk carries tombstones
        # (the scanner has no $delete shape) or fails to parse.
        end = buf.rfind(b"\n") + 1
        chunk = buf[:end]
        parsed = None
        if chunk and b'"$delete"' not in chunk:
            if chunk.count(b"\n") > remaining:
                # trim to the remaining-limit'th newline; the rest of
                # the buffer is re-read on the next poll
                cut = -1
                for _ in range(remaining):
                    cut = chunk.find(b"\n", cut + 1)
                chunk = chunk[: cut + 1]
                truncated = True
            try:
                from predictionio_tpu.data.storage import colspans

                parsed = colspans.parse_events(chunk)
            except (ValueError, KeyError, UnicodeDecodeError) as err:
                logger.warning(
                    "tailer: bulk parse failed, falling back "
                    "per-line: %s",
                    err,
                )
                parsed = None
                truncated = capped
        if parsed is not None:
            consumed = len(chunk)
            for event in parsed:
                if (
                    fresh
                    and event.creation_time.timestamp()
                    <= self._watermark
                ):
                    continue
                if self._mark_seen(event):
                    out.append(event)
            self._finish_file(key, st, start + consumed, truncated)
            return out
        pos = 0
        while pos < len(buf):
            nl = buf.find(b"\n", pos)
            if nl < 0:
                break  # torn trailing line: wait for the newline
            if len(out) >= remaining:
                truncated = True
                break
            raw = buf[pos:nl]
            pos = nl + 1
            consumed = pos
            event = self._parse_line(raw)
            if event is None:
                continue
            if fresh and event.creation_time.timestamp() <= self._watermark:
                # rewrite resurfaced pre-attach history; not ours
                continue
            if self._mark_seen(event):
                out.append(event)
        self._finish_file(key, st, start + consumed, truncated)
        return out

    def _poll_files(self, limit: int) -> list[Event]:
        out: list[Event] = []
        for path in self._events.tail_files(self._app_id, self._channel_id):
            if len(out) >= limit:
                break
            read = self._read_file(path)
            if read is None:
                continue
            st, fresh, start, buf, capped = read
            out.extend(
                self._consume_object(
                    str(path), st, buf, start, fresh, capped,
                    limit - len(out),
                )
            )
        return out

    # -- columnar poll mode -------------------------------------------------

    def poll_columnar(self, limit: int | None = None) -> TailedBatch:
        """Like :meth:`poll`, but rate-shaped chunks decode straight to
        :class:`colspans.ColumnarTail` arrays (no per-line Event
        objects). Cursor, rotation, torn-line, and dedupe semantics are
        identical to :meth:`poll`; streams the classifier can't take
        fall back to the object path per chunk or per line. Modes other
        than "files" (and degraded no-native installs) deliver the
        plain object poll wrapped in a one-segment batch."""
        from predictionio_tpu import native

        limit = self._batch_limit if limit is None else int(limit)
        if (
            self.mode != "files"
            or self._columnar_config is None
            or not native.native_available()
        ):
            events = self.poll(limit)  # poll() persists the cursor
            return TailedBatch([events] if events else [])
        segments = self._poll_files_columnar(limit)
        self._save()
        return TailedBatch(segments)

    def _poll_files_columnar(self, limit: int) -> list:
        segments: list = []
        delivered = 0
        for path in self._events.tail_files(self._app_id, self._channel_id):
            if delivered >= limit:
                break
            read = self._read_file(path)
            if read is None:
                continue
            st, fresh, start, buf, capped = read
            key = str(path)
            remaining = limit - delivered
            if fresh:
                # broken lineage (rotation/compaction/attach): the re-read
                # from byte 0 needs watermark + per-event dedupe filtering,
                # which is exactly the object path's job
                segs = [
                    self._consume_object(
                        key, st, buf, start, True, capped, remaining
                    )
                ]
            else:
                segs = self._consume_columnar(
                    key, st, buf, start, capped, remaining
                )
            for seg in segs:
                n = _seg_len(seg)
                if n:
                    segments.append(seg)
                    delivered += n
        return segments

    def _consume_columnar(
        self, key, st, buf, start, capped, remaining
    ) -> list:
        """Deliver one read buffer through the span->array decoder.

        The complete-line prefix of the buffer goes to the decoder in
        one call; a chunk cut mid-line by the read cap hands only that
        clean prefix over and records an offset-only cursor for the
        remainder (no re-read of decoded bytes, no double-fold). Any
        decode failure — including an injected ``tail.decode`` fault —
        falls back to the object path for the whole chunk, counted in
        ``pio_tailer_columnar_fallback_lines_total``."""
        from predictionio_tpu.data.storage import colspans

        end = buf.rfind(b"\n") + 1
        chunk = buf[:end]
        truncated = capped
        if not chunk:
            # torn-only buffer: wait for the writer to finish the line
            self._finish_file(key, st, start, truncated)
            return []
        if b'"$delete"' in chunk:
            # tombstones have no rate shape; the object path skips them
            return [
                self._consume_object(
                    key, st, buf, start, False, capped, remaining
                )
            ]
        if chunk.count(b"\n") > remaining:
            cut = -1
            for _ in range(remaining):
                cut = chunk.find(b"\n", cut + 1)
            chunk = chunk[: cut + 1]
            truncated = True
        t0 = time.perf_counter()
        try:
            faults.fault_point("tail.decode")
            tail = colspans.decode_tail(chunk, self._columnar_config)
        except Exception as err:
            logger.warning(
                "tailer: columnar decode failed, falling back to the "
                "object path: %s", err,
            )
            _m_col_fallback.inc(chunk.count(b"\n"))
            return [
                self._consume_object(
                    key, st, buf, start, False, capped, remaining
                )
            ]
        # seen-id dedupe must stay sequential (an id can repeat within
        # one chunk — replacement events — and across polls after a
        # rotation re-read); rows without an id always deliver
        drop: list[int] = []
        for i, eid in enumerate(tail.event_ids):
            if eid is None:
                continue
            if eid in self._seen:
                drop.append(i)
            else:
                self._seen.add(eid)
        if drop:
            import numpy as np

            keep = np.ones(tail.n_rows, dtype=bool)
            keep[drop] = False
            tail = tail.select(keep)
        fb_events: list[Event] = []
        if len(tail.fallback_lines):
            # mixed stream: the classifier routed these line numbers to
            # the object parser ($set payloads, non-rate events, odd
            # syntax) — same per-line loop the object path runs
            lines = chunk.split(b"\n")
            for i in tail.fallback_lines:
                event = self._parse_line(lines[i])
                if event is None:
                    continue
                if self._mark_seen(event):
                    fb_events.append(event)
        t1 = time.perf_counter()
        _m_col_lines.inc(tail.n_rows)
        _m_col_fallback.inc(len(tail.fallback_lines))
        tr = obs_trace.current_trace()
        if tr is not None:
            tr.add_span("tail.decode", t0, t1)
        self._finish_file(key, st, start + len(chunk), truncated)
        out: list = [tail]
        if fb_events:
            out.append(fb_events)
        return out

    def _finish_file(self, key, st, new_offset: int, truncated: bool) -> None:
        if truncated:
            # stop mid-file: record the offset but NOT the stat, so
            # the next poll re-reads the remainder
            self._files[key] = _FileCursor(new_offset, st.st_ino, -1, -1)
        else:
            self._files[key] = _FileCursor(
                new_offset, st.st_ino, st.st_mtime_ns, st.st_size
            )
        self._dirty = True

    def _poll_seq(self, limit: int) -> list[Event]:
        got = self._events.tail_events(
            self._app_id, self._channel_id, after=self._seq, limit=limit
        )
        if got is None:  # capability vanished (shouldn't happen)
            return []
        events, cursor = got
        if cursor != self._seq:
            self._seq = cursor
            self._dirty = True
        out = [e for e in events if self._mark_seen(e)]
        if out:
            self._dirty = True
        return out

    def _poll_generic(self, limit: int) -> list[Event]:
        token = self._events.change_token(self._app_id, self._channel_id)
        if token is not None and token == self._token:
            return []
        out: list[Event] = []
        truncated = False
        for event in self._events.find(self._app_id, self._channel_id):
            if event.creation_time.timestamp() <= self._watermark:
                continue
            if not self._mark_seen(event):
                continue
            out.append(event)
            if len(out) >= limit:
                truncated = True
                break
        if not truncated:
            # only advance the token when the scan was complete —
            # otherwise the rest of the backlog would be skipped
            self._token = token
        if out:
            self._dirty = True
        return out

    # -- staleness ----------------------------------------------------------

    def events_behind(self) -> int | None:
        """Estimated undelivered events (upper bound: deletes and
        replaced records count too), or None when unknowable cheaply."""
        if self.mode == "files":
            behind = 0
            for path in self._events.tail_files(self._app_id, self._channel_id):
                cur = self._files.get(str(path))
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                if (
                    cur is not None
                    and st.st_ino == cur.ino
                    and st.st_size == cur.size
                    and st.st_mtime_ns == cur.mtime_ns
                ):
                    continue
                start = (
                    cur.offset
                    if cur is not None
                    and st.st_ino == cur.ino
                    and st.st_size >= cur.offset
                    else 0
                )
                try:
                    with open(path, "rb") as f:
                        f.seek(start)
                        behind += f.read(_BEHIND_SCAN_CAP).count(b"\n")
                except OSError:
                    continue
            return behind
        if self.mode == "seq":
            end = self._events.tail_end(self._app_id, self._channel_id)
            if isinstance(end, int) and isinstance(self._seq, int):
                return max(0, end - self._seq)
            return None  # float cursors (postgres) aren't countable
        token = self._events.change_token(self._app_id, self._channel_id)
        return 0 if token is not None and token == self._token else None
