"""Realtime speed layer: event tailing + incremental ALS fold-in.

The reference PredictionIO is a Lambda architecture — batch retrain plus
a speed layer where serving reflects events that arrived after the last
train. This package is that speed layer: :class:`EventTailer` follows an
event store incrementally with a durable cursor, :class:`ALSFoldIn`
solves touched user rows in closed form against the fixed item factors
(the ALX per-row least-squares primitive, arxiv 2112.02194), and
:class:`SpeedLayer` drives the loop against a deployed engine server,
hot-patching its model tables under an epoch fence so a full retrain +
``/reload`` always wins. See docs/realtime.md.
"""

from predictionio_tpu.realtime.foldin import ALSFoldIn, FoldInConfig, FoldInStats
from predictionio_tpu.realtime.speed_layer import SpeedLayer
from predictionio_tpu.realtime.tailer import EventTailer

__all__ = [
    "ALSFoldIn",
    "EventTailer",
    "FoldInConfig",
    "FoldInStats",
    "SpeedLayer",
]
