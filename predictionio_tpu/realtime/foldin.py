"""Incremental ALS fold-in: solve touched user rows against fixed items.

The ALX observation (arxiv 2112.02194): one ALS half-step already solves
every user row in closed form against the current item factors, and that
per-row least-squares is exactly the "fold a new/changed user in without
retraining" primitive. This module reuses the same jitted Gramian +
batched Cholesky path (:func:`ops.als.solve_bucket_explicit`, f32 solve
regardless of storage dtype) on the batch of users touched by tailed
rating events:

- each touched user's FULL rating history is re-read from the event
  store (the new events are already ingested there), so the solve is
  the exact half-step the next retrain would take for that row;
- item factors stay fixed — int8 tables are dequantized at gather time
  on device, exactly like training;
- solved rows are written back in the model's storage dtype: f32/bf16
  cast, or int8 requantized with a fresh per-row scale
  (:func:`ops.als.quantize_rows` semantics);
- brand-new users are appended to the factor table and the id index;
- events naming items unseen at train time can't be solved against (no
  factor row) — they accumulate in ``cold_items`` (count + rating sum)
  as cold-start stats for the next retrain to pick up.

The patched model SHARES the item arrays with the old model and never
mutates served state — the server swap is a pointer flip under its lock.
"""

from __future__ import annotations

import dataclasses
import logging

import numpy as np

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.models.recommendation import ALSModel
from predictionio_tpu.ops import als as als_ops

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class FoldInConfig:
    """Rating-extraction + solve parameters; must match the deployed
    engine's datasource/algorithm params so the fold-in solves the same
    problem the batch trainer does (SpeedLayer derives one from the
    server's EngineParams)."""

    event_names: tuple[str, ...] = ("rate", "buy")
    rating_key: str | None = "rating"
    default_ratings: dict | None = None
    override_ratings: dict | None = None
    entity_type: str = "user"
    target_entity_type: str = "item"
    reg: float = 0.01
    weighted_reg: bool = True


@dataclasses.dataclass
class FoldInStats:
    """What one fold() call did."""

    events: int = 0
    rating_events: int = 0
    users_touched: int = 0
    users_added: int = 0
    users_skipped: int = 0  # touched but no trainable pairs
    cold_item_events: int = 0


def _pow2(n: int, floor: int = 1) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


class ALSFoldIn:
    """Folds batches of rating events into an ALSModel's user table."""

    def __init__(
        self,
        events,
        app_id: int,
        channel_id: int | None = None,
        config: FoldInConfig | None = None,
    ):
        self._events = events
        self._app_id = app_id
        self._channel_id = channel_id
        self.config = config or FoldInConfig()
        # item id -> [event count, rating sum]; unseen-at-train items
        self.cold_items: dict[str, list] = {}
        # device copy of the item table, keyed by the identity of the
        # host array so a /reload (new model object) invalidates it
        self._item_dev = None
        self._item_dev_key = None

    # -- rating extraction (mirrors base.Events.scan_ratings) ---------------

    def _rating_of(self, e: Event) -> float | None:
        cfg = self.config
        if e.event not in cfg.event_names:
            return None
        if e.entity_type != cfg.entity_type:
            return None
        if e.target_entity_type != cfg.target_entity_type:
            return None
        if e.target_entity_id is None:
            return None
        v = (cfg.override_ratings or {}).get(e.event)
        if v is None:
            v = (
                e.properties.to_dict().get(cfg.rating_key)
                if cfg.rating_key is not None
                else None
            )
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                v = (cfg.default_ratings or {}).get(e.event)
        if v is None:
            return None
        return float(v)

    # -- history reads ------------------------------------------------------

    def _histories(self, touched: list[str]) -> dict[str, list[Event]]:
        """Full rating-event history per touched user, including the
        events that triggered this fold (they are already ingested)."""
        cfg = self.config
        out: dict[str, list[Event]] = {u: [] for u in touched}
        if getattr(self._events, "entity_indexed", False):
            for uid in touched:
                out[uid] = self._events.find(
                    self._app_id,
                    self._channel_id,
                    entity_type=cfg.entity_type,
                    entity_id=uid,
                    event_names=list(cfg.event_names),
                    target_entity_type=cfg.target_entity_type,
                )
            return out
        # replay backends: one bulk scan amortizes across the batch
        touched_set = set(touched)
        for e in self._events.find(
            self._app_id,
            self._channel_id,
            entity_type=cfg.entity_type,
            event_names=list(cfg.event_names),
            target_entity_type=cfg.target_entity_type,
        ):
            if e.entity_id in touched_set:
                out[e.entity_id].append(e)
        return out

    # -- the fold -----------------------------------------------------------

    def fold(
        self, model: ALSModel, events: list[Event]
    ) -> tuple[ALSModel | None, FoldInStats]:
        """Fold a batch of tailed events into ``model``.

        Returns ``(patched_model, stats)`` — patched_model is ``None``
        when the batch contained nothing foldable (stats says why). The
        input model is never mutated."""
        stats = FoldInStats(events=len(events))
        touched: list[str] = []
        touched_set: set[str] = set()
        self._collect_events(model, events, stats, touched, touched_set)
        if not touched:
            return None, stats
        return self._fold_touched(model, touched, stats)

    def fold_in_columnar(
        self, model: ALSModel, batch
    ) -> tuple[ALSModel | None, FoldInStats]:
        """Fold one :class:`~realtime.tailer.TailedBatch` — columnar
        array segments and object-path Event segments, in delivery
        order — without constructing an Event for any columnar row.

        The columnar rows were already shape-classified by the decoder
        (``colspans.decode_tail`` keeps exactly what :meth:`_rating_of`
        would accept), so collection reduces to touched-user and
        cold-item accumulation over arrays; the solve and patch are the
        same jitted path :meth:`fold` takes, hence bit-identical
        results across f32/bf16/int8 storage."""
        stats = FoldInStats(events=batch.n_events)
        touched: list[str] = []
        touched_set: set[str] = set()
        for seg in batch.segments:
            if isinstance(seg, list):
                self._collect_events(model, seg, stats, touched, touched_set)
            else:
                self._collect_columnar(model, seg, stats, touched, touched_set)
        if not touched:
            return None, stats
        return self._fold_touched(model, touched, stats)

    def _collect_events(
        self, model, events, stats, touched, touched_set
    ) -> None:
        for e in events:
            v = self._rating_of(e)
            if v is None:
                continue
            stats.rating_events += 1
            if e.target_entity_id not in model.item_index:
                acc = self.cold_items.setdefault(e.target_entity_id, [0, 0.0])
                acc[0] += 1
                acc[1] += v
                stats.cold_item_events += 1
            if e.entity_id not in touched_set:
                touched_set.add(e.entity_id)
                touched.append(e.entity_id)

    def _collect_columnar(
        self, model, tail, stats, touched, touched_set
    ) -> None:
        n = tail.n_rows
        if n == 0:
            return
        stats.rating_events += n
        # cold-item accumulation, vectorized per distinct item (the
        # per-event loop's counts/sums, bincount-shaped)
        counts = np.bincount(tail.item_idx, minlength=len(tail.item_ids))
        sums = np.bincount(
            tail.item_idx, weights=tail.ratings,
            minlength=len(tail.item_ids),
        )
        for j, iid in enumerate(tail.item_ids):
            if iid in model.item_index:
                continue
            acc = self.cold_items.setdefault(iid, [0, 0.0])
            acc[0] += int(counts[j])
            acc[1] += float(sums[j])
            stats.cold_item_events += int(counts[j])
        for uid in tail.user_ids:  # first-appearance order, like events
            if uid not in touched_set:
                touched_set.add(uid)
                touched.append(uid)

    def _fold_touched(
        self, model: ALSModel, touched: list[str], stats: FoldInStats
    ) -> tuple[ALSModel | None, FoldInStats]:
        """History re-read + solve + patch for the touched users (the
        shared tail of :meth:`fold` and :meth:`fold_in_columnar` — the
        solve is exact against full histories, so results can't depend
        on which decode path delivered the triggering events)."""
        histories = self._histories(touched)
        users: list[str] = []
        pairs: list[list[tuple[int, float]]] = []
        for uid in touched:
            seen: dict[int, float] = {}
            for e in histories.get(uid, ()):
                v = self._rating_of(e)
                if v is None:
                    continue
                ix = model.item_index.get(e.target_entity_id)
                if ix is None:
                    continue  # cold item: no factor row to solve against
                seen[ix] = v  # replay order: last write wins
            if not seen:
                stats.users_skipped += 1
                continue
            users.append(uid)
            pairs.append(list(seen.items()))
        stats.users_touched = len(users)
        if not users:
            return None, stats

        solved = self._solve(model, pairs)
        patched = self._patch(model, users, solved, stats)
        return patched, stats

    def _solve(self, model: ALSModel, pairs) -> np.ndarray:
        """Closed-form f32 solve of the touched rows, padded to stable
        (B, K) program shapes so repeat folds reuse the jit cache."""
        import jax.numpy as jnp

        B = _pow2(len(pairs))
        K = _pow2(max(len(p) for p in pairs), floor=8)
        col_ids = np.zeros((B, K), dtype=np.int32)
        ratings = np.zeros((B, K), dtype=np.float32)
        mask = np.zeros((B, K), dtype=np.float32)
        for i, p in enumerate(pairs):
            for j, (ix, v) in enumerate(p):
                col_ids[i, j] = ix
                ratings[i, j] = v
                mask[i, j] = 1.0
        item_host = model.item_table()
        key = id(
            item_host[0] if isinstance(item_host, tuple) else item_host
        )
        if self._item_dev_key != key:
            if isinstance(item_host, tuple):
                self._item_dev = (
                    jnp.asarray(item_host[0]),
                    jnp.asarray(item_host[1]),
                )
            else:
                self._item_dev = jnp.asarray(item_host)
            self._item_dev_key = key
        x = als_ops.solve_bucket_explicit(
            self._item_dev,
            jnp.asarray(col_ids),
            jnp.asarray(ratings),
            jnp.asarray(mask),
            reg=self.config.reg,
            weighted_reg=self.config.weighted_reg,
            compute_dtype="float32",
        )
        return np.asarray(x)[: len(pairs)]

    def _patch(
        self,
        model: ALSModel,
        users: list[str],
        solved: np.ndarray,
        stats: FoldInStats,
    ) -> ALSModel:
        """New ALSModel with the solved rows written back (appending
        brand-new users); item arrays are shared, nothing is mutated."""
        index = model.user_index.to_dict()
        n_existing = len(index)
        new_ids = [u for u in users if u not in index]
        for uid in new_ids:
            index[uid] = len(index)
        stats.users_added = len(new_ids)
        user_index = (
            model.user_index if not new_ids else BiMap(index)
        )

        uf = model.user_factors
        if model.user_scales is not None:
            # int8 storage: requantize each solved row with a fresh
            # per-row scale (quantize_rows semantics, host-side)
            sc = np.max(np.abs(solved), axis=1) / 127.0
            sc[sc <= 0] = 1.0
            q = np.round(solved / sc[:, None]).astype(np.int8)
            values = np.concatenate(
                [uf, np.zeros((len(new_ids), uf.shape[1]), dtype=uf.dtype)]
            )
            scales = np.concatenate(
                [
                    model.user_scales,
                    np.ones(len(new_ids), dtype=model.user_scales.dtype),
                ]
            )
            for i, uid in enumerate(users):
                ix = index[uid]
                values[ix] = q[i]
                scales[ix] = sc[i]
            return ALSModel(
                user_index=user_index,
                item_index=model.item_index,
                user_factors=values,
                item_factors=model.item_factors,
                user_scales=scales,
                item_scales=model.item_scales,
            )
        values = np.concatenate(
            [uf, np.zeros((len(new_ids), uf.shape[1]), dtype=uf.dtype)]
        )
        rows = solved.astype(uf.dtype)
        for i, uid in enumerate(users):
            values[index[uid]] = rows[i]
        if n_existing == 0 and not new_ids:  # pragma: no cover - guard
            raise AssertionError("patch with no rows")
        return ALSModel(
            user_index=user_index,
            item_index=model.item_index,
            user_factors=values,
            item_factors=model.item_factors,
            user_scales=None,
            item_scales=model.item_scales,
        )

    def cold_start_stats(self) -> dict[str, dict]:
        """Accumulated unseen-item stats: id -> {events, mean_rating}."""
        return {
            iid: {"events": c, "mean_rating": s / c if c else 0.0}
            for iid, (c, s) in self.cold_items.items()
        }
