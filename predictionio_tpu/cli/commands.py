"""CLI command implementations (reference tools/.../commands/*.scala).

The ``pio`` verbs call these; the admin server reuses the app commands
(reference admin/CommandClient.scala wraps the same logic).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import (
    AccessKey,
    App,
    Channel,
    Storage,
    get_storage,
)

logger = logging.getLogger(__name__)


class CommandError(RuntimeError):
    pass


# -- app commands (commands/App.scala) --------------------------------------


def app_new(
    name: str,
    app_id: int = 0,
    description: str | None = None,
    access_key: str = "",
    storage: Storage | None = None,
) -> dict[str, Any]:
    storage = storage or get_storage()
    apps = storage.get_metadata_apps()
    if apps.get_by_name(name) is not None:
        raise CommandError(f"App {name} already exists. Aborting.")
    new_id = apps.insert(App(app_id, name, description))
    if new_id is None:
        raise CommandError(f"Unable to create new app (id {app_id} taken?).")
    storage.get_events().init(new_id)
    key = storage.get_metadata_access_keys().insert(
        AccessKey(access_key, appid=new_id)
    )
    if key is None:
        raise CommandError("Unable to create new access key.")
    return {"id": new_id, "name": name, "access_key": key}


def app_list(storage: Storage | None = None) -> list[dict[str, Any]]:
    storage = storage or get_storage()
    keys = storage.get_metadata_access_keys()
    out = []
    for app in storage.get_metadata_apps().get_all():
        app_keys = keys.get_by_appid(app.id)
        out.append(
            {
                "id": app.id,
                "name": app.name,
                "description": app.description,
                "access_key": app_keys[0].key if app_keys else "",
            }
        )
    return out


def app_show(name: str, storage: Storage | None = None) -> dict[str, Any]:
    storage = storage or get_storage()
    app = storage.get_metadata_apps().get_by_name(name)
    if app is None:
        raise CommandError(f"App {name} does not exist. Aborting.")
    keys = storage.get_metadata_access_keys().get_by_appid(app.id)
    channels = storage.get_metadata_channels().get_by_appid(app.id)
    return {
        "id": app.id,
        "name": app.name,
        "description": app.description,
        "access_keys": [{"key": k.key, "events": k.events} for k in keys],
        "channels": [{"id": c.id, "name": c.name} for c in channels],
    }


def app_delete(name: str, storage: Storage | None = None) -> None:
    storage = storage or get_storage()
    app = storage.get_metadata_apps().get_by_name(name)
    if app is None:
        raise CommandError(f"App {name} does not exist. Aborting.")
    events = storage.get_events()
    for ch in storage.get_metadata_channels().get_by_appid(app.id):
        events.remove(app.id, ch.id)
        storage.get_metadata_channels().delete(ch.id)
    events.remove(app.id)
    for key in storage.get_metadata_access_keys().get_by_appid(app.id):
        storage.get_metadata_access_keys().delete(key.key)
    storage.get_metadata_apps().delete(app.id)


def app_data_delete(
    name: str, channel: str | None = None, storage: Storage | None = None
) -> None:
    storage = storage or get_storage()
    app = storage.get_metadata_apps().get_by_name(name)
    if app is None:
        raise CommandError(f"App {name} does not exist. Aborting.")
    events = storage.get_events()
    if channel is None:
        events.remove(app.id)
        events.init(app.id)
        return
    chans = [
        c
        for c in storage.get_metadata_channels().get_by_appid(app.id)
        if c.name == channel
    ]
    if not chans:
        raise CommandError(f"Channel {channel} does not exist. Aborting.")
    events.remove(app.id, chans[0].id)
    events.init(app.id, chans[0].id)


def channel_new(
    app_name: str, channel_name: str, storage: Storage | None = None
) -> dict[str, Any]:
    storage = storage or get_storage()
    app = storage.get_metadata_apps().get_by_name(app_name)
    if app is None:
        raise CommandError(f"App {app_name} does not exist. Aborting.")
    if not Channel.is_valid_name(channel_name):
        raise CommandError(
            f"Unable to create new channel. The channel name {channel_name} is "
            "invalid (1-16 alphanumeric or '-' characters)."
        )
    channel_id = storage.get_metadata_channels().insert(
        Channel(0, channel_name, app.id)
    )
    if channel_id is None:
        raise CommandError(f"Channel {channel_name} already exists. Aborting.")
    storage.get_events().init(app.id, channel_id)
    return {"id": channel_id, "name": channel_name, "app_id": app.id}


def channel_delete(
    app_name: str, channel_name: str, storage: Storage | None = None
) -> None:
    storage = storage or get_storage()
    app = storage.get_metadata_apps().get_by_name(app_name)
    if app is None:
        raise CommandError(f"App {app_name} does not exist. Aborting.")
    chans = [
        c
        for c in storage.get_metadata_channels().get_by_appid(app.id)
        if c.name == channel_name
    ]
    if not chans:
        raise CommandError(f"Channel {channel_name} does not exist. Aborting.")
    storage.get_events().remove(app.id, chans[0].id)
    storage.get_metadata_channels().delete(chans[0].id)


# -- access key commands (commands/AccessKey.scala) -------------------------


def accesskey_new(
    app_name: str,
    key: str = "",
    events: list[str] | None = None,
    storage: Storage | None = None,
) -> str:
    storage = storage or get_storage()
    app = storage.get_metadata_apps().get_by_name(app_name)
    if app is None:
        raise CommandError(f"App {app_name} does not exist. Aborting.")
    created = storage.get_metadata_access_keys().insert(
        AccessKey(key, appid=app.id, events=list(events or []))
    )
    if created is None:
        raise CommandError("Unable to create new access key.")
    return created


def accesskey_list(
    app_name: str | None = None, storage: Storage | None = None
) -> list[dict[str, Any]]:
    storage = storage or get_storage()
    keys = storage.get_metadata_access_keys()
    if app_name is None:
        all_keys = keys.get_all()
    else:
        app = storage.get_metadata_apps().get_by_name(app_name)
        if app is None:
            raise CommandError(f"App {app_name} does not exist. Aborting.")
        all_keys = keys.get_by_appid(app.id)
    return [{"key": k.key, "app_id": k.appid, "events": k.events} for k in all_keys]


def accesskey_delete(key: str, storage: Storage | None = None) -> None:
    storage = storage or get_storage()
    if not storage.get_metadata_access_keys().delete(key):
        raise CommandError(f"Access key {key} does not exist. Aborting.")


def _resolve_app_name(appid_or_name: str, storage: Storage) -> str:
    """Accept an app name or a numeric app id (reference --appid flag)."""
    apps = storage.get_metadata_apps()
    if apps.get_by_name(appid_or_name) is not None:
        return appid_or_name
    if appid_or_name.isdigit():
        app = apps.get(int(appid_or_name))
        if app is not None:
            return app.name
    raise CommandError(f"App {appid_or_name} does not exist. Aborting.")


# -- export / import (tools/export/EventsToFile.scala, imprt/FileToEvents) --


def export_events(
    app_name: str,
    output_path: str,
    channel: str | None = None,
    storage: Storage | None = None,
) -> int:
    """Dump an app's events as JSON-lines (one event per line).

    Backends whose storage format is already the wire format (jsonl,
    partitioned) stream their replay-clean logs verbatim
    (``export_jsonl`` — no per-event Python objects, the inverse of the
    import splice); others serialize through the Event model."""
    from predictionio_tpu.data import store

    storage = storage or get_storage()
    app_name = _resolve_app_name(app_name, storage)
    events_dao = storage.get_events()
    fast = getattr(events_dao, "export_jsonl", None)
    if fast is not None:
        app_id, channel_id = store.app_name_to_id(app_name, channel, storage)
        with open(output_path, "wb") as f:
            n = fast(app_id, channel_id, f)
        if n is not None:
            return n
        # capability probe said no (http backend whose backing store
        # can't splice-export): fall through to the per-event path
    events = store.find(app_name, channel_name=channel, storage=storage)
    with open(output_path, "w") as f:
        for e in events:
            f.write(json.dumps(e.to_dict(for_api=False), sort_keys=True) + "\n")
    return len(events)


def _positions_in_spans(chunk: bytes, pattern: bytes, starts, ends):
    """Boolean per-span mask: does any occurrence of ``pattern`` in
    ``chunk`` fall inside [starts, ends)? Vectorized via one global find
    pass + searchsorted (occurrences are rare; spans are many)."""
    import numpy as np

    hits = []
    pos = chunk.find(pattern)
    while pos >= 0:
        hits.append(pos)
        pos = chunk.find(pattern, pos + 1)
    if not hits:
        return np.zeros(len(starts), dtype=bool)
    hp = np.asarray(hits, dtype=np.int64)
    return np.searchsorted(hp, starts) < np.searchsorted(hp, ends)


def _splice_import_chunk(chunk: bytes, now_iso: str):
    """Validated splice-through for one line-aligned JSONL chunk.

    The import wire format and the jsonl storage format are the same, so
    a line that passes the (vectorized, span-level) validation rules of
    ``data.event.validate`` can be appended verbatim with eventId /
    creationTime spliced in — no Event object, no re-serialization.
    Returns (blob_to_append: bytes, fallback_lines: list[bytes]); lines
    that fail any cheap check take the full parse+validate path instead.
    """
    import binascii

    import numpy as np

    from predictionio_tpu import native

    sc = native.scan_events(chunk)
    n = len(sc)
    a8 = np.frombuffer(chunk, dtype=np.uint8)
    # line spans (scanner counts lines the same way: split on \n)
    nl = np.flatnonzero(a8 == 0x0A)
    starts = np.concatenate([[0], nl + 1])[:n]
    ends = np.concatenate([nl, [len(chunk)]])[:n]

    offs, lens = sc.offs, sc.lens

    def first_byte(field):
        o = offs[:, field]
        return np.where(o >= 0, a8[np.clip(o, 0, len(a8) - 1)], 0)

    def has_prefix(field, prefix: bytes):
        """span starts with prefix (False where absent/short)."""
        o, ln = offs[:, field], lens[:, field]
        ok = (o >= 0) & (ln >= len(prefix))
        out = ok.copy()
        for j, byte in enumerate(prefix):
            out &= np.where(
                ok, a8[np.clip(o + j, 0, len(a8) - 1)] == byte, False
            )
        return out

    ok = sc.flags == 0
    # any "$delete" byte sequence anywhere in the line punts to the slow
    # path: appended verbatim, a top-level {"$delete": id} key would act
    # as a jsonl delete MARKER on replay — deleting an attacker-chosen
    # existing event. The slow path's Event.from_dict drops unknown keys.
    ok &= ~_positions_in_spans(chunk, b'"$delete"', starts, ends)
    ok &= (offs[:, native.F_EVENT] >= 0) & (lens[:, native.F_EVENT] > 0)
    ok &= (offs[:, native.F_ENTITY_TYPE] >= 0) & (lens[:, native.F_ENTITY_TYPE] > 0)
    ok &= (offs[:, native.F_ENTITY_ID] >= 0) & (lens[:, native.F_ENTITY_ID] > 0)
    # reserved names: any $-event or pio_ prefix goes to the slow path
    # (full validate decides builtin vs illegal)
    ok &= first_byte(native.F_EVENT) != ord("$")
    ok &= ~has_prefix(native.F_EVENT, b"pio_")
    ok &= ~has_prefix(native.F_ENTITY_TYPE, b"pio_")
    ok &= ~has_prefix(native.F_TARGET_ENTITY_TYPE, b"pio_")
    # target type/id specified together, both non-empty when present
    t_type, t_id = offs[:, native.F_TARGET_ENTITY_TYPE], offs[:, native.F_TARGET_ENTITY_ID]
    ok &= (t_type >= 0) == (t_id >= 0)
    ok &= (t_type < 0) | (lens[:, native.F_TARGET_ENTITY_TYPE] > 0)
    ok &= (t_id < 0) | (lens[:, native.F_TARGET_ENTITY_ID] > 0)
    # eventTime must be on the wire AND parseable — an unparseable time
    # appended verbatim would poison every later read of the log
    ok &= offs[:, native.F_EVENT_TIME] >= 0
    ok &= ~np.isnan(
        native.parse_times(
            chunk, offs[:, native.F_EVENT_TIME], lens[:, native.F_EVENT_TIME]
        )
    )
    ct_present = offs[:, native.F_CREATION_TIME] >= 0
    ok &= ~ct_present | ~np.isnan(
        native.parse_times(
            chunk, offs[:, native.F_CREATION_TIME], lens[:, native.F_CREATION_TIME]
        )
    )
    # property keys may not use the pio_/$ reserved prefixes; a cheap
    # conservative substring test sends suspects to the full validator.
    # Any backslash in the properties span also punts to the validator:
    # JSON escapes (pio_x) could smuggle a reserved key past a raw
    # byte test
    p_off, p_len = offs[:, native.F_PROPERTIES], lens[:, native.F_PROPERTIES]
    p_start = np.where(p_off >= 0, p_off, 0).astype(np.int64)
    p_end = p_start + np.where(p_off >= 0, p_len, 0)
    suspicious = _positions_in_spans(chunk, b'"pio_', p_start, p_end)
    suspicious |= _positions_in_spans(chunk, b'"$', p_start, p_end)
    suspicious |= _positions_in_spans(chunk, b"\\", p_start, p_end)
    ok &= ~((p_off >= 0) & suspicious)

    ok_ix = np.flatnonzero(ok)
    # pre-generate random hex event ids for lines that lack one
    need_id = offs[ok_ix, native.F_EVENT_ID] < 0
    hexpool = binascii.hexlify(np.random.default_rng().bytes(16 * int(need_id.sum())))
    ct_suffix = (',"creationTime":"%s"' % now_iso).encode()
    fallback = [
        chunk[starts[i] : ends[i]]
        for i in np.flatnonzero(~ok & (sc.flags & native.FLAG_EMPTY == 0))
    ]
    # assemble the blob in one native pass (the per-line Python loop was
    # ~40% of import wall-clock at 2M events); falls back to the loop in
    # degraded no-native mode
    need_ct = offs[ok_ix, native.F_CREATION_TIME] < 0
    blob = native.splice_lines(
        chunk, starts[ok_ix], ends[ok_ix], need_id, need_ct,
        bytes(hexpool), ct_suffix,
    )
    if blob is not None:
        return blob, len(ok_ix), fallback
    out: list[bytes] = []
    id_i = 0
    for row, wants_id in zip(ok_ix, need_id):
        line = chunk[starts[row] : ends[row]].rstrip()
        tail = b""
        if wants_id:
            eid = hexpool[32 * id_i : 32 * id_i + 32]
            id_i += 1
            tail += b',"eventId":"' + eid + b'"'
        if offs[row, native.F_CREATION_TIME] < 0:
            tail += ct_suffix
        out.append(line[:-1] + tail + b"}" if tail else line)
    return b"\n".join(out), len(out), fallback


def import_events(
    app_name: str,
    input_path: str,
    channel: str | None = None,
    storage: Storage | None = None,
    jobs: int | None = None,
) -> int:
    import threading
    from concurrent.futures import ThreadPoolExecutor
    from datetime import datetime, timezone

    from predictionio_tpu.data import store
    from predictionio_tpu.data.event import validate
    from predictionio_tpu.data.storage import colspans

    storage = storage or get_storage()
    app_name = _resolve_app_name(app_name, storage)
    app_id, channel_id = store.app_name_to_id(app_name, channel, storage)
    events_dao = storage.get_events()
    # jsonl backends take the splice-through path: wire format == storage
    # format, so validated lines append verbatim (no Event round trip) —
    # the 10^7-events/minute bulk-load path (reference FileToEvents runs
    # this load as a Spark job, tools/.../imprt/FileToEvents.scala:34-106)
    # The dict holds it so the pooled workers below can demote to the
    # slow path exactly once, without a shared nonlocal rebind race.
    splice = {"fn": getattr(events_dao, "append_jsonl", None)}
    now_iso = (
        datetime.now(timezone.utc).isoformat(timespec="milliseconds")
        .replace("+00:00", "Z")
    )
    if jobs is None:
        jobs = int(os.environ.get("PIO_IMPORT_JOBS", "0") or 0)
    if jobs <= 0:
        # the chunk pipeline overlaps native splice parse (GIL released)
        # with the storage appends' fsyncs; past a few workers the disk
        # is the bottleneck, so the default stays modest
        jobs = min(4, os.cpu_count() or 1)

    def _flush_slow(data: bytes | list[bytes]) -> int:
        if isinstance(data, list):
            data = b"\n".join(data)
        # shared span-scanning decoder (data/storage/colspans.py — the
        # same one under the columnar cache and the tail path) decodes
        # the fixed wire fields without a per-line DOM parse (json
        # fallback for flagged lines inside)
        events = colspans.parse_events(data)
        done = 0
        for start in range(0, len(events), 500):
            batch = events[start : start + 500]
            for event in batch:
                validate(event)
            events_dao.batch_insert(batch, app_id, channel_id)
            done += len(batch)
        return done

    def _flush(data: bytes) -> int:
        fn = splice["fn"]
        if fn is None:
            return _flush_slow(data)
        done = 0
        blob, n_spliced, fallback = _splice_import_chunk(data, now_iso)
        if blob:
            try:
                fn(blob, app_id, channel_id)
                done += n_spliced
            except NotImplementedError:
                # http backend whose storage service can't splice:
                # degrade to per-event inserts for the rest of the run
                splice["fn"] = None
                done += _flush_slow(blob)
        if fallback:
            done += _flush_slow(fallback)
        return done

    # stream line-aligned chunks so peak memory stays bounded for
    # multi-GB event files; with jobs > 1 the chunks decode + append on
    # a thread pool (append order across chunks is immaterial: replay is
    # last-write-wins per event id and import lines carry unique ids),
    # with in-flight submissions bounded so a fast reader can't buffer
    # the whole file
    chunk_size = 8 << 20
    carry = b""
    futures: list = []
    inflight = threading.BoundedSemaphore(jobs * 2)

    def _run(data: bytes) -> int:
        try:
            return _flush(data)
        finally:
            inflight.release()

    def _submit(pool, data: bytes) -> None:
        if pool is None:
            futures.append(_flush(data))
        else:
            inflight.acquire()
            futures.append(pool.submit(_run, data))

    pool = ThreadPoolExecutor(max_workers=jobs) if jobs > 1 else None
    try:
        with open(input_path, "rb") as f:
            while True:
                chunk = f.read(chunk_size)
                if not chunk:
                    break
                chunk = carry + chunk
                cut = chunk.rfind(b"\n")
                if cut < 0:
                    carry = chunk
                    continue
                carry = chunk[cut + 1 :]
                _submit(pool, chunk[: cut + 1])
        if carry.strip():
            _submit(pool, carry)
        return sum(
            f if isinstance(f, int) else f.result() for f in futures
        )
    finally:
        if pool is not None:
            pool.shutdown(wait=True)


def import_events_http(
    input_path: str,
    url: str,
    access_key: str,
    channel: str | None = None,
    frame_events: int = 2000,
) -> int:
    """Bulk import over the wire-speed binary endpoint: stream the
    jsonl file in line-aligned chunks, pack each chunk into PIF1 frames
    (data/storage/frame.py) and POST them to ``/batch/events.bin`` on a
    keep-alive connection. 429 ``IngestBackpressure`` answers are
    retried after ``Retry-After``; connection drops reconnect and
    resend (exported lines carry event ids, so a resend that overlaps a
    partially committed request replays idempotently). Every request
    carries one ``X-PIO-Trace`` id minted for the import run, so the
    server-side trace ring stitches the whole bulk ingest into one
    client-correlatable trace family (``GET /traces.json``)."""
    import http.client as _hc
    import time as _time
    from urllib.parse import quote, urlsplit

    from predictionio_tpu.data.storage import frame

    parts = urlsplit(url)
    if parts.scheme not in ("", "http"):
        raise CommandError(
            f"import --http supports http:// URLs only, got {url!r}"
        )
    host = parts.hostname or "127.0.0.1"
    port = parts.port or 7070
    path = "/batch/events.bin?accessKey=" + quote(access_key)
    if channel:
        path += "&channel=" + quote(channel)
    from predictionio_tpu.obs import trace as obs_trace

    headers = {
        "Content-Type": "application/octet-stream",
        obs_trace.TRACE_HEADER: obs_trace.new_trace_id(),
    }

    conn = _hc.HTTPConnection(host, port, timeout=60)
    total = 0
    skipped = 0

    def _post(body: bytes) -> None:
        nonlocal conn, total
        for attempt in range(8):
            try:
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
            except (OSError, _hc.HTTPException):
                conn.close()
                conn = _hc.HTTPConnection(host, port, timeout=60)
                if attempt == 7:
                    raise
                continue
            if resp.status == 429:
                try:
                    delay = float(resp.getheader("Retry-After") or 1.0)
                except ValueError:
                    delay = 1.0
                _time.sleep(min(delay, 5.0))
                continue
            if resp.status != 200:
                raise CommandError(
                    f"import --http: server answered {resp.status}: "
                    f"{payload[:200]!r}"
                )
            total += int(json.loads(payload).get("accepted", 0))
            return
        raise CommandError(
            "import --http: gave up after repeated backpressure"
        )

    def _send_chunk(data: bytes) -> None:
        nonlocal skipped
        events = []
        for line in data.split(b"\n"):
            line = line.strip()
            if not line:
                continue
            if line.startswith(b'{"$delete"'):
                skipped += 1  # tombstones are storage-internal
                continue
            events.append(json.loads(line))
        if events:
            _post(frame.encode_body(events, frame_events=frame_events))

    chunk_size = 8 << 20
    carry = b""
    try:
        with open(input_path, "rb") as f:
            while True:
                chunk = f.read(chunk_size)
                if not chunk:
                    break
                chunk = carry + chunk
                cut = chunk.rfind(b"\n")
                if cut < 0:
                    carry = chunk
                    continue
                carry = chunk[cut + 1 :]
                _send_chunk(chunk[: cut + 1])
        if carry.strip():
            _send_chunk(carry)
    finally:
        conn.close()
    if skipped:
        logger.warning(
            "import --http: skipped %d $delete tombstone lines", skipped
        )
    return total


# -- status (commands/Management.scala:56-160) ------------------------------


def status(storage: Storage | None = None) -> dict[str, Any]:
    storage = storage or get_storage()
    storage.verify_all_data_objects()
    import jax

    repos = {}
    for repo in ("METADATA", "EVENTDATA", "MODELDATA"):
        name, typ = storage.repository_source(repo)
        repos[repo] = {"source": name, "type": typ}
    return {
        "storage": repos,
        "devices": [str(d) for d in jax.devices()],
        "default_backend": jax.default_backend(),
    }
