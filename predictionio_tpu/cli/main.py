"""`pio` command-line interface.

Capability parity with the reference console
(tools/.../console/Console.scala:37-768): the full verb set — app /
accesskey / channel management, train, deploy, undeploy, eval,
eventserver, adminserver, dashboard, export, import, status, version,
build (a no-op syntax check here: Python engines need no sbt assembly).

The reference forks spark-submit JVMs per verb (Runner.scala:185-308);
here drivers run in-process — the process boundary that mattered (CLI vs
long-running servers) is kept: ``deploy``/``eventserver`` stay in the
foreground unless backgrounded by the caller.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from predictionio_tpu import __version__


def _enter_engine_dir(args) -> None:
    """``--engine-dir DIR`` (default: the cwd): run as if launched from
    an engine template directory — its ``engine.json`` becomes the
    default variant, and the directory holding the variant file joins
    ``sys.path`` so a local template package imports (the reference
    CLI's run-from-template-dir workflow; Console.scala resolves
    engine.json relative to the working directory). Idempotent: safe to
    call from any command prologue."""
    if getattr(args, "_engine_dir_entered", False):
        return
    args._engine_dir_entered = True
    engine_dir = os.path.abspath(
        getattr(args, "engine_dir", None) or os.getcwd()
    )
    if not getattr(args, "variant", None):
        candidate = os.path.join(engine_dir, "engine.json")
        if os.path.exists(candidate):
            args.variant = candidate
    # a console-script entry point has no cwd on sys.path: the directory
    # holding the variant file IS the engine dir, and its local template
    # package must import no matter how the variant was named (bare cwd
    # pickup, --engine-dir, or explicit --variant — including daemon
    # children that only receive --variant)
    dirs = []
    if getattr(args, "engine_dir", None):
        dirs.append(engine_dir)
    if getattr(args, "variant", None):
        dirs.append(os.path.dirname(os.path.abspath(args.variant)))
    for d in dirs:
        if d not in sys.path:
            sys.path.insert(0, d)


def _variant_label(args) -> str:
    """The engine-instance variant label: the variant FILE NAME, so
    `pio train --engine-dir d` and `cd d && pio deploy` agree on the
    label regardless of how the path was spelled."""
    return (
        os.path.basename(getattr(args, "variant", None) or "") or "default"
    )


def _engine_identity(args, variant: dict) -> tuple[str, str, str]:
    """(engine_id, version, variant label) — the instance lookup key.

    A variant without an ``id`` field falls back to the real path of its
    directory, so two different id-less engines never collide on the
    (default, 0, engine.json) key while the same engine resolves
    identically from every invocation style."""
    engine_id = variant.get("id")
    if not engine_id:
        v = getattr(args, "variant", None)
        engine_id = (
            os.path.dirname(os.path.realpath(v)) if v else "default"
        )
    return engine_id, variant.get("version", "0"), _variant_label(args)


def _engine_from_args(args) -> tuple:
    """Resolve (engine, variant dict, factory name) from --engine-factory /
    --variant (engine.json; defaults to ./engine.json like the
    reference) / --engine-dir."""
    from predictionio_tpu.core.engine import resolve_engine_factory
    from predictionio_tpu.core.workflow import load_variant

    _enter_engine_dir(args)
    variant: dict = {}
    if getattr(args, "variant", None):
        variant = load_variant(args.variant)
    factory = getattr(args, "engine_factory", None) or variant.get("engineFactory")
    if not factory:
        raise SystemExit(
            "error: specify --engine-factory dotted.path, a --variant JSON "
            "with an engineFactory field, or run from an engine directory "
            "containing engine.json (see --engine-dir)"
        )
    engine = resolve_engine_factory(factory)
    return engine, variant, factory


def cmd_version(args) -> int:
    print(__version__)
    return 0


def cmd_status(args) -> int:
    if getattr(args, "json", False):
        return _status_json()
    from predictionio_tpu.cli import commands

    try:
        info = commands.status()
    except ImportError as e:
        # driver-gated backends (postgres/psycopg2, s3/boto3): the
        # remedy is in the message — surface it, not a traceback
        print(f"storage verification failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps(info, indent=2))
    print("(sanity check) All storage repositories verified.")
    line = _training_line()
    if line:
        print(line)
    for line in _supervisor_lines():
        print(line)
    for line in _slo_lines():
        print(line)
    for line in _variant_lines():
        print(line)
    for line in _replica_lines():
        print(line)
    return 0


def _variant_lines() -> list[str]:
    """Human per-tenant lines for ``pio status`` when a live engine
    daemon mounts more than one variant: one row per mount off its
    /stats.json ``variants`` block, e.g.
    ``variant[engine/b]: 124 reqs, p99 3.1ms, epoch 2``."""
    import urllib.request

    from predictionio_tpu.cli import daemon

    lines: list[str] = []
    for name in daemon.known_services():
        if daemon.read_pid(name) is None:
            continue
        port = daemon.service_port(name)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats.json", timeout=2.0
            ) as r:
                stats = json.loads(r.read())
        except Exception:
            continue
        variants = (
            stats.get("variants") if isinstance(stats, dict) else None
        ) or {}
        if len(variants) <= 1:
            continue
        for vname, v in variants.items():
            parts = [f"{v.get('requestCount', 0)} reqs"]
            if v.get("p99Ms") is not None:
                parts.append(f"p99 {v['p99Ms']}ms")
            parts.append(f"epoch {v.get('epoch', '?')}")
            if v.get("secondsBehind") is not None:
                parts.append(f"{v['secondsBehind']}s behind")
            if v.get("modelAgeSec") is not None:
                parts.append(f"model age {v['modelAgeSec']}s")
            lines.append(f"variant[{name}/{vname}]: {', '.join(parts)}")
    return lines


def _replica_lines() -> list[str]:
    """Human per-replica lines for ``pio status`` when a router tier is
    up: one row per pool member off its /stats.json ``replicas`` block,
    e.g. ``replica[router/engine-0]: ready, 2 inflight, p99 31.0ms,
    124 reqs`` — ejected members lead with their state upper-cased."""
    import urllib.request

    from predictionio_tpu.cli import daemon

    lines: list[str] = []
    for name in daemon.known_services():
        if daemon.read_pid(name) is None:
            continue
        port = daemon.service_port(name)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats.json", timeout=2.0
            ) as r:
                stats = json.loads(r.read())
        except Exception:
            continue
        replicas = (
            stats.get("replicas") if isinstance(stats, dict) else None
        ) or {}
        for rname, rr in replicas.items():
            state = str(rr.get("state", "?"))
            mark = state if state == "ready" else state.upper()
            parts = [
                f"{rr.get('inflight', 0)} inflight",
                f"p99 {rr.get('p99Ms', 0)}ms",
                f"{rr.get('requests', 0)} reqs",
            ]
            if rr.get("ejections"):
                parts.append(f"{rr['ejections']} ejections")
            lines.append(
                f"replica[{name}/{rname}]: {mark}, {', '.join(parts)}"
            )
    return lines


def _supervisor_lines() -> list[str]:
    """Human supervisor lines for ``pio status``, one per supervised
    service: ``supervisor[engine]: up (restarts 1)`` — with the last
    exit reason and next-retry ETA when it is mid-backoff or broken."""
    from predictionio_tpu.server import supervisor as sup_mod

    doc = sup_mod.read_state()
    if doc is None:
        return []
    lines: list[str] = []
    stale = "" if doc.get("live") else " [supervisor not running]"
    for name, s in (doc.get("services") or {}).items():
        parts = [f"restarts {s.get('restarts', 0)}"]
        if s.get("pid"):
            parts.append(f"pid {s['pid']}")
        if s.get("last_exit") and s.get("state") != "up":
            parts.append(f"last exit: {s['last_exit']}")
        if s.get("next_retry_in_s") is not None:
            parts.append(f"retry in {s['next_retry_in_s']}s")
        lines.append(
            f"supervisor[{name}]: {s.get('state', '?')} "
            f"({', '.join(parts)}){stale}"
        )
    rt = doc.get("retrain")
    if isinstance(rt, dict):
        parts = [
            f"every {rt.get('interval_s')}s"
            + (" (slo)" if rt.get("slo_driven") else ""),
            f"runs {rt.get('runs', 0)}",
            f"skips {rt.get('skips', 0)}",
            f"failures {rt.get('failures', 0)}",
        ]
        last = rt.get("last_run") or {}
        if last:
            parts.append(
                "last ok" if last.get("ok") else
                f"last failed ({last.get('exit')})"
            )
        if rt.get("next_in_s") is not None:
            parts.append(f"next in {rt['next_in_s']}s")
        lines.append(
            f"supervisor[retrain]: {rt.get('state', '?')} "
            f"({', '.join(parts)}){stale}"
        )
    return lines


def _training_progress() -> dict | None:
    """The live-training progress doc (obs/progress.py), or None when
    no checkpointed ``pio train`` is currently publishing."""
    from predictionio_tpu.obs import progress as obs_progress

    doc = obs_progress.read_progress()
    return doc if obs_progress.is_live(doc) else None


def _training_line() -> str | None:
    """Human one-liner for ``pio status``: "training: iter 7/20, ETA 41s"."""
    doc = _training_progress()
    if doc is None:
        return None
    # under --tol the iteration count is an upper bound (the solve may
    # plateau out early), so render "iter 7/<=20, ETA <=41s"
    bound = "<=" if doc.get("eta_is_bound") else ""
    parts = [f"iter {doc.get('iteration')}/{bound}{doc.get('total_iterations')}"]
    if doc.get("eta_s") is not None:
        parts.append(f"ETA {bound}{round(doc['eta_s'])}s")
    rmse = doc.get("rmse")
    if rmse:
        parts.append(f"RMSE {rmse[-1]:.4f}")
    if doc.get("events_per_s"):
        parts.append(f"{doc['events_per_s']:,.0f} events/s")
    return "training: " + ", ".join(parts)


def _fetch_slo_docs() -> dict[str, dict]:
    """``/slo.json`` per live daemon (pid file + answering port); silent
    on daemons that are down or predate the endpoint."""
    import urllib.request

    from predictionio_tpu.cli import daemon

    docs: dict[str, dict] = {}
    for name in daemon.known_services():
        if daemon.read_pid(name) is None:
            continue
        port = daemon.service_port(name)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/slo.json", timeout=2.0
            ) as r:
                doc = json.loads(r.read())
        except Exception:
            continue
        if isinstance(doc, dict):
            docs[name] = doc
    return docs


def _slo_lines() -> list[str]:
    """Human SLO lines for ``pio status``: one per objective, e.g.
    ``slo[engine] engine.latency: OK (burn 0.2/0.1)``; violated and
    burning objectives lead with their state upper-cased. Follows with
    the newest state transitions off each daemon's alert ring."""
    lines: list[str] = []
    alerts: list[tuple[float, str]] = []
    for service, doc in _fetch_slo_docs().items():
        for s in doc.get("slos", []):
            state = str(s.get("state", "?"))
            mark = state.upper() if state != "ok" else "OK"
            burn = ""
            if s.get("burn_fast") is not None:
                burn = f" (burn {s['burn_fast']}/{s.get('burn_slow')})"
            cur = ""
            if s.get("current") is not None:
                cur = f", current {s['current']}"
            lines.append(
                f"slo[{service}] {s.get('name')}: {mark}{burn}{cur}"
            )
        for a in doc.get("alerts", []):
            t = float(a.get("t") or 0.0)
            alerts.append(
                (
                    t,
                    f"alert[{service}] {a.get('slo')}: "
                    f"{a.get('from')} -> {a.get('to')} "
                    f"(burn {a.get('burn_fast')}/{a.get('burn_slow')}, "
                    f"t={a.get('t')})",
                )
            )
    lines.extend(line for _, line in sorted(alerts)[-5:])
    return lines


def cmd_bench(args) -> int:
    """``pio bench --compare OLD.json [NEW.json]``: regression-diff two
    bench summary artifacts (>tolerance moves in the bad direction exit
    non-zero). Running the benchmarks themselves stays with bench.py."""
    if not getattr(args, "compare", None):
        print("usage: pio bench --compare OLD.json [NEW.json]",
              file=sys.stderr)
        return 2
    if len(args.compare) > 2:
        print("bench --compare takes at most OLD and NEW", file=sys.stderr)
        return 2
    from predictionio_tpu.cli import bench_compare

    try:
        return bench_compare.main(
            args.compare[0],
            args.compare[1] if len(args.compare) > 1 else None,
            tolerance=args.tolerance,
        )
    except (OSError, ValueError) as e:
        print(f"bench compare failed: {e}", file=sys.stderr)
        return 2


def cmd_profile(args) -> int:
    """``pio profile --seconds N [--url]``: on-demand jax.profiler trace
    capture — in-process (with a small jit workload so the trace is
    never empty), or via ``POST /profile`` on a running daemon so the
    capture sees that server's real traffic. Prints one compact JSON
    summary line either way (trace dir, window, file count/bytes)."""
    if args.url:
        import urllib.parse
        import urllib.request

        query = {"seconds": str(args.seconds)}
        if args.out:
            query["out"] = args.out
        url = (
            args.url.rstrip("/")
            + "/profile?"
            + urllib.parse.urlencode(query)
        )
        req = urllib.request.Request(url, method="POST")
        try:
            # the server captures synchronously: allow the window + slack
            with urllib.request.urlopen(
                req, timeout=args.seconds + 30.0
            ) as r:
                body = r.read()
        except Exception as e:
            print(f"profile request failed: {e}", file=sys.stderr)
            return 1
        print(body.decode().strip())
        return 0

    from predictionio_tpu.obs import device as obs_device

    try:
        result = obs_device.profile_capture(
            args.seconds, out_dir=args.out, burn=True
        )
    except RuntimeError as e:
        print(f"profile failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps(result, separators=(",", ":")))
    return 0


def cmd_incidents(args) -> int:
    """``pio incidents list|show|prune``: inspect the flight recorder's
    bundle directory (``$PIO_RUN_DIR/incidents``). ``show NAME`` prints
    a bundle summary (or one file verbatim with ``--file``); ``prune``
    keeps the newest ``--keep`` bundles."""
    from predictionio_tpu.obs import incident as obs_incident

    action = getattr(args, "incidents_command", None) or "list"
    if action == "list":
        bundles = obs_incident.list_incidents()
        if getattr(args, "json", False):
            print(json.dumps(bundles, separators=(",", ":")))
            return 0
        if not bundles:
            print(f"no incident bundles under {obs_incident.incidents_dir()}")
            return 0
        for b in bundles:
            print(
                f"{b['name']}  reason={b.get('reason')}  "
                f"files={len(b.get('files', []))}  "
                f"{b.get('bytes', 0):,} bytes"
            )
        return 0
    if action == "show":
        try:
            bundle = obs_incident.load_incident(args.name)
        except FileNotFoundError as e:
            print(str(e), file=sys.stderr)
            return 1
        if getattr(args, "file", None):
            doc = bundle.get(args.file)
            if doc is None:
                print(
                    f"no file {args.file!r} in bundle "
                    f"(have: {', '.join(sorted(bundle))})",
                    file=sys.stderr,
                )
                return 1
            print(doc if isinstance(doc, str) else json.dumps(doc, indent=2))
            return 0
        meta = bundle.get("meta.json", {})
        slo_doc = bundle.get("slo.json", {})
        traces = bundle.get("traces.json", {})
        hist = bundle.get("history.json", {})
        summary = {
            "name": args.name,
            "reason": meta.get("reason"),
            "iso": meta.get("iso"),
            "context": meta.get("context"),
            "slo_states": {
                s.get("name"): s.get("state")
                for s in slo_doc.get("slos", [])
            },
            "alerts": len(slo_doc.get("alerts", [])),
            "traces": len(traces.get("slowest", [])),
            "traces_slo_violated": len(traces.get("sloViolated", [])),
            "history_series": len(hist.get("series", {})),
            "files": sorted(bundle),
        }
        print(json.dumps(summary, indent=2))
        return 0
    if action == "prune":
        removed = obs_incident.prune(keep=args.keep)
        print(f"pruned {len(removed)} bundle(s)"
              + (f": {', '.join(removed)}" if removed else ""))
        return 0
    print(f"unknown incidents action {action!r}", file=sys.stderr)
    return 2


def _top_targets(urls: list[str] | None) -> list[tuple[str, str]]:
    """(name, base_url) pairs ``pio top`` polls: explicit ``--url``
    values, else every live daemon (pid file + default port)."""
    if urls:
        return [(u.split("//")[-1].rstrip("/"), u.rstrip("/")) for u in urls]
    from predictionio_tpu.cli import daemon

    out = []
    for name in daemon.known_services():
        if daemon.read_pid(name) is None:
            continue
        port = daemon.service_port(name)
        out.append((name, f"http://127.0.0.1:{port}"))
    return out


def _top_row(name: str, base: str) -> dict:
    """One daemon's live numbers, derived from its history rings: qps
    from the newest ``pio_http_requests_total`` delta, p99 from the
    newest request-latency quantile sample, ``seconds_behind`` and the
    worst fast-window burn rate from their gauge series."""
    import urllib.request

    def fetch(path: str) -> dict:
        with urllib.request.urlopen(base + path, timeout=2.0) as r:
            return json.loads(r.read())

    row: dict = {"service": name, "url": base}
    try:
        hist = fetch("/history.json")
    except Exception as e:
        row["error"] = f"{type(e).__name__}"
        return row
    step = float(hist.get("step_s") or 5.0)
    series = hist.get("series", {})

    def latest(key_prefix: str, suffix: str = "") -> float | None:
        vals = [
            doc["points"][-1][1]
            for key, doc in series.items()
            if key.startswith(key_prefix) and key.endswith(suffix)
            and doc.get("points")
        ]
        return max(vals) if vals else None

    req_delta = sum(
        doc["points"][-1][1]
        for key, doc in series.items()
        if key.startswith("pio_http_requests_total") and doc.get("points")
    )
    row["qps"] = round(req_delta / step, 2)
    p99 = latest("pio_http_request_seconds", ":p99")
    if p99 is not None:
        row["p99_ms"] = round(p99 * 1e3, 3)
    behind = latest("pio_realtime_seconds_behind")
    if behind is not None:
        row["seconds_behind"] = round(behind, 3)
    burn = latest("pio_slo_burn_rate")
    if burn is not None:
        row["burn"] = round(burn, 2)
    try:
        slo_doc = fetch("/slo.json")
        states = [str(s.get("state")) for s in slo_doc.get("slos", [])]
        row["slo"] = {
            st: states.count(st)
            for st in ("ok", "burning", "violated")
            if states.count(st)
        }
        row["alerts"] = len(slo_doc.get("alerts", []))
    except Exception:
        pass
    # multi-tenant engine servers: one sub-row per mounted variant
    # (/stats.json "variants" block); solo deploys render no sub-rows
    try:
        stats = fetch("/stats.json")
        variants = stats.get("variants") or {}
        if len(variants) > 1:
            row["variants"] = {
                vname: {
                    "requests": v.get("requestCount"),
                    "p99_ms": v.get("p99Ms"),
                    "epoch": v.get("epoch"),
                    "seconds_behind": v.get("secondsBehind"),
                    "model_age_s": v.get("modelAgeSec"),
                }
                for vname, v in variants.items()
            }
        # router tier: one sub-row per backend replica (/stats.json
        # "replicas" block), mirroring the variant sub-row convention
        replicas = stats.get("replicas") or {}
        if replicas:
            row["replicas"] = {
                rname: {
                    "state": r.get("state"),
                    "inflight": r.get("inflight"),
                    "p99_ms": r.get("p99Ms"),
                    "requests": r.get("requests"),
                    "ejections": r.get("ejections"),
                }
                for rname, r in replicas.items()
            }
    except Exception:
        pass
    return row


def cmd_top(args) -> int:
    """``pio top [--once] [--interval S] [--url BASE ...]``: live
    terminal view across daemons — qps, p99, seconds_behind, burn rates,
    all read from each server's ``/history.json`` rings (no server-side
    aggregation; the CLI only diffs what the rings already hold)."""
    interval = max(float(getattr(args, "interval", 2.0)), 0.2)
    once = bool(getattr(args, "once", False))
    while True:
        targets = _top_targets(getattr(args, "url", None))
        rows = [_top_row(name, base) for name, base in targets]
        if not once:
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
        stamp = time.strftime("%H:%M:%S")
        print(f"pio top — {stamp} — {len(rows)} service(s)")
        header = (
            f"{'SERVICE':<14} {'QPS':>9} {'P99_MS':>9} {'BEHIND_S':>9} "
            f"{'BURN':>7} {'SLO':<22} {'ALERTS':>6}"
        )
        print(header)
        for row in rows:
            if "error" in row:
                print(f"{row['service']:<14} unreachable ({row['error']})")
                continue
            slo_str = (
                ",".join(f"{k}:{v}" for k, v in row.get("slo", {}).items())
                or "-"
            )
            print(
                f"{row['service']:<14} {row.get('qps', 0):>9} "
                f"{row.get('p99_ms', '-'):>9} "
                f"{row.get('seconds_behind', '-'):>9} "
                f"{row.get('burn', '-'):>7} {slo_str:<22} "
                f"{row.get('alerts', 0):>6}"
            )
            for vname, v in (row.get("variants") or {}).items():
                req = v.get("requests")
                print(
                    f"  ↳{vname:<12} {req if req is not None else '-':>9} "
                    f"{v.get('p99_ms') if v.get('p99_ms') is not None else '-':>9} "
                    f"{v.get('seconds_behind') if v.get('seconds_behind') is not None else '-':>9} "
                    f"{'':>7} epoch:{v.get('epoch', '-')}"
                )
            for rname, rr in (row.get("replicas") or {}).items():
                req = rr.get("requests")
                print(
                    f"  ↳{rname:<12} {req if req is not None else '-':>9} "
                    f"{rr.get('p99_ms') if rr.get('p99_ms') is not None else '-':>9} "
                    f"{'':>9} {'':>7} "
                    f"{rr.get('state', '?')} inflight:{rr.get('inflight', 0)} "
                    f"ejections:{rr.get('ejections', 0)}"
                )
        if not rows:
            print("no live daemons (and no --url given)")
        if once:
            return 0
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0


def _status_json() -> int:
    """``pio status --json``: one compact JSON line merging ``/metrics``
    + ``/stats.json`` from every running daemon (live pid files), in
    the bench summary-line convention. Endpoints that refuse (the event
    server's /stats.json wants an access key) are skipped, not fatal."""
    import urllib.request

    from predictionio_tpu.cli import daemon
    from predictionio_tpu.obs import metrics as obs_metrics

    def fetch(url: str):
        try:
            with urllib.request.urlopen(url, timeout=2.0) as r:
                return r.read()
        except Exception:
            return None

    services: dict = {}
    for name in daemon.known_services():
        pid = daemon.read_pid(name)
        if pid is None:
            continue
        port = daemon.service_port(name)
        entry: dict = {"pid": pid, "port": port}
        base = f"http://127.0.0.1:{port}"
        raw = fetch(f"{base}/metrics")
        if raw is not None:
            entry["metrics"] = obs_metrics.parse_prometheus(raw)
        raw = fetch(f"{base}/stats.json")
        if raw is not None:
            try:
                entry["stats"] = json.loads(raw)
            except ValueError:
                pass
        raw = fetch(f"{base}/slo.json")
        if raw is not None:
            try:
                entry["slo"] = json.loads(raw)
            except ValueError:
                pass
        services[name] = entry
    summary: dict = {"services": services}
    # self-healing supervisor state (supervisor.json), when a fleet ran
    # (or runs) under `pio start-all --supervise`
    from predictionio_tpu.server import supervisor as sup_mod

    sup_doc = sup_mod.read_state()
    if sup_doc is not None:
        summary["supervisor"] = sup_doc
    # the SLO alert ring across services, oldest->newest, each record
    # tagged with the daemon it came from (satellite: alerts were
    # counted but not inspectable without scraping /slo.json)
    alerts = [
        {"service": name, **a}
        for name, entry in services.items()
        for a in (entry.get("slo") or {}).get("alerts", [])
    ]
    alerts.sort(key=lambda a: float(a.get("t") or 0.0))
    summary["alerts"] = alerts[-10:]
    # incident bundles on this host (flight-recorder output)
    from predictionio_tpu.obs import incident as obs_incident

    bundles = obs_incident.list_incidents()
    summary["incidents"] = {
        "count": len(bundles),
        "latest": bundles[0]["name"] if bundles else None,
        "dir": str(obs_incident.incidents_dir()),
    }
    # live checkpointed training on this host, if any (the per-service
    # device blocks already ride in services.*.stats.device)
    progress = _training_progress()
    if progress is not None:
        summary["training"] = progress
    print(json.dumps(summary, separators=(",", ":")))
    return 0


def cmd_build(args) -> int:
    """Python engines need no assembly; ``build`` verifies what sbt
    would have caught: the factory imports AND the variant's component
    names/params bind to the engine's registered classes — a broken
    template fails here, not at train."""
    _enter_engine_dir(args)  # idempotent; resolves ./engine.json pickup
    if getattr(args, "engine_factory", None) or getattr(args, "variant", None):
        engine, variant, factory = _engine_from_args(args)
        if variant:
            try:
                engine.params_from_variant(variant)
            except Exception as e:
                print(f"build failed: variant does not bind to "
                      f"{factory}: {e}", file=sys.stderr)
                return 1
        print("Engine factory resolves; build OK.")
    else:
        print("Nothing to build for Python engines; use --engine-factory to verify.")
    return 0


def cmd_app(args) -> int:
    from predictionio_tpu.cli import commands

    try:
        if args.app_command == "new":
            info = commands.app_new(
                args.name, app_id=args.id or 0, description=args.description,
                access_key=args.access_key or "",
            )
            print(f"Created a new app:")
            print(f"      Name: {info['name']}")
            print(f"        ID: {info['id']}")
            print(f"Access Key: {info['access_key']}")
        elif args.app_command == "list":
            for a in commands.app_list():
                print(f"{a['id']:>6} | {a['name']} | {a['access_key']}")
        elif args.app_command == "show":
            info = commands.app_show(args.name)
            print(json.dumps(info, indent=2))
        elif args.app_command == "delete":
            commands.app_delete(args.name)
            print(f"Deleted app {args.name}.")
        elif args.app_command == "data-delete":
            commands.app_data_delete(args.name, channel=args.channel)
            print(f"Deleted data of app {args.name}.")
        elif args.app_command == "channel-new":
            info = commands.channel_new(args.name, args.channel)
            print(f"Created channel {info['name']} (id {info['id']}).")
        elif args.app_command == "channel-delete":
            commands.channel_delete(args.name, args.channel)
            print(f"Deleted channel {args.channel}.")
        else:
            print(
                "usage: pio app "
                "{new,list,show,delete,data-delete,channel-new,channel-delete}",
                file=sys.stderr,
            )
            return 1
        return 0
    except commands.CommandError as e:
        print(str(e), file=sys.stderr)
        return 1


def cmd_accesskey(args) -> int:
    from predictionio_tpu.cli import commands

    try:
        if args.ak_command == "new":
            key = commands.accesskey_new(args.app_name, events=args.event or [])
            print(f"Created new access key: {key}")
        elif args.ak_command == "list":
            for k in commands.accesskey_list(args.app_name):
                print(f"{k['key']} | app {k['app_id']} | events {k['events'] or 'ALL'}")
        elif args.ak_command == "delete":
            commands.accesskey_delete(args.key)
            print(f"Deleted access key {args.key}.")
        else:
            print("usage: pio accesskey {new,list,delete}", file=sys.stderr)
            return 1
        return 0
    except commands.CommandError as e:
        print(str(e), file=sys.stderr)
        return 1


def _parse_mesh(spec: str | None) -> list[tuple[str, int]] | None:
    """'data=4,model=2' -> [("data", 4), ("model", 2)]."""
    if not spec:
        return None
    axes = []
    for part in spec.split(","):
        name, _, size = part.partition("=")
        try:
            n = int(size)
        except ValueError:
            n = 0
        if not name or n == 0 or n < -1:
            raise SystemExit(
                f"bad --mesh axis {part!r}; expected name=size with a "
                "positive integer size (or -1 once for the remainder)"
            )
        axes.append((name.strip(), n))
    if sum(1 for _, n in axes if n == -1) > 1:
        raise SystemExit("--mesh: at most one axis may be -1")
    return axes


def cmd_train(args) -> int:
    from predictionio_tpu.core.engine import WorkflowParams
    from predictionio_tpu.core.workflow import run_train

    if getattr(args, "no_columnar_cache", False):
        os.environ["PIO_COLUMNAR_CACHE"] = "0"
    if getattr(args, "checkpoint_every", None):
        os.environ["PIO_CHECKPOINT_EVERY"] = str(args.checkpoint_every)
    if getattr(args, "resume", False):
        os.environ["PIO_RESUME"] = "1"
    if getattr(args, "checkpoint_dir", None):
        os.environ["PIO_CHECKPOINT_DIR"] = args.checkpoint_dir
    if getattr(args, "warm_start", False):
        os.environ["PIO_WARM_START"] = "1"
    if getattr(args, "tol", None) is not None:
        os.environ["PIO_TOL"] = str(args.tol)
    if getattr(args, "no_prep_cache", False):
        os.environ["PIO_PREP_CACHE"] = "0"
    if getattr(args, "prep_cache_dir", None):
        os.environ["PIO_PREP_CACHE_DIR"] = args.prep_cache_dir
    if getattr(args, "multihost", False):
        # join the global mesh BEFORE anything touches JAX: afterwards
        # jax.devices() is the pod-wide set and --mesh axes span hosts
        # (the cluster-submission analog of the reference's spark-submit
        # master flags, tools/.../Runner.scala:193-244)
        from predictionio_tpu.parallel.mesh import initialize_multihost

        initialize_multihost(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    engine, variant, factory = _engine_from_args(args)
    engine_params = engine.params_from_variant(variant)
    wp = WorkflowParams(
        batch=args.batch or "",
        verbose=args.verbose,
        skip_sanity_check=args.skip_sanity_check,
        stop_after_read=args.stop_after_read,
        stop_after_prepare=args.stop_after_prepare,
        profile_dir=args.profile_dir,
        mesh_axes=_parse_mesh(getattr(args, "mesh", None)),
    )
    engine_id, engine_version, variant_label = _engine_identity(args, variant)
    instance_id = run_train(
        engine,
        engine_params,
        engine_id=engine_id,
        engine_version=engine_version,
        engine_variant=variant_label,
        engine_factory=factory,
        workflow_params=wp,
    )
    print(f"Training completed. Engine instance ID: {instance_id}")
    return 0


def cmd_eval(args) -> int:
    from predictionio_tpu.core.workflow_eval import run_evaluation

    instance_id, result = run_evaluation(
        evaluation_class=args.evaluation_class,
        engine_params_generator_class=args.engine_params_generator_class,
        batch=args.batch or "",
    )
    print(result.to_one_liner())
    print(f"Evaluation completed. Evaluation instance ID: {instance_id}")
    # compact machine-readable summary as the FINAL stdout line (same
    # contract as bench.py: drivers that keep only a bounded tail of
    # stdout can json.loads the last line on its own)
    best = result.best_score
    summary = {
        "metric": result.metric_header,
        "best_index": result.best_idx,
        "best_params": result.best_engine_params.to_jsonable(),
        "best_scores": {
            result.metric_header: best.score,
            **dict(zip(result.other_metric_headers, best.other_scores)),
        },
        "scores": [ms.score for _, ms in result.engine_params_scores],
        "candidates": len(result.engine_params_scores),
        "fast_path_candidates": result.fast_path_candidates,
        "phase_seconds": {
            k: round(v, 3) for k, v in result.phase_seconds.items()
        },
        "cache": result.cache_stats,
        "instance_id": instance_id,
    }
    print(json.dumps(summary, sort_keys=True))
    return 0


def _load_server_config(args):
    """server.conf for key auth / SSL: --server-config flag, else the
    PIO_SERVER_CONF env var, else conf/server.conf when present
    (the reference loads server.conf from the classpath unconditionally)."""
    import os

    from predictionio_tpu.common import load_server_config

    path = (
        getattr(args, "server_config", None)
        or os.environ.get("PIO_SERVER_CONF")
        or "conf/server.conf"
    )
    return load_server_config(path=path)


def _maybe_run_workers(args) -> int | None:
    """The `--workers N` dispatch shared by deploy/eventserver: None
    means "continue single-process"."""
    if getattr(args, "workers", 1) <= 1:
        return None
    if args.port == 0:
        print("--workers needs an explicit --port", file=sys.stderr)
        return 1
    return _run_workers(args)


def _run_workers(args) -> int:
    """Spawn N copies of this exact CLI invocation (each binding the
    same port with SO_REUSEPORT) and supervise them: forward SIGTERM/
    SIGINT, exit nonzero if ANY worker dies (an external supervisor
    restarts the set). The reference's spray server scales with JVM
    threads inside one process; CPython serving is GIL-bound, so the
    scale-out unit here is the PROCESS."""
    import signal
    import subprocess
    import time

    # strip every --workers spelling (separate token, --workers=N, and
    # argparse prefix abbreviations): a surviving flag would make each
    # child spawn its own workers — a fork bomb
    argv = []
    tokens = iter(sys.argv[1:])
    for tok in tokens:
        if tok.startswith("--w") and "--workers".startswith(
            tok.split("=", 1)[0]
        ):
            if "=" not in tok:
                next(tokens, None)  # drop the value token too
            continue
        argv.append(tok)
    if "--reuse-port" not in argv:
        argv.append("--reuse-port")

    procs: list = []

    # install the forwarders BEFORE spawning: a SIGTERM landing in the
    # spawn window must still reach (and not orphan) early workers
    def forward(signum, _frame):
        for pr in procs:
            pr.send_signal(signum)

    signal.signal(signal.SIGTERM, forward)
    signal.signal(signal.SIGINT, forward)
    for _ in range(args.workers):
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "predictionio_tpu.cli.main"] + argv
            )
        )
    rc = 0
    try:
        # poll ALL workers: the death of any one must surface (waiting
        # on one pid would let the set run degraded indefinitely)
        while procs and rc == 0:
            time.sleep(0.5)
            for pr in list(procs):
                code = pr.poll()
                if code is None:
                    continue
                procs.remove(pr)
                if code not in (0, -signal.SIGTERM, -signal.SIGINT):
                    rc = code or 1
    finally:
        for pr in procs:
            pr.terminate()
        for pr in procs:
            try:
                pr.wait(timeout=10)
            except Exception:
                pr.kill()
    return rc


def _maybe_enable_compilation_cache() -> None:
    """Wire jax's persistent compilation cache when
    ``PIO_COMPILATION_CACHE_DIR`` is set (the daemon defaults it for
    fleet services): deploy warmup compiles land on disk, so a restarted
    server skips recompiles entirely — the first-query latency spike
    dies at most once per (program, jax version) per machine."""
    cache_dir = os.environ.get("PIO_COMPILATION_CACHE_DIR")
    if not cache_dir:
        return
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache every program: serving top-k programs compile fast but
        # re-compile on every restart without this
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # pragma: no cover - cache is an optimization
        print(
            f"persistent compilation cache unavailable ({e}); continuing",
            file=sys.stderr,
        )


def cmd_deploy(args) -> int:
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.server.engine_server import EngineServer

    rc = _maybe_run_workers(args)
    if rc is not None:
        return rc
    _maybe_enable_compilation_cache()

    engine, variant, factory = _engine_from_args(args)
    storage = get_storage()
    instances = storage.get_metadata_engine_instances()
    if args.engine_instance_id:
        instance = instances.get(args.engine_instance_id)
        if instance is None:
            print(f"engine instance {args.engine_instance_id} not found", file=sys.stderr)
            return 1
    else:
        engine_id, engine_version, variant_label = _engine_identity(
            args, variant
        )
        instance = instances.get_latest_completed(
            engine_id, engine_version, variant_label
        )
        if instance is None and getattr(args, "variant", None):
            # instances trained before the basename-label change carry
            # the as-typed path as their label; fall back so they stay
            # deployable without a retrain
            instance = instances.get_latest_completed(
                variant.get("id", "default"),
                engine_version,
                args.variant,
            )
        if instance is None:
            print(
                "No valid engine instance found for this engine; "
                "have you run `pio train` yet?",
                file=sys.stderr,
            )
            return 1
    try:
        extra_variants = _resolve_extra_variants(args, instances)
    except SystemExit:
        raise
    except Exception as e:
        print(f"--variants resolution failed: {e}", file=sys.stderr)
        return 1
    server = EngineServer(
        engine,
        instance,
        storage=storage,
        host=args.ip,
        port=args.port,
        feedback=args.feedback,
        event_server_url=(
            f"http://{args.event_server_ip}:{args.event_server_port}"
            if args.feedback
            else None
        ),
        access_key=args.accesskey,
        server_config=_load_server_config(args),
        log_url=args.log_url,
        log_prefix=args.log_prefix,
        batch_window_ms=args.batch_window_ms,
        reuse_port=args.reuse_port,
        query_cache_mb=args.query_cache_mb,
        extra_variants=extra_variants,
    )
    # AOT warmup BEFORE the port binds: the first real query hits a
    # compiled scoring program (and, with PIO_COMPILATION_CACHE_DIR, the
    # compile itself persists across restarts)
    if not getattr(args, "no_warmup", False):
        server.warmup()
    layers = []
    if getattr(args, "realtime", 0.0) and args.realtime > 0:
        from pathlib import Path

        from predictionio_tpu.realtime import SpeedLayer

        cursor = args.realtime_cursor or str(
            Path("~/.pio_tpu").expanduser()
            / "realtime"
            / f"cursor_{instance.engine_id}_{args.port}.json"
        )
        layers.append(
            SpeedLayer(server, interval=args.realtime, cursor_path=cursor)
        )
        # every co-tenant mount tails and folds independently — its
        # layer holds the _Variant, so patches land behind that mount's
        # own epoch fence, never a neighbor's
        for name, v in server.variants.items():
            if v is server._default_variant:
                continue
            vcursor = str(
                Path("~/.pio_tpu").expanduser()
                / "realtime"
                / f"cursor_{v.instance.engine_id}_{args.port}_{name}.json"
            )
            layers.append(
                SpeedLayer(v, interval=args.realtime, cursor_path=vcursor)
            )
        for layer in layers:
            layer.start()
    # foreground, like the reference: backgrounding is the caller's job
    # (shell &, supervisor); a daemon thread would die with this process
    try:
        server.start(background=False)
    finally:
        for layer in layers:
            layer.stop()
    return 0


def _resolve_extra_variants(args, instances) -> list:
    """``--variants a.json,b.json`` -> [(mount_name, engine, instance)].

    Each file resolves exactly like a solo ``pio deploy --variant`` of
    that path: its own engineFactory (falling back to the primary's),
    its own (id, version, basename-label) instance lookup. The mount
    name is the file's basename minus ``.json`` — the path prefix
    queries route on (``/<name>/queries.json``)."""
    spec = getattr(args, "variants", None) or ""
    paths = [p.strip() for p in spec.split(",") if p.strip()]
    if not paths:
        return []
    from predictionio_tpu.core.engine import resolve_engine_factory
    from predictionio_tpu.core.workflow import load_variant

    extra = []
    for path in paths:
        variant = load_variant(path)
        factory = variant.get("engineFactory") or getattr(
            args, "engine_factory", None
        )
        if not factory:
            raise SystemExit(
                f"error: variant file {path} has no engineFactory field "
                "and no --engine-factory was given"
            )
        engine = resolve_engine_factory(factory)
        engine_id = variant.get("id") or os.path.dirname(
            os.path.realpath(path)
        )
        label = os.path.basename(path)
        inst = instances.get_latest_completed(
            engine_id, variant.get("version", "0"), label
        )
        if inst is None:
            raise SystemExit(
                f"error: no completed engine instance for variant {path} "
                "(train it first: pio train --variant " + path + ")"
            )
        name = label[:-5] if label.endswith(".json") else label
        extra.append((name, engine, inst))
    return extra


def cmd_undeploy(args) -> int:
    import urllib.request

    url = f"http://{args.ip}:{args.port}/stop"
    try:
        urllib.request.urlopen(urllib.request.Request(url, data=b""), timeout=10)
        print("Undeployed.")
        return 0
    except Exception as e:
        print(f"undeploy failed: {e}", file=sys.stderr)
        return 1


def cmd_eventserver(args) -> int:
    from predictionio_tpu.server.event_server import EventServer

    rc = _maybe_run_workers(args)
    if rc is not None:
        return rc
    server = EventServer(
        host=args.ip, port=args.port, stats=args.stats,
        reuse_port=args.reuse_port,
    )
    server.start(background=False)
    return 0


def cmd_storageserver(args) -> int:
    """Serve this host's storage repositories over HTTP so remote
    processes (event server / trainer / engine server on other machines)
    can bind their repositories to it via the ``http`` backend — the
    client-server storage role JDBC Postgres plays in the reference."""
    from predictionio_tpu.server.storage_server import StorageServer

    StorageServer(
        host=args.ip,
        port=args.port,
        auth_key=args.auth_key,
        server_config=_load_server_config(args) if args.server_config else None,
    ).start(background=False)
    return 0


def cmd_adminserver(args) -> int:
    from predictionio_tpu.server.admin_server import AdminServer

    AdminServer(host=args.ip, port=args.port).start(background=False)
    return 0


def cmd_dashboard(args) -> int:
    from predictionio_tpu.server.dashboard import Dashboard

    Dashboard(
        host=args.ip, port=args.port, server_config=_load_server_config(args)
    ).start(background=False)
    return 0


def cmd_route(args) -> int:
    """``pio route``: the scale-out router tier — one front port
    spreading /queries.json across a replica set of engine servers with
    consistent-hash affinity, health-aware ejection, and hedged
    requests (server/router.py; docs/operations.md "Scale-out
    serving")."""
    from predictionio_tpu.server.router import RouterServer, parse_replica_spec

    replicas: list[tuple[str, str, int]] = []
    for i, spec in enumerate(args.replica or []):
        try:
            replicas.append(parse_replica_spec(spec, i))
        except ValueError as e:
            print(f"route: {e}", file=sys.stderr)
            return 1
    if args.replicas:
        host = args.engine_host
        base = args.engine_port
        replicas.extend(
            (f"engine-{i}", host, base + i) for i in range(args.replicas)
        )
    if not replicas:
        print(
            "route: name at least one backend (--replica HOST:PORT or "
            "--replicas N)", file=sys.stderr,
        )
        return 1
    server = RouterServer(
        replicas,
        host=args.ip,
        port=args.port,
        reuse_port=args.reuse_port,
        probe_interval_s=args.probe_interval or None,
        hedge=False if args.no_hedge else None,
    )
    server.start(background=False)
    return 0


def cmd_export(args) -> int:
    from predictionio_tpu.cli import commands
    from predictionio_tpu.data.store import EventStoreError

    try:
        n = commands.export_events(
            args.appid_or_name, args.output, channel=args.channel
        )
    except (commands.CommandError, EventStoreError) as e:
        print(str(e), file=sys.stderr)
        return 1
    print(f"Exported {n} events to {args.output}.")
    return 0


def cmd_import(args) -> int:
    from predictionio_tpu.cli import commands
    from predictionio_tpu.data.store import EventStoreError

    try:
        if getattr(args, "http", None):
            if not args.access_key:
                print("--http requires --access-key", file=sys.stderr)
                return 1
            n = commands.import_events_http(
                args.input, args.http, args.access_key,
                channel=args.channel,
            )
        else:
            n = commands.import_events(
                args.appid_or_name, args.input,
                channel=args.channel, jobs=args.jobs,
            )
    except (commands.CommandError, EventStoreError) as e:
        print(str(e), file=sys.stderr)
        return 1
    print(f"Imported {n} events.")
    if getattr(args, "warm_cache", False):
        from predictionio_tpu.data import store
        from predictionio_tpu.data.storage import get_storage

        storage = get_storage()
        rows = store.warm_columnar_cache(
            commands._resolve_app_name(args.appid_or_name, storage),
            channel_name=args.channel,
            storage=storage,
        )
        print(f"Columnar cache warmed ({rows} rating rows).")
    return 0


def cmd_run(args) -> int:
    """Run a user main function with storage configured (reference `pio
    run` — Console.scala:664-700 launches a main class on Spark with the
    pio classpath; here: import dotted path, call its main/entry)."""
    import importlib

    target = args.main_class
    mod_name, _, attr = target.partition(":")
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, attr or "main", None)
    if fn is None:
        raise SystemExit(
            f"error: {mod_name} has no {attr or 'main'}(); use "
            "module:function to name an entry point"
        )
    result = fn(*args.args)
    # a bool return is success/failure, not an exit code (int(True) == 1)
    if isinstance(result, bool):
        return 0 if result else 1
    return int(result) if isinstance(result, int) else 0


def cmd_cache(args) -> int:
    """``pio cache list|evict|prune``: packed-prep cache lifecycle
    (core/prep_cache.py). Entries are derived data — evicting one only
    costs the next train a full scan+pack."""
    from predictionio_tpu.core import prep_cache

    verb = getattr(args, "cache_verb", None) or "list"
    if verb == "list":
        entries = prep_cache.cache_entries(detail=True)
        total = sum(e["bytes"] for e in entries)
        cap = prep_cache.max_bytes()
        if getattr(args, "json", False):
            print(json.dumps({
                "dir": str(prep_cache.cache_dir()),
                "total_bytes": total,
                "max_bytes": cap,
                "entries": entries,
            }, indent=2))
            return 0
        print(f"Prep cache: {prep_cache.cache_dir()}")
        if not entries:
            print("  (empty)")
            return 0
        for e in entries:
            packs = []
            if e.get("single_pack"):
                packs.append("single")
            if e.get("sharded_pack"):
                packs.append("sharded")
            age = time.time() - e["atime"]
            print(
                f"  {e['name']}: {e['bytes'] / 1e6:.1f} MB, "
                f"{e.get('n', 0):,} events, "
                f"packs [{', '.join(packs) or 'none'}], "
                f"last used {age:.0f}s ago"
            )
        cap_s = f" / cap {cap / 1e6:.1f} MB" if cap else ""
        print(f"  total {total / 1e6:.1f} MB{cap_s}")
        return 0
    if verb == "evict":
        if prep_cache.evict(args.entry):
            print(f"evicted {args.entry}")
            return 0
        print(f"cache: no such entry {args.entry!r}", file=sys.stderr)
        return 1
    if verb == "prune":
        limit = None
        if getattr(args, "max_mb", None) is not None:
            limit = int(float(args.max_mb) * 1024 * 1024)
        out = prep_cache.prune(limit=limit)
        if getattr(args, "json", False):
            print(json.dumps(out, indent=2))
        else:
            print(
                f"pruned: {len(out['husks'])} husk(s), "
                f"{len(out['evicted'])} entry(ies) evicted"
            )
        return 0
    print(f"cache: unknown verb {verb!r}", file=sys.stderr)
    return 1


def cmd_start_all(args) -> int:
    """Bring up the service fleet as detached daemons (reference
    bin/pio-start-all; see cli/daemon.py for the process model).
    With ``--supervise`` the fleet runs under a foreground supervisor
    (server/supervisor.py) that restarts crashed children with backoff."""
    from predictionio_tpu.cli import daemon

    # --reuse-port on the HTTP services so `pio rolling-restart` can
    # overlap a replacement instance on the same port later
    plan: list[tuple[str, list[str], int]] = [
        (
            "eventserver",
            ["eventserver", "--ip", args.ip, "--port", str(args.event_port),
             "--reuse-port"]
            + (["--stats"] if args.stats else []),
            args.event_port,
        )
    ]
    if not args.no_dashboard:
        plan.append(
            (
                "dashboard",
                ["dashboard", "--ip", args.ip, "--port", str(args.dashboard_port)],
                args.dashboard_port,
            )
        )
    if not args.no_adminserver:
        plan.append(
            (
                "adminserver",
                ["adminserver", "--ip", args.ip, "--port", str(args.admin_port)],
                args.admin_port,
            )
        )
    if args.variant or args.engine_factory or args.engine_dir:
        # beyond the reference's script: also deploy the latest trained
        # engine so one verb yields a fully queryable stack. Paths go
        # absolute — the daemon child's cwd is not this shell's.
        deploy = ["deploy", "--ip", args.ip, "--reuse-port"]
        if args.variant:
            deploy += ["--variant", os.path.abspath(args.variant)]
        if args.engine_factory:
            deploy += ["--engine-factory", args.engine_factory]
        if args.engine_dir:
            deploy += ["--engine-dir", os.path.abspath(args.engine_dir)]
        if getattr(args, "variants", None):
            deploy += [
                "--variants",
                ",".join(
                    os.path.abspath(p.strip())
                    for p in args.variants.split(",")
                    if p.strip()
                ),
            ]
        replicas = int(getattr(args, "replicas", 0) or 0)
        if replicas > 0:
            # scale-out: N engine replicas on consecutive ports, each a
            # first-class supervised service (engine-0..engine-N-1),
            # fronted by the router tier on --router-port
            router_host = args.ip if args.ip != "0.0.0.0" else "127.0.0.1"
            route = ["route", "--ip", args.ip,
                     "--port", str(args.router_port), "--reuse-port"]
            for i in range(replicas):
                port = args.engine_port + i
                plan.append((
                    f"engine-{i}",
                    deploy + ["--port", str(port)],
                    port,
                ))
                route += ["--replica", f"engine-{i}={router_host}:{port}"]
            plan.append(("router", route, args.router_port))
        else:
            plan.append(
                ("engine", deploy + ["--port", str(args.engine_port)],
                 args.engine_port)
            )

    if getattr(args, "supervise", False):
        return _run_supervised(args, plan)

    started: list[str] = []
    for name, argv, port in plan:
        host = args.ip if args.ip != "0.0.0.0" else "127.0.0.1"
        try:
            pid = daemon.start_service(name, argv, host, port)
        except RuntimeError as e:
            print(f"start-all: {e}", file=sys.stderr)
            for prev in reversed(started):  # roll back partial bring-up
                daemon.stop_service(prev)
            return 1
        started.append(name)
        print(f"{name}: up on port {port} (pid {pid})")
    print(f"Run dir: {daemon.run_dir()}")
    return 0


def _parse_duration(value: str) -> float:
    """``300`` / ``300s`` / ``15m`` / ``1h`` -> seconds."""
    s = str(value).strip().lower()
    mult = 1.0
    if s.endswith(("s", "m", "h")):
        mult = {"s": 1.0, "m": 60.0, "h": 3600.0}[s[-1]]
        s = s[:-1]
    try:
        out = float(s) * mult
    except ValueError:
        raise ValueError(f"bad duration {value!r} (want e.g. 300s, 15m, 1h)")
    if out <= 0:
        raise ValueError(f"duration must be positive, got {value!r}")
    return out


def _retrain_scheduler(args, plan, host):
    """Build the RetrainScheduler for ``--retrain-every``, or None."""
    from predictionio_tpu.server import supervisor as sup_mod

    raw = getattr(args, "retrain_every", None)
    if not raw:
        return None
    interval = _parse_duration(raw)
    engine_ports = [
        port for name, _argv, port in plan
        if name == "engine" or name.startswith("engine-")
    ]
    if not engine_ports:
        raise ValueError(
            "--retrain-every needs a deployed engine "
            "(--variant/--engine-factory/--engine-dir)"
        )
    train_argv = ["train", "--warm-start"]
    if args.variant:
        train_argv += ["--variant", os.path.abspath(args.variant)]
    if args.engine_factory:
        train_argv += ["--engine-factory", args.engine_factory]
    if args.engine_dir:
        train_argv += ["--engine-dir", os.path.abspath(args.engine_dir)]
    if getattr(args, "retrain_tol", None):
        train_argv += ["--tol", str(args.retrain_tol)]
    floor = getattr(args, "retrain_floor", None)
    return sup_mod.RetrainScheduler(
        interval,
        train_argv=train_argv,
        engine_ports=engine_ports,
        host=host,
        slo_driven=bool(getattr(args, "retrain_slo", False)),
        floor_s=_parse_duration(floor) if floor else None,
    )


def _run_supervised(args, plan) -> int:
    """``pio start-all --supervise`` / ``pio supervise``: run the fleet
    under the self-healing supervisor in the FOREGROUND (the supervisor
    is the thing an init system or terminal owns; its children are the
    detached daemons). SIGTERM/SIGINT request an orderly reverse-order
    stop — each child gets a drain-grace SIGTERM first."""
    import signal

    from predictionio_tpu.cli import daemon
    from predictionio_tpu.server import supervisor as sup_mod

    host = args.ip if args.ip != "0.0.0.0" else "127.0.0.1"
    specs = [
        sup_mod.ServiceSpec(name=name, argv=argv, host=host, port=port)
        for name, argv, port in plan
    ]
    try:
        retrain = _retrain_scheduler(args, plan, host)
    except ValueError as e:
        print(f"supervise: {e}", file=sys.stderr)
        return 1
    sup = sup_mod.Supervisor(specs, retrain=retrain)

    def _request_stop(signum, _frame):
        sup.request_stop()

    signal.signal(signal.SIGTERM, _request_stop)
    signal.signal(signal.SIGINT, _request_stop)

    stats = None
    stats_port = getattr(args, "supervise_port", 0) or 0
    if stats_port:
        stats = sup_mod.stats_app(sup, host=host, port=stats_port)
        stats.start(background=True)
        print(f"supervisor: stats on http://{host}:{stats_port}/stats.json")
    try:
        sup.start_all()
    except Exception as e:
        print(f"supervise: {e}", file=sys.stderr)
        sup.stop()
        if stats is not None:
            stats.stop()
        return 1
    for name, doc in sup.services().items():
        print(
            f"{name}: {doc['state']} on port {doc['port']} (pid {doc['pid']})"
        )
    if retrain is not None:
        mode = "SLO-adaptive" if retrain.slo_driven else "fixed"
        print(
            f"retrain: every {retrain.base_interval_s:.0f}s ({mode}) -> "
            f"{len(retrain.engine_ports)} engine(s)"
        )
    print(f"Run dir: {daemon.run_dir()} (supervised; ^C or SIGTERM to stop)")
    try:
        sup.run()
    finally:
        if stats is not None:
            stats.stop()
    return 0


def cmd_rolling_restart(args) -> int:
    """``pio rolling-restart <service>``: zero-downtime replacement of a
    recorded daemon — new instance overlaps on the same port via
    SO_REUSEPORT, must pass /readyz, then the old one drains out.

    ``pio rolling-restart engineserver`` walks the whole engine replica
    set (``engine`` and every ``engine-<i>``) ONE replica at a time: a
    router tier in front keeps serving off the others while each rolls,
    so the fleet upgrades with zero failed requests."""
    import re

    from predictionio_tpu.cli import daemon

    if args.service in ("engineserver", "engines"):
        names = [
            n for n in daemon.known_services()
            if n == "engine" or re.fullmatch(r"engine-\d+", n)
        ]
        if not names:
            print(
                "rolling-restart: no running engine replicas recorded",
                file=sys.stderr,
            )
            return 1
    else:
        names = [args.service]
    for name in names:
        try:
            info = daemon.rolling_restart(name, wait=args.wait)
        except RuntimeError as e:
            print(f"rolling-restart: {e}", file=sys.stderr)
            return 1
        print(
            f"{info['service']}: rolled pid {info['old_pid']} -> "
            f"{info['new_pid']} on port {info['port']} "
            f"(instance {info['instance']})"
        )
    return 0


def cmd_stop_all(args) -> int:
    """Tear down everything start-all recorded (reference bin/pio-stop-all)."""
    from predictionio_tpu.cli import daemon

    stopped = 0
    # reverse bring-up order: engine first, event server last
    for name in reversed(daemon.known_services()):
        if daemon.stop_service(name):
            print(f"{name}: stopped")
            stopped += 1
    if not stopped:
        print("Nothing to stop.")
    return 0


def cmd_unregister(args) -> int:
    # engine registration is implicit for Python factories (import-by-name,
    # no registry rows to delete) — no-op parity with Console.scala's
    # unregister verb
    print("Nothing to unregister: Python engine factories are resolved by import.")
    return 0


def cmd_shell(args) -> int:
    """Interactive REPL with the storage singleton wired (the pio-shell
    analog, bin/pio-shell — a Spark shell with pio jars preloaded)."""
    import code

    from predictionio_tpu.data import store
    from predictionio_tpu.data.storage import get_storage

    banner = (
        "predictionio-tpu shell\n"
        "  storage  -> configured Storage singleton\n"
        "  store    -> event-store facade (find/aggregate_properties)\n"
    )
    code.interact(
        banner=banner, local={"storage": get_storage(), "store": store}
    )
    return 0


def cmd_template(args) -> int:
    # deprecated no-op in the reference too (Console.scala template verbs)
    print(
        "The template command is deprecated; engine templates are Python "
        "packages — copy one from predictionio_tpu.models as a starting point."
    )
    return 0


def cmd_upgrade(args) -> int:
    # disabled in the reference too (Console.scala: "Upgrade is not
    # available"); storage-format migrations here go through
    # `pio export` + `pio import`
    print(
        "Upgrade is not available; migrate data between storage formats "
        "with `pio export` and `pio import`."
    )
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="pio", description="PredictionIO-TPU console")
    sub = p.add_subparsers(dest="command")

    sub.add_parser("version").set_defaults(fn=cmd_version)
    st = sub.add_parser("status")
    st.add_argument(
        "--json",
        action="store_true",
        help="one compact JSON line merging /metrics + /stats.json "
        "from running daemons",
    )
    st.set_defaults(fn=cmd_status)

    bc = sub.add_parser("bench")
    bc.add_argument(
        "--compare", nargs="+", metavar="SUMMARY.json",
        help="diff two bench summary JSONs (OLD [NEW]; NEW defaults to "
        "the newest BENCH_r*.json in the cwd) and exit non-zero on any "
        ">tolerance regression",
    )
    bc.add_argument(
        "--tolerance", type=float, default=0.10,
        help="relative change treated as a regression (default 0.10)",
    )
    bc.set_defaults(fn=cmd_bench)

    pr = sub.add_parser("profile")
    pr.add_argument(
        "--seconds", type=float, default=5.0,
        help="capture window (clamped to 120s)",
    )
    pr.add_argument(
        "--url",
        help="POST /profile on a running daemon (e.g. "
        "http://127.0.0.1:8000) instead of capturing in-process",
    )
    pr.add_argument(
        "--out", help="trace output directory (default: a timestamped "
        "dir under $PIO_RUN_DIR/profiles)",
    )
    pr.set_defaults(fn=cmd_profile)

    inc = sub.add_parser("incidents")
    incsub = inc.add_subparsers(dest="incidents_command")
    incl = incsub.add_parser("list")
    incl.add_argument(
        "--json", action="store_true",
        help="machine-readable bundle listing",
    )
    incs = incsub.add_parser("show")
    incs.add_argument("name", help="bundle directory name (see list)")
    incs.add_argument(
        "--file",
        help="print one bundle file verbatim (e.g. slo.json, traces.json)",
    )
    incp = incsub.add_parser("prune")
    incp.add_argument(
        "--keep", type=int, default=None,
        help="bundles to retain (default $PIO_INCIDENT_KEEP or 20)",
    )
    inc.set_defaults(fn=cmd_incidents)

    tp = sub.add_parser("top")
    tp.add_argument(
        "--once", action="store_true",
        help="print one frame and exit (scripting/tests)",
    )
    tp.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh interval in seconds (default 2)",
    )
    tp.add_argument(
        "--url", action="append",
        help="poll this base URL instead of discovering live daemons "
        "(repeatable, e.g. http://127.0.0.1:8000)",
    )
    tp.set_defaults(fn=cmd_top)

    b = sub.add_parser("build")
    b.add_argument("--engine-factory")
    b.add_argument("--variant")
    b.add_argument("--engine-dir")
    b.set_defaults(fn=cmd_build)

    a = sub.add_parser("app")
    asub = a.add_subparsers(dest="app_command")
    for name in ("new", "show", "delete", "data-delete"):
        ap = asub.add_parser(name)
        ap.add_argument("name")
        if name == "new":
            ap.add_argument("--id", type=int, default=0)
            ap.add_argument("--description")
            ap.add_argument("--access-key", default="")
        if name == "data-delete":
            ap.add_argument("--channel")
    asub.add_parser("list")
    for name in ("channel-new", "channel-delete"):
        cp = asub.add_parser(name)
        cp.add_argument("name")
        cp.add_argument("channel")
    a.set_defaults(fn=cmd_app)

    ak = sub.add_parser("accesskey")
    aksub = ak.add_subparsers(dest="ak_command")
    akn = aksub.add_parser("new")
    akn.add_argument("app_name")
    akn.add_argument("--event", action="append")
    akl = aksub.add_parser("list")
    akl.add_argument("app_name", nargs="?")
    akd = aksub.add_parser("delete")
    akd.add_argument("key")
    ak.set_defaults(fn=cmd_accesskey)

    t = sub.add_parser("train")
    t.add_argument("--engine-factory")
    t.add_argument("--variant")
    t.add_argument("--engine-dir")
    t.add_argument("--batch", default="")
    t.add_argument("--verbose", action="count", default=0)
    t.add_argument("--skip-sanity-check", action="store_true")
    t.add_argument("--stop-after-read", action="store_true")
    t.add_argument("--stop-after-prepare", action="store_true")
    t.add_argument("--profile-dir", help="write a JAX profiler trace here")
    t.add_argument(
        "--profile",
        dest="profile_dir",
        metavar="DIR",
        help="alias for --profile-dir: wrap the training loop in "
        "jax.profiler.trace and write the trace to DIR",
    )
    t.add_argument(
        "--mesh",
        help="device-mesh axes for the training run, e.g. 'data=8' or "
        "'data=4,model=2' (-1 once absorbs remaining devices)",
    )
    t.add_argument(
        "--multihost", action="store_true",
        help="join a multi-host JAX runtime before training: run the "
        "same command on every host (TPU pod slices auto-detect; "
        "elsewhere pass --coordinator/--num-processes/--process-id or "
        "the PIO_COORDINATOR_ADDRESS/PIO_NUM_PROCESSES/PIO_PROCESS_ID "
        "env vars); --mesh axes then span the global device set",
    )
    t.add_argument("--coordinator", help="host:port of process 0")
    t.add_argument("--num-processes", type=int)
    t.add_argument("--process-id", type=int)
    t.add_argument(
        "--no-columnar-cache", action="store_true",
        help="read training events from the row logs instead of the "
        "columnar segment cache (sets PIO_COLUMNAR_CACHE=0 for this run)",
    )
    t.add_argument(
        "--checkpoint-every", type=int, metavar="N",
        help="snapshot the ALS factor carry atomically every N "
        "iterations so a killed run can resume (sets "
        "PIO_CHECKPOINT_EVERY; see docs/robustness.md)",
    )
    t.add_argument(
        "--resume", action="store_true",
        help="restore the latest checkpoint whose data fingerprint "
        "matches this run and continue bit-identically from its "
        "iteration (sets PIO_RESUME=1; no-op when none matches)",
    )
    t.add_argument(
        "--checkpoint-dir", metavar="DIR",
        help="where checkpoints live (sets PIO_CHECKPOINT_DIR; "
        "default ~/.pio_tpu/checkpoints)",
    )
    t.add_argument(
        "--warm-start", action="store_true",
        help="seed the solve from the latest COMPLETED instance's "
        "model instead of random factors (sets PIO_WARM_START=1; an "
        "incompatible previous model — changed rank or storage dtype — "
        "falls back to cold start with a warning; docs/operations.md "
        "hot-retrain runbook)",
    )
    t.add_argument(
        "--tol", type=float, metavar="T",
        help="stop iterating when the per-segment train RMSE improves "
        "by less than T (sets PIO_TOL; rides the checkpoint-segmented "
        "dispatch, so combine with --warm-start to turn a good starting "
        "point into fewer iterations)",
    )
    t.add_argument(
        "--no-prep-cache", action="store_true",
        help="skip the packed-prep cache and rebuild the training "
        "representation from the event log (sets PIO_PREP_CACHE=0; "
        "docs/storage.md \"Packed-prep cache\")",
    )
    t.add_argument(
        "--prep-cache-dir", metavar="DIR",
        help="where packed-prep cache entries live (sets "
        "PIO_PREP_CACHE_DIR; default ~/.pio_tpu/prep_cache)",
    )
    t.set_defaults(fn=cmd_train)

    ev = sub.add_parser("eval")
    ev.add_argument("evaluation_class")
    ev.add_argument("engine_params_generator_class", nargs="?")
    ev.add_argument("--batch", default="")
    ev.set_defaults(fn=cmd_eval)

    d = sub.add_parser("deploy")
    d.add_argument("--engine-factory")
    d.add_argument("--variant")
    d.add_argument("--engine-dir")
    d.add_argument("--engine-instance-id")
    d.add_argument("--ip", default="0.0.0.0")
    d.add_argument("--port", type=int, default=8000)
    d.add_argument("--feedback", action="store_true")
    d.add_argument("--event-server-ip", default="0.0.0.0")
    d.add_argument("--event-server-port", type=int, default=7070)
    d.add_argument("--accesskey")
    d.add_argument("--server-config", help="server.conf path (key auth / SSL)")
    d.add_argument(
        "--log-url",
        help="POST serving errors to this URL (reference --log-url)",
    )
    d.add_argument(
        "--log-prefix", help="prefix prepended to remote log payloads"
    )
    d.add_argument(
        "--batch-window-ms", type=float, default=0.0,
        help="micro-batch concurrent queries for up to this many ms into "
        "one batched device call (0 = per-request serving); amortizes "
        "per-call dispatch on TPU attachments",
    )
    d.add_argument(
        "--workers", type=int, default=1,
        help="run this many server PROCESSES sharing the port via "
        "SO_REUSEPORT (the kernel balances accepts); scales serving "
        "past one interpreter's GIL. Needs an explicit --port.",
    )
    d.add_argument(
        "--reuse-port", action="store_true",
        help="bind with SO_REUSEPORT (set automatically for workers; "
        "useful when an external supervisor runs the processes)",
    )
    d.add_argument(
        "--query-cache-mb", type=float, default=0.0, metavar="MB",
        help="cache preserialized query responses in this many MB, "
        "invalidated exactly on every /reload and speed-layer patch via "
        "the epoch fence (0 = disabled); engines opt out per query via "
        "cacheable_query — see docs/serving.md",
    )
    d.add_argument(
        "--no-warmup", action="store_true",
        help="skip the deploy-time throwaway predict that pre-compiles "
        "the scoring programs before the port binds",
    )
    d.add_argument(
        "--realtime", type=float, default=0.0, metavar="SECONDS",
        help="enable the speed layer: tail the app's event stream every "
        "SECONDS and fold new rating events into the live model between "
        "retrains (0 = batch-only serving); see docs/realtime.md",
    )
    d.add_argument(
        "--realtime-cursor",
        help="durable tailer cursor file (default: "
        "~/.pio_tpu/realtime/cursor_<engine>_<port>.json)",
    )
    d.add_argument(
        "--variants", metavar="A.JSON,B.JSON",
        help="mount additional trained engine variants in THIS process, "
        "routed by path prefix (/<name>/queries.json, name = file "
        "basename minus .json) or the X-PIO-Variant header; each mount "
        "keeps its own epoch fence, /reload, and speed layer while "
        "sharing the HTTP front end, micro-batcher, and jit cache — "
        "see docs/serving.md",
    )
    d.set_defaults(fn=cmd_deploy)

    u = sub.add_parser("undeploy")
    u.add_argument("--ip", default="0.0.0.0")
    u.add_argument("--port", type=int, default=8000)
    u.set_defaults(fn=cmd_undeploy)

    es = sub.add_parser("eventserver")
    es.add_argument("--ip", default="0.0.0.0")
    es.add_argument("--port", type=int, default=7070)
    es.add_argument("--stats", action="store_true")
    es.add_argument(
        "--workers", type=int, default=1,
        help="run this many ingest PROCESSES sharing the port via "
        "SO_REUSEPORT (storage appends are cross-process safe); needs "
        "an explicit --port",
    )
    es.add_argument("--reuse-port", action="store_true")
    es.set_defaults(fn=cmd_eventserver)

    ad = sub.add_parser("adminserver")
    ad.add_argument("--ip", default="0.0.0.0")
    ad.add_argument("--port", type=int, default=7071)
    ad.set_defaults(fn=cmd_adminserver)

    ss = sub.add_parser("storageserver")
    ss.add_argument("--ip", default="0.0.0.0")
    ss.add_argument("--port", type=int, default=7072)
    ss.add_argument("--auth-key", help="shared key clients must present")
    ss.add_argument("--server-config", help="server.conf path (TLS)")
    ss.set_defaults(fn=cmd_storageserver)

    db = sub.add_parser("dashboard")
    db.add_argument("--ip", default="0.0.0.0")
    db.add_argument("--port", type=int, default=9000)
    db.add_argument("--server-config", help="server.conf path (key auth / SSL)")
    db.set_defaults(fn=cmd_dashboard)

    rt = sub.add_parser(
        "route",
        help="scale-out router tier over a set of engine replicas",
    )
    rt.add_argument("--ip", default="0.0.0.0")
    rt.add_argument("--port", type=int, default=8100)
    rt.add_argument(
        "--replica", action="append", metavar="[NAME=]HOST:PORT",
        help="one backend engine replica (repeatable)",
    )
    rt.add_argument(
        "--replicas", type=int, default=0, metavar="N",
        help="route to N replicas on consecutive ports starting at "
        "--engine-port (names engine-0..engine-N-1, matching what "
        "`pio start-all --replicas N` spawns)",
    )
    rt.add_argument("--engine-host", default="127.0.0.1")
    rt.add_argument("--engine-port", type=int, default=8000)
    rt.add_argument(
        "--probe-interval", type=float, default=0.0, metavar="SECONDS",
        help="replica /readyz probe interval (default "
        "PIO_ROUTER_PROBE_INTERVAL_S or 1.0)",
    )
    rt.add_argument(
        "--no-hedge", action="store_true",
        help="disable hedged requests (equivalent to PIO_ROUTER_HEDGE=0)",
    )
    rt.add_argument("--reuse-port", action="store_true")
    rt.set_defaults(fn=cmd_route)

    ex = sub.add_parser("export")
    ex.add_argument("--appid-or-name", required=True)
    ex.add_argument("--output", required=True)
    ex.add_argument("--channel")
    ex.set_defaults(fn=cmd_export)

    im = sub.add_parser("import")
    im.add_argument("--appid-or-name", required=True)
    im.add_argument("--input", required=True)
    im.add_argument("--channel")
    im.add_argument(
        "--jobs", type=int, default=None,
        help="decode/append worker threads for the bulk import "
        "(default: PIO_IMPORT_JOBS env or min(4, cpus); 1 = sequential)",
    )
    im.add_argument(
        "--warm-cache", action="store_true",
        help="build the columnar segment cache right after the import "
        "so the first train reads mmap'ed column blocks",
    )
    im.add_argument(
        "--http", metavar="URL", default=None,
        help="import over the wire: POST the file as binary frames to "
        "URL/batch/events.bin on a live event server instead of writing "
        "storage directly (requires --access-key)",
    )
    im.add_argument(
        "--access-key", default=None,
        help="access key for --http mode (the target app's key)",
    )
    im.set_defaults(fn=cmd_import)

    tpl = sub.add_parser("template")
    tpl.add_argument("rest", nargs="*")
    tpl.set_defaults(fn=cmd_template)

    up = sub.add_parser("upgrade")
    up.add_argument("rest", nargs="*")
    up.set_defaults(fn=cmd_upgrade)

    r = sub.add_parser("run")
    r.add_argument("main_class", help="dotted module path, or module:function")
    r.add_argument("args", nargs="*")
    r.set_defaults(fn=cmd_run)

    def _fleet_args(parser) -> None:
        parser.add_argument("--ip", default="0.0.0.0")
        parser.add_argument("--event-port", type=int, default=7070)
        parser.add_argument("--dashboard-port", type=int, default=9000)
        parser.add_argument("--admin-port", type=int, default=7071)
        parser.add_argument("--engine-port", type=int, default=8000)
        parser.add_argument("--stats", action="store_true")
        parser.add_argument("--no-dashboard", action="store_true")
        parser.add_argument("--no-adminserver", action="store_true")
        parser.add_argument("--variant", help="also deploy this engine variant")
        parser.add_argument(
            "--engine-factory", help="also deploy this engine factory"
        )
        parser.add_argument(
            "--engine-dir", help="also deploy the engine in this dir"
        )
        parser.add_argument(
            "--variants", metavar="A.JSON,B.JSON",
            help="co-mount these trained engine variants in the "
            "deployed engine process (see pio deploy --variants)",
        )
        parser.add_argument(
            "--supervise-port", type=int, default=0,
            help="with --supervise: serve supervisor /stats.json and "
            "/metrics on this port",
        )
        parser.add_argument(
            "--replicas", type=int, default=0, metavar="N",
            help="deploy the engine as N replicas on consecutive ports "
            "starting at --engine-port, fronted by the `pio route` "
            "router tier on --router-port (see docs/operations.md "
            "\"Scale-out serving\")",
        )
        parser.add_argument(
            "--router-port", type=int, default=8100,
            help="router-tier port used with --replicas",
        )
        parser.add_argument(
            "--retrain-every", metavar="DUR", default=None,
            help="with --supervise: run a warm `pio train` + engine "
            "/reload on this cadence (e.g. 300s, 15m, 1h; see "
            "docs/operations.md \"Continuous retraining\")",
        )
        parser.add_argument(
            "--retrain-slo", action="store_true",
            help="adapt the retrain cadence to the serving.freshness "
            "SLO burn rate (halve while burning, decay back when ok)",
        )
        parser.add_argument(
            "--retrain-floor", metavar="DUR", default=None,
            help="shortest adaptive retrain interval "
            "(default: --retrain-every / 8)",
        )
        parser.add_argument(
            "--retrain-tol", type=float, default=None, metavar="T",
            help="pass --tol T to the scheduled warm trains "
            "(early-stop on an RMSE plateau)",
        )

    sa = sub.add_parser("start-all")
    _fleet_args(sa)
    sa.add_argument(
        "--supervise", action="store_true",
        help="stay in the foreground and restart crashed services "
        "with backoff (see docs/operations.md)",
    )
    sa.set_defaults(fn=cmd_start_all)

    sv = sub.add_parser(
        "supervise", help="start-all under the self-healing supervisor"
    )
    _fleet_args(sv)
    sv.set_defaults(fn=cmd_start_all, supervise=True)

    rr = sub.add_parser(
        "rolling-restart",
        help="zero-downtime replacement of one recorded service",
    )
    rr.add_argument("service", help="a service name from `pio status`")
    rr.add_argument(
        "--wait", type=float, default=90.0,
        help="seconds to wait for the replacement's /readyz (default 90)",
    )
    rr.set_defaults(fn=cmd_rolling_restart)

    ca = sub.add_parser(
        "cache", help="packed-prep cache lifecycle (list / evict / prune)"
    )
    casub = ca.add_subparsers(dest="cache_verb")
    cl = casub.add_parser("list", help="entries, LRU order, sizes")
    cl.add_argument("--json", action="store_true")
    ce = casub.add_parser("evict", help="drop one entry by name")
    ce.add_argument("entry", help="entry name from `pio cache list`")
    cp = casub.add_parser(
        "prune", help="sweep tmp husks + enforce the size budget"
    )
    cp.add_argument(
        "--max-mb", type=float, default=None,
        help="override PIO_PREP_CACHE_MAX_MB for this prune",
    )
    cp.add_argument("--json", action="store_true")
    ca.set_defaults(fn=cmd_cache)

    sub.add_parser("stop-all").set_defaults(fn=cmd_stop_all)

    sub.add_parser("unregister").set_defaults(fn=cmd_unregister)
    sub.add_parser("shell").set_defaults(fn=cmd_shell)

    return p


def main(argv: list[str] | None = None) -> int:
    from predictionio_tpu.utils import apply_platform_env

    apply_platform_env()  # honor JAX_PLATFORMS even under plugin boot hooks
    parser = build_parser()
    raw = sys.argv[1:] if argv is None else list(argv)
    if raw[:1] == ["help"]:
        parser.print_help()
        return 0
    args = parser.parse_args(raw)
    if not getattr(args, "fn", None):
        parser.print_help()
        return 1
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
