"""`pio` CLI entry point (reference tools/.../console/Console.scala:78).

Verbs land here incrementally; unknown verbs print usage and exit 1.
"""

from __future__ import annotations

import sys


USAGE = """pio <command> [options]

Commands (TPU-native PredictionIO):
  status                     check storage configuration
  version                    print version

Run 'pio <command> --help' for command help."""


def main(argv: list[str] | None = None) -> int:
    args = sys.argv[1:] if argv is None else list(argv)
    if not args or args[0] in ("help", "--help", "-h"):
        print(USAGE)
        return 0
    verb = args[0]
    if verb == "version":
        from predictionio_tpu import __version__

        print(__version__)
        return 0
    if verb == "status":
        from predictionio_tpu.data.storage import REPOSITORIES, get_storage

        storage = get_storage()
        storage.verify_all_data_objects()
        for repo in REPOSITORIES:
            name, typ = storage.repository_source(repo)
            print(f"{repo}: source={name} type={typ}")
        print("(sanity check) All storage repositories verified.")
        return 0
    print(f"pio: unknown command {verb!r}", file=sys.stderr)
    print(USAGE, file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
