"""``pio bench --compare OLD.json [NEW.json]``: regression-gate two
bench summary lines.

The BENCH_r* trajectory (bench.py's compact summary, one JSON object
per run) records every measured rate and latency the project gates on;
this module turns any two of them into a pass/fail diff. Numeric leaves
are matched by path, direction is inferred from the key name (a `_ms`
is lower-better, a `_per_s` higher-better), and any leaf that moved
more than ``tolerance`` in the bad direction is a regression — the CLI
exits non-zero so CI can gate on it.

Dependency-free on purpose: it must run on an operator laptop holding
two JSON files and nothing else.
"""

from __future__ import annotations

import json
import re

__all__ = ["compare", "leaf_direction", "format_report", "main"]

# direction heuristics, checked in order against the LAST path segment
# (lowercased). First match wins; unmatched numeric leaves are compared
# informationally but never flagged.
_LOWER_BETTER = (
    "_ms", "_s", "_us", "_ns", "_seconds", "p50", "p99", "p90",
    "latency", "behind", "rss", "overhead", "cost", "lost", "rmse",
    "compiles", "_pct", "failed", "restarts", "retries", "ejections",
    "wall_ratio", "rebuilds", "failures", "evictions",
)
_HIGHER_BETTER = (
    "per_s", "qps", "speedup", "events", "throughput", "hit_rate",
    "ratio_ok", "recall", "win_ratio", "scaling_ratio", "saved",
    "reuse",
)
# keys that are config/identity, not measurements
_SKIP = (
    "value", "conns", "clients", "workers", "batch_size", "cores",
    "acked", "n", "count", "rounds", "budget", "objective", "seed",
    "port", "pid", "capacity", "scale", "tenants", "variants",
    "replicas", "hedges", "iterations",
)


def leaf_direction(key: str) -> str | None:
    """'lower' / 'higher' / None (informational) for a leaf key."""
    k = key.lower()
    if any(k == s or k.endswith("_" + s) or k == s.rstrip("_") for s in _SKIP):
        return None
    for pat in _HIGHER_BETTER:
        if pat in k:
            return "higher"
    for pat in _LOWER_BETTER:
        if k.endswith(pat) or pat in k:
            return "lower"
    return None


def _numeric_leaves(doc, path=""):
    if isinstance(doc, dict):
        for k, v in doc.items():
            yield from _numeric_leaves(v, f"{path}.{k}" if path else str(k))
    elif isinstance(doc, bool):
        return  # booleans are gates, not measurements
    elif isinstance(doc, (int, float)):
        yield path, float(doc)


def compare(old: dict, new: dict, tolerance: float = 0.10) -> dict:
    """Diff two bench summary docs. Returns ``{regressions,
    improvements, compared, missing}`` where each regression names the
    leaf path, both values, and the signed change fraction."""
    old_leaves = dict(_numeric_leaves(old))
    new_leaves = dict(_numeric_leaves(new))
    regressions, improvements, compared = [], [], 0
    for path, old_v in sorted(old_leaves.items()):
        if path not in new_leaves:
            continue
        new_v = new_leaves[path]
        direction = leaf_direction(path.rsplit(".", 1)[-1])
        if direction is None:
            continue
        compared += 1
        if old_v == 0.0:
            # can't express relative change from zero; a nonzero
            # lower-better value appearing IS a regression signal
            if direction == "lower" and new_v > 0.0:
                regressions.append({
                    "path": path, "old": old_v, "new": new_v,
                    "change_pct": None,
                })
            continue
        change = (new_v - old_v) / abs(old_v)
        worse = change > tolerance if direction == "lower" \
            else change < -tolerance
        better = change < -tolerance if direction == "lower" \
            else change > tolerance
        row = {
            "path": path, "old": old_v, "new": new_v,
            "change_pct": round(change * 100.0, 1),
        }
        if worse:
            regressions.append(row)
        elif better:
            improvements.append(row)
    return {
        "regressions": regressions,
        "improvements": improvements,
        "compared": compared,
        "missing": sorted(set(old_leaves) - set(new_leaves)),
        "tolerance_pct": round(tolerance * 100.0, 1),
    }


def format_report(report: dict, old_name: str, new_name: str) -> str:
    lines = [
        f"bench compare: {old_name} -> {new_name} "
        f"({report['compared']} leaves, "
        f"tolerance {report['tolerance_pct']}%)"
    ]
    for r in report["regressions"]:
        pct = "n/a" if r["change_pct"] is None else f"{r['change_pct']:+}%"
        lines.append(
            f"  REGRESSION {r['path']}: {r['old']} -> {r['new']} ({pct})"
        )
    for r in report["improvements"]:
        lines.append(
            f"  improved   {r['path']}: {r['old']} -> {r['new']} "
            f"({r['change_pct']:+}%)"
        )
    if not report["regressions"] and not report["improvements"]:
        lines.append("  no change beyond tolerance")
    lines.append(
        f"{len(report['regressions'])} regression(s), "
        f"{len(report['improvements'])} improvement(s)"
    )
    return "\n".join(lines)


def _recover_truncated(text: str) -> dict:
    """Salvage a JSON capture cut mid-object (the checked-in BENCH_r*
    artifacts keep only the tail of stdout, so the detail line usually
    lost its opening braces). Re-parse every ``"key": value`` pair whose
    value still decodes and rebuild a doc from them; nested sections
    come back under their own key so leaf paths line up with a fully
    parsed run. Later duplicates win, matching JSON object semantics."""
    dec = json.JSONDecoder()
    doc: dict = {}
    for m in re.finditer(r'"([A-Za-z0-9_.\-]+)"\s*:\s*', text):
        try:
            val, _ = dec.raw_decode(text, m.end())
        except ValueError:
            continue
        if isinstance(val, bool):
            continue
        if isinstance(val, (dict, int, float)):
            doc[m.group(1)] = val
    return doc


def _parse_text(text: str) -> dict | None:
    """Whole text, else LAST parseable JSON line (the compact summary
    by bench.py convention), else truncation salvage. None if nothing
    numeric survives."""
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            return doc
    except ValueError:
        pass
    for line in reversed(text.strip().splitlines()):
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict):
            return doc
    salvaged = _recover_truncated(text)
    return salvaged or None


def _load_summary(path: str) -> dict:
    """A bench artifact may be the full-detail line, the compact line, a
    file holding both, or a driver wrapper ``{"cmd", "rc", "tail"}``
    whose ``tail`` string carries a (possibly truncated) copy of the
    bench stdout — unwrap and parse whatever measurement data is
    actually there."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    doc = _parse_text(text)
    if doc is None:
        raise ValueError(f"{path}: no parseable JSON found")
    tail = doc.get("tail")
    if isinstance(tail, str):
        inner = _parse_text(tail)
        if inner:
            return inner
    return doc


def main(old_path: str, new_path: str | None = None,
         tolerance: float = 0.10) -> int:
    """CLI entry; returns the process exit code (non-zero on
    regression). When NEW is omitted, picks the newest ``BENCH_r*.json``
    in the current directory that is not OLD."""
    import glob
    import os

    if new_path is None:
        candidates = sorted(
            (p for p in glob.glob("BENCH_r*.json")
             if os.path.abspath(p) != os.path.abspath(old_path)),
            key=os.path.getmtime,
        )
        if not candidates:
            print("bench compare: no NEW given and no BENCH_r*.json found")
            return 2
        new_path = candidates[-1]
    old = _load_summary(old_path)
    new = _load_summary(new_path)
    report = compare(old, new, tolerance=tolerance)
    print(format_report(report, old_path, new_path))
    return 1 if report["regressions"] else 0
