"""One-shot bring-up / teardown of the pio service fleet.

Parity with the reference's ops scripts (bin/pio-start-all,
bin/pio-stop-all, bin/pio-daemon): ``pio start-all`` launches the event
server (and optionally dashboard, admin server, and a deployed engine)
as detached OS processes with pid files and per-service logs under a run
directory; ``pio stop-all`` terminates whatever the pid files point at.
The reference's scripts additionally start HBase/Elasticsearch — external
JVM services with no analog here; storage backends in this framework are
in-process (sqlite/jsonl/localfs) or already-running remote services.

Pid files live in ``$PIO_RUN_DIR`` (default ``~/.pio_tpu/run``); each
service writes ``<name>.pid`` and logs to ``<name>.log``. Stale pid
files (process already gone) are cleaned up on both verbs.
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

# service name -> default port (matching the reference's defaults:
# event server :7070, dashboard :9000, admin :7071; engine :8000; the
# router tier fronts engine replicas on :8100)
DEFAULT_PORTS = {
    "eventserver": 7070,
    "dashboard": 9000,
    "adminserver": 7071,
    "engine": 8000,
    "router": 8100,
}


def service_port(name: str) -> int:
    """The port a named service actually listens on: its service record
    (written at start) wins — replica-set members (``engine-0``,
    ``engine-1``, ...) have no DEFAULT_PORTS entry — falling back to the
    static default, then 0 for the unknown."""
    rec = read_service_record(name)
    if rec is not None:
        try:
            return int(rec.get("port") or 0)
        except (TypeError, ValueError):
            pass
    return DEFAULT_PORTS.get(name, 0)


def run_dir() -> Path:
    d = Path(os.environ.get("PIO_RUN_DIR", "~/.pio_tpu/run")).expanduser()
    d.mkdir(parents=True, exist_ok=True)
    return d


def service_env() -> dict[str, str]:
    """Environment for spawned services: the operator's env plus
    persistent cache defaults so every child of one fleet shares them —
    ``PIO_COMPILATION_CACHE_DIR`` (under the run dir) so `pio start-all`
    restarts skip XLA recompiles, and ``PIO_PREP_CACHE_DIR`` (the
    resolved prep-cache dir) so supervisor-scheduled warm retrains hit
    the same packed-prep entries the deploy-time train published. An
    explicit env var (even empty, to disable) wins."""
    env = dict(os.environ)
    if "PIO_COMPILATION_CACHE_DIR" not in env:
        cache = run_dir() / "jit_cache"
        cache.mkdir(parents=True, exist_ok=True)
        env["PIO_COMPILATION_CACHE_DIR"] = str(cache)
    if "PIO_PREP_CACHE_DIR" not in env:
        from predictionio_tpu.core import prep_cache

        env["PIO_PREP_CACHE_DIR"] = str(prep_cache.cache_dir())
    return env


def _pid_file(name: str) -> Path:
    return run_dir() / f"{name}.pid"


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def read_pid(name: str) -> int | None:
    """Pid from the pid file, or None; drops the file if the pid is dead."""
    pf = _pid_file(name)
    if not pf.exists():
        return None
    try:
        pid = int(pf.read_text().strip())
    except ValueError:
        pf.unlink(missing_ok=True)
        return None
    if not _alive(pid):
        pf.unlink(missing_ok=True)
        return None
    return pid


def wait_port(host: str, port: int, timeout: float = 30.0) -> bool:
    """Poll until something accepts TCP connections on (host, port)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, port), timeout=1.0):
                return True
        except OSError:
            time.sleep(0.1)
    return False


def _http_get_json(host: str, port: int, path: str,
                   timeout: float = 2.0) -> dict | None:
    """One GET returning the parsed JSON body (any status), or None when
    nothing answers / the answer isn't JSON (foreign listener)."""
    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
        finally:
            conn.close()
        doc = json.loads(body)
        return doc if isinstance(doc, dict) else None
    except Exception:
        return None


def probe_health(host: str, port: int, timeout: float = 2.0) -> dict | None:
    """``GET /healthz`` — the liveness doc (with the per-boot instance
    id) when one of OUR servers answers, else None."""
    doc = _http_get_json(host, port, "/healthz", timeout=timeout)
    if doc is not None and "instance" in doc:
        return doc
    return None


def probe_ready(host: str, port: int, timeout: float = 2.0) -> dict | None:
    """``GET /readyz`` doc (ready or not), or None when unreachable."""
    doc = _http_get_json(host, port, "/readyz", timeout=timeout)
    if doc is not None and "instance" in doc:
        return doc
    return None


def wait_healthy(
    host: str,
    port: int,
    timeout: float = 30.0,
    proc: subprocess.Popen | None = None,
    not_instance: str | None = None,
) -> dict | None:
    """Poll ``/healthz`` until a live instance answers (optionally one
    whose instance id differs from ``not_instance``). Fails fast when
    ``proc`` exits. Returns the health doc, or None on timeout."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            return None
        doc = probe_health(host, port, timeout=1.0)
        if doc is not None and doc.get("instance") != not_instance:
            return doc
        time.sleep(0.1)
    return None


def wait_ready(
    host: str,
    port: int,
    timeout: float = 60.0,
    proc: subprocess.Popen | None = None,
    not_instance: str | None = None,
) -> dict | None:
    """Poll ``/readyz`` until a ready instance answers (optionally one
    whose instance id differs from ``not_instance`` — the
    rolling-restart handoff condition, where two same-port listeners
    share accepts and probes land on either). Returns the ready doc, or
    None on timeout / ``proc`` death."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            return None
        doc = probe_ready(host, port, timeout=1.0)
        if (
            doc is not None
            and doc.get("ready")
            and doc.get("instance") != not_instance
        ):
            return doc
        time.sleep(0.1)
    return None


def _record_file(name: str) -> Path:
    return run_dir() / f"{name}.json"


def write_service_record(name: str, argv: list[str], host: str, port: int,
                         instance: str | None = None) -> None:
    """Persist how a service was started (argv/host/port/instance) so
    ``pio rolling-restart`` and the supervisor can respawn it verbatim."""
    tmp = _record_file(name).with_suffix(".json.tmp")
    tmp.write_text(json.dumps({
        "name": name, "argv": list(argv), "host": host, "port": port,
        "instance": instance,
    }))
    tmp.replace(_record_file(name))


def read_service_record(name: str) -> dict | None:
    rf = _record_file(name)
    if not rf.exists():
        return None
    try:
        doc = json.loads(rf.read_text())
        return doc if isinstance(doc, dict) else None
    except (ValueError, OSError):
        return None


def spawn_service(name: str, argv: list[str]) -> subprocess.Popen:
    """Spawn one pio verb as a detached child logging to the run dir.
    The caller owns health-waiting and pid-file bookkeeping (the
    supervisor keeps the Popen handle so crashes are reaped with an exit
    status instead of lingering as zombies)."""
    log = open(run_dir() / f"{name}.log", "a")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "predictionio_tpu.cli.main", *argv],
            stdout=log,
            stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL,
            start_new_session=True,  # survives the CLI process and its tty
            env=service_env(),
        )
    finally:
        log.close()
    return proc


def start_service(name: str, argv: list[str], host: str, port: int) -> int:
    """Spawn one pio verb as a detached daemon; returns its pid.

    Comes up via ``/healthz`` rather than a raw TCP connect: probing the
    port BEFORE the spawn detects a foreign/leftover listener up front
    (the old TOCTOU — a reachable port was counted as success no matter
    who owned it), and the health doc's instance id is recorded so later
    probes can tell this boot from any other.

    Raises RuntimeError if a live pid file already exists, the port is
    already owned, or the service does not come up healthy.
    """
    existing = read_pid(name)
    if existing is not None:
        raise RuntimeError(
            f"{name} already running (pid {existing}); `pio stop-all` first"
        )
    pre = probe_health(host, port, timeout=1.0)
    if pre is not None:
        raise RuntimeError(
            f"{name}: {host}:{port} already serving (instance "
            f"{pre.get('instance')}, pid {pre.get('pid')}); "
            "`pio stop-all` first"
        )
    try:
        with socket.create_connection((host, port), timeout=1.0):
            pass
        raise RuntimeError(
            f"{name}: a foreign (non-pio) listener owns {host}:{port}"
        )
    except OSError:
        pass  # nothing listening — the expected case
    proc = spawn_service(name, argv)
    doc = wait_healthy(host, port, timeout=30.0, proc=proc)
    if doc is None:
        if proc.poll() is not None:
            raise RuntimeError(
                f"{name} exited with rc={proc.returncode} before serving "
                f"(see {run_dir() / f'{name}.log'})"
            )
        # same escalation as stop_service: a child mid-startup may defer
        # SIGTERM, finish binding later, and become unstoppable (no pid
        # file) unless we make sure it is gone now
        proc.terminate()
        try:
            proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        raise RuntimeError(
            f"{name} did not answer /healthz on {host}:{port} within 30s "
            f"(see {run_dir() / f'{name}.log'})"
        )
    _pid_file(name).write_text(str(proc.pid))
    write_service_record(name, argv, host, port,
                         instance=doc.get("instance"))
    return proc.pid


def drain_grace() -> float:
    """How long a SIGTERM'd server may take to drain before escalation:
    its drain window plus settle headroom."""
    try:
        drain_s = float(os.environ.get("PIO_DRAIN_TIMEOUT_S", "") or 10.0)
    except ValueError:
        drain_s = 10.0
    return drain_s + 5.0


def stop_service(name: str, grace: float | None = None) -> bool:
    """SIGTERM the service's recorded pid (SIGKILL after ``grace``,
    default the drain window + headroom — SIGTERM now triggers a
    graceful drain, not an immediate exit).

    Returns True if something was stopped.
    """
    pid = read_pid(name)
    if pid is None:
        _record_file(name).unlink(missing_ok=True)
        return False
    if grace is None:
        grace = drain_grace()
    os.kill(pid, signal.SIGTERM)
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if not _alive(pid):
            break
        time.sleep(0.1)
    else:
        os.kill(pid, signal.SIGKILL)
        # SIGKILL is not instantaneous: wait for the process to actually
        # leave the table, or the caller may rebind the port / reuse the
        # name while the old process is still exiting
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if not _alive(pid):
                break
            time.sleep(0.05)
    _pid_file(name).unlink(missing_ok=True)
    _record_file(name).unlink(missing_ok=True)
    return True


def rolling_restart(name: str, wait: float = 90.0) -> dict:
    """Zero-downtime restart: spawn a NEW instance of ``name`` on the
    same port (``SO_REUSEPORT`` — the service must have been started
    with ``--reuse-port``, as ``pio start-all`` does), wait until the
    new instance answers ``/readyz``, then SIGTERM the old one so it
    drains and exits. In-flight requests finish on the old instance;
    drained keep-alive connections reconnect onto the new one.
    """
    rec = read_service_record(name)
    if rec is None:
        raise RuntimeError(
            f"no service record for {name} under {run_dir()} — was it "
            "started with `pio start-all` (or a recent start_service)?"
        )
    old_pid = read_pid(name)
    if old_pid is None:
        raise RuntimeError(
            f"{name} is not running; use `pio start-all` instead"
        )
    host, port = rec["host"], int(rec["port"])
    old_doc = probe_health(host, port, timeout=2.0)
    old_instance = (old_doc or {}).get("instance") or rec.get("instance")
    proc = spawn_service(name, rec["argv"])
    ready = wait_ready(
        host, port, timeout=wait, proc=proc, not_instance=old_instance
    )
    if ready is None:
        detail = (
            f"new {name} exited with rc={proc.returncode} (did the old "
            "instance bind without --reuse-port?)"
            if proc.poll() is not None
            else f"new {name} not ready within {wait}s"
        )
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=drain_grace())
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        raise RuntimeError(
            f"rolling restart aborted, old instance untouched: {detail} "
            f"(see {run_dir() / f'{name}.log'})"
        )
    # the new instance owns accepts from here; drain the old one
    os.kill(old_pid, signal.SIGTERM)
    deadline = time.monotonic() + drain_grace()
    while time.monotonic() < deadline:
        if not _alive(old_pid):
            break
        time.sleep(0.1)
    else:
        os.kill(old_pid, signal.SIGKILL)
        while _alive(old_pid):
            time.sleep(0.05)
    _pid_file(name).write_text(str(proc.pid))
    write_service_record(name, rec["argv"], host, port,
                         instance=ready.get("instance"))
    return {
        "service": name,
        "old_pid": old_pid,
        "new_pid": proc.pid,
        "old_instance": old_instance,
        "instance": ready.get("instance"),
        "port": port,
    }


def known_services() -> list[str]:
    """Service names with live pid files, bring-up order."""
    order = list(DEFAULT_PORTS)
    present = [p.stem for p in run_dir().glob("*.pid")]
    return [n for n in order if n in present] + [
        n for n in sorted(present) if n not in order
    ]
