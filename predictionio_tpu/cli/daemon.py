"""One-shot bring-up / teardown of the pio service fleet.

Parity with the reference's ops scripts (bin/pio-start-all,
bin/pio-stop-all, bin/pio-daemon): ``pio start-all`` launches the event
server (and optionally dashboard, admin server, and a deployed engine)
as detached OS processes with pid files and per-service logs under a run
directory; ``pio stop-all`` terminates whatever the pid files point at.
The reference's scripts additionally start HBase/Elasticsearch — external
JVM services with no analog here; storage backends in this framework are
in-process (sqlite/jsonl/localfs) or already-running remote services.

Pid files live in ``$PIO_RUN_DIR`` (default ``~/.pio_tpu/run``); each
service writes ``<name>.pid`` and logs to ``<name>.log``. Stale pid
files (process already gone) are cleaned up on both verbs.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

# service name -> default port (matching the reference's defaults:
# event server :7070, dashboard :9000, admin :7071; engine :8000)
DEFAULT_PORTS = {
    "eventserver": 7070,
    "dashboard": 9000,
    "adminserver": 7071,
    "engine": 8000,
}


def run_dir() -> Path:
    d = Path(os.environ.get("PIO_RUN_DIR", "~/.pio_tpu/run")).expanduser()
    d.mkdir(parents=True, exist_ok=True)
    return d


def service_env() -> dict[str, str]:
    """Environment for spawned services: the operator's env plus a
    persistent jax compilation cache default (``PIO_COMPILATION_CACHE_DIR``
    under the run dir) so `pio start-all` restarts skip XLA recompiles —
    the deploy warmup's compiles land on disk the first time and every
    later bring-up reuses them. An explicit env var (even empty, to
    disable) wins."""
    env = dict(os.environ)
    if "PIO_COMPILATION_CACHE_DIR" not in env:
        cache = run_dir() / "jit_cache"
        cache.mkdir(parents=True, exist_ok=True)
        env["PIO_COMPILATION_CACHE_DIR"] = str(cache)
    return env


def _pid_file(name: str) -> Path:
    return run_dir() / f"{name}.pid"


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


def read_pid(name: str) -> int | None:
    """Pid from the pid file, or None; drops the file if the pid is dead."""
    pf = _pid_file(name)
    if not pf.exists():
        return None
    try:
        pid = int(pf.read_text().strip())
    except ValueError:
        pf.unlink(missing_ok=True)
        return None
    if not _alive(pid):
        pf.unlink(missing_ok=True)
        return None
    return pid


def wait_port(host: str, port: int, timeout: float = 30.0) -> bool:
    """Poll until something accepts TCP connections on (host, port)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection((host, port), timeout=1.0):
                return True
        except OSError:
            time.sleep(0.1)
    return False


def start_service(name: str, argv: list[str], host: str, port: int) -> int:
    """Spawn one pio verb as a detached daemon; returns its pid.

    Raises RuntimeError if a live pid file already exists or the service
    does not come up on its port.
    """
    existing = read_pid(name)
    if existing is not None:
        raise RuntimeError(
            f"{name} already running (pid {existing}); `pio stop-all` first"
        )
    log = open(run_dir() / f"{name}.log", "a")
    proc = subprocess.Popen(
        [sys.executable, "-m", "predictionio_tpu.cli.main", *argv],
        stdout=log,
        stderr=subprocess.STDOUT,
        stdin=subprocess.DEVNULL,
        start_new_session=True,  # survives the CLI process and its tty
        env=service_env(),
    )
    log.close()
    up = wait_port(host, port, timeout=30.0)
    if proc.poll() is not None:
        # the child died — a reachable port here is some FOREIGN listener
        # (port already taken), not our service; don't claim success
        raise RuntimeError(
            f"{name} exited with rc={proc.returncode} before serving "
            f"(port {port} may be in use; see {run_dir() / f'{name}.log'})"
        )
    if not up:
        # same escalation as stop_service: a child mid-startup may defer
        # SIGTERM, finish binding later, and become unstoppable (no pid
        # file) unless we make sure it is gone now
        proc.terminate()
        try:
            proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        raise RuntimeError(
            f"{name} did not open {host}:{port} within 30s "
            f"(see {run_dir() / f'{name}.log'})"
        )
    _pid_file(name).write_text(str(proc.pid))
    return proc.pid


def stop_service(name: str, grace: float = 10.0) -> bool:
    """SIGTERM the service's recorded pid (SIGKILL after ``grace``).

    Returns True if something was stopped.
    """
    pid = read_pid(name)
    if pid is None:
        return False
    os.kill(pid, signal.SIGTERM)
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if not _alive(pid):
            break
        time.sleep(0.1)
    else:
        os.kill(pid, signal.SIGKILL)
    _pid_file(name).unlink(missing_ok=True)
    return True


def known_services() -> list[str]:
    """Service names with live pid files, bring-up order."""
    order = list(DEFAULT_PORTS)
    present = [p.stem for p in run_dir().glob("*.pid")]
    return [n for n in order if n in present] + [
        n for n in sorted(present) if n not in order
    ]
