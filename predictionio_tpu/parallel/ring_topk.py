"""Ring-sharded batch scoring: serve catalogs too big for one chip.

The reference's answer to "model bigger than one host" is a PAlgorithm
whose RDD-backed model issues a Spark job per query
(MatrixFactorizationModel.recommendProducts, invoked from
examples/scala-parallel-recommendation/custom-prepartor/src/main/scala/
ALSAlgorithm.scala:88) — per-query cluster scatter/gather over TCP. The
TPU-native design keeps item factors **resident and sharded** across the
mesh and moves them over ICI instead:

- item factors are sharded row-wise over the mesh axis; each device holds
  one shard plus that shard's global item ids (and exclusion mask),
- the query batch is sharded over the same axis; queries never move,
- n ring steps: each device scores its local queries against the item
  shard it currently holds, merges a running per-query top-k, then
  ``ppermute``s the item shard (+ ids + mask) to its ring neighbour.

This is the ring-attention communication pattern (stationary Q, rotating
KV — PAPERS.md) applied to retrieval: compute on the current shard fully
overlaps the ICI transfer of the next, so HBM never holds more than
``items/n`` of the catalog and no all_gather materialises the full score
matrix. Per-query top-k merge keeps the working set at [b, k + i_shard].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.ops.als import dequantize_rows
from predictionio_tpu.ops.topk import NEG_INF
from predictionio_tpu.parallel.compat import pcast_varying, shard_map


@functools.partial(
    jax.jit, static_argnames=("k", "mesh", "axis", "normalize", "coarse")
)
def _ring_topk_device(
    queries,  # [B', D] sharded P(axis) on dim 0
    item_factors,  # [I', D] sharded P(axis) on dim 0
    item_ids,  # [I'] int32 sharded P(axis); -1 marks padding
    keep_mask,  # [I'] float32 sharded P(axis); 0 = excluded or padding
    k: int,
    *,
    mesh: Mesh,
    axis: str,
    normalize: bool,
    coarse: bool = False,
):
    n = mesh.shape[axis]
    perm = [(j, (j + 1) % n) for j in range(n)]

    quantized = isinstance(item_factors, tuple)

    def local(q_blk, v_blk, ids_blk, mask_blk):
        if normalize:
            # normalize once before the ring: ppermute only relocates
            # rows, so normalized shards stay normalized as they rotate
            q_blk = q_blk / jnp.maximum(
                jnp.linalg.norm(q_blk, axis=1, keepdims=True), 1e-12
            )
            if quantized:
                # cosine is per-row scale-invariant, so normalization
                # folds INTO the scale (1/||q||): the slab keeps rotating
                # as (int8, f32 scale) and dequantizes to unit rows
                vq, _ = v_blk
                nrm = jnp.linalg.norm(vq.astype(jnp.float32), axis=1)
                v_blk = (vq, 1.0 / jnp.maximum(nrm, 1e-12))
            else:
                v_blk = v_blk / jnp.maximum(
                    jnp.linalg.norm(v_blk, axis=1, keepdims=True), 1e-12
                )

        def step(carry, _):
            v, ids, keep, best_s, best_i = carry
            if quantized and coarse:
                # coarse shortlist pass (ops/retrieval.py): score the
                # int8 slab WITHOUT materializing its dequantized f32
                # copy — the per-row scale factors out of the dot and
                # multiplies back per column. Ranking-equivalent to the
                # dequantized score up to f32 rounding; two-stage
                # serving rescores the shortlist exactly anyway.
                vq, vs = v
                s = (
                    jnp.matmul(
                        q_blk, vq.T.astype(q_blk.dtype),
                        preferred_element_type=jnp.float32,
                    )
                    * vs[None, :]
                )
            else:
                # int8 slabs dequantize per step, right before the
                # matmul: ICI hops stay quantized, scores stay f32
                vd = dequantize_rows(*v) if quantized else v
                s = q_blk @ vd.T  # [b, i] — MXU matmul per ring step
            s = jnp.where(keep[None, :] > 0, s, NEG_INF)
            cand_s = jnp.concatenate([best_s, s], axis=1)
            cand_i = jnp.concatenate(
                [best_i, jnp.broadcast_to(ids[None, :], s.shape)], axis=1
            )
            best_s, idx = jax.lax.top_k(cand_s, k)
            best_i = jnp.take_along_axis(cand_i, idx, axis=1)
            # rotate the shard to the next device; XLA overlaps this
            # ppermute with the next step's matmul
            v = jax.tree_util.tree_map(
                lambda x: jax.lax.ppermute(x, axis, perm), v
            )
            ids = jax.lax.ppermute(ids, axis, perm)
            keep = jax.lax.ppermute(keep, axis, perm)
            return (v, ids, keep, best_s, best_i), None

        b = q_blk.shape[0]
        # constants must be marked device-varying to sit in a shard_map
        # scan carry alongside the ppermute'd (varying) shard arrays
        varying = lambda x: pcast_varying(x, axis)
        init = (
            v_blk,
            ids_blk,
            mask_blk,
            varying(jnp.full((b, k), NEG_INF, q_blk.dtype)),
            varying(jnp.full((b, k), -1, jnp.int32)),
        )
        (_, _, _, best_s, best_i), _ = jax.lax.scan(step, init, None, length=n)
        return best_s, best_i

    v_spec = (P(axis), P(axis)) if quantized else P(axis)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), v_spec, P(axis), P(axis)),
        out_specs=(P(axis), P(axis)),
    )(queries, item_factors, item_ids, keep_mask)


@functools.partial(jax.jit, static_argnames=("sharding",))
def _exclude_on_device(keep_all, exclude_ids, sharding):
    """Scatter excluded ids into the resident keep vector ON DEVICE:
    per-query exclusion without a full-catalog host->device copy.
    Out-of-range padding ids are dropped by the scatter."""
    keep = keep_all.at[exclude_ids].set(0.0, mode="drop")
    return jax.lax.with_sharding_constraint(keep, sharding)


class RingCatalog:
    """An item catalog staged sharded on the mesh, reusable across queries.

    The [I, D] factor matrix (the big, query-independent array; dense
    f32/bf16 or the int8 (values, scales) pair of storage_dtype="int8")
    is padded, sharded, and transferred to the mesh ONCE at construction; per-query
    work only ships the [B, D] query batch and (with ``exclude_ids``) a
    small padded id list over PCIe — the exclusion mask is built ON
    DEVICE by scattering those ids into the resident keep vector, so a
    10^7-item catalog never moves a [I] vector per query. This is what
    "factors resident and sharded" means for a deployed server — without
    it every request would re-stage catalog-sized data host-to-device.
    (``exclude_mask`` remains for callers that already hold a full mask;
    it pays the full [I] transfer.)
    """

    def __init__(self, item_factors, mesh: Mesh, axis: str = "data"):
        quantized = isinstance(item_factors, tuple)
        if quantized:
            # int8 catalog: (values [I, D], per-row f32 scales [I]) from
            # storage_dtype="int8" training — staged AND rotated in
            # quantized form, 4x less HBM and ICI than f32
            vq = np.asarray(item_factors[0], dtype=np.int8)
            vs = np.asarray(item_factors[1], dtype=np.float32)
        else:
            vq = np.asarray(item_factors, dtype=np.float32)
            vs = None
        self.mesh = mesh
        self.axis = axis
        self.num_items = vq.shape[0]
        self.dim = vq.shape[1]
        n = mesh.shape[axis]
        pad_i = (-self.num_items) % n
        self._sharding = NamedSharding(mesh, P(axis))
        vq_pad = np.concatenate([vq, np.zeros((pad_i, self.dim), vq.dtype)])
        if quantized:
            # padding rows dequantize to zero (0 * scale); scale 1 keeps
            # them harmless
            self._v = (
                jax.device_put(vq_pad, self._sharding),
                jax.device_put(
                    np.concatenate([vs, np.ones(pad_i, np.float32)]),
                    self._sharding,
                ),
            )
        else:
            self._v = jax.device_put(vq_pad, self._sharding)
        self._ids = jax.device_put(
            np.concatenate(
                [
                    np.arange(self.num_items, dtype=np.int32),
                    np.full(pad_i, -1, np.int32),
                ]
            ),
            self._sharding,
        )
        base_keep = np.ones(self.num_items + pad_i, np.float32)
        base_keep[self.num_items :] = 0.0
        self._base_keep = base_keep
        self._keep_all = jax.device_put(base_keep, self._sharding)

    def top_k(
        self,
        user_vectors,
        k: int,
        exclude_mask=None,
        exclude_ids=None,
        normalize=False,
        coarse=False,
    ):
        """Top-k over the staged catalog. See :func:`ring_top_k`.

        ``coarse=True`` is the mesh shortlist pass of two-stage
        retrieval (ops/retrieval.py): int8 slabs are scored without
        dequantization (ranking-equivalent, not bitwise-equal, to the
        exact scores) — callers rescore the returned ids exactly.
        Dense catalogs score identically either way.

        ``B`` and ``k`` are compile-time shapes in the device program, and
        serving traffic varies both per request (``query.num`` drives k).
        Both are padded up to power-of-two buckets so arbitrary traffic
        reuses a handful of compiled programs instead of accumulating one
        per distinct (B, k); results are sliced back before returning.

        ``exclude_ids`` (preferred for serving): a SMALL int array of
        item indices to exclude — scattered into the device-resident keep
        vector inside the jitted program (padded to power-of-two length
        for compile reuse), shipping O(len) bytes instead of the O(I)
        full-mask copy ``exclude_mask`` costs.
        """
        user_vectors = np.asarray(user_vectors, dtype=np.float32)
        B = user_vectors.shape[0]
        k = min(k, self.num_items)
        k_pad = min(1 << max(0, k - 1).bit_length(), self.num_items)
        n = self.mesh.shape[self.axis]
        # pad B to n * 2^j: divisible by the mesh axis AND bucketed
        per_dev = max(1, -(-B // n))
        pad_b = n * (1 << (per_dev - 1).bit_length()) - B
        q = np.concatenate(
            [user_vectors, np.zeros((pad_b, self.dim), np.float32)]
        )
        if exclude_ids is not None:
            if exclude_mask is not None:
                raise ValueError(
                    "pass exclude_ids or exclude_mask, not both"
                )
            eids = np.asarray(exclude_ids, dtype=np.int32).ravel()
            total = self._keep_all.shape[0]
            # power-of-two padding with an out-of-range index the
            # scatter drops (mode="drop")
            cap = 1 << max(0, len(eids) - 1).bit_length() if len(eids) else 1
            padded = np.full(cap, total, np.int32)
            padded[: len(eids)] = eids
            keep = _exclude_on_device(
                self._keep_all, jnp.asarray(padded), self._sharding
            )
        elif exclude_mask is None:
            keep = self._keep_all
        else:
            host_keep = self._base_keep.copy()
            host_keep[: self.num_items] = np.where(
                np.asarray(exclude_mask).astype(bool),
                0.0,
                host_keep[: self.num_items],
            )
            keep = jax.device_put(host_keep, self._sharding)
        scores, out_ids = _ring_topk_device(
            jax.device_put(q, self._sharding),
            self._v,
            self._ids,
            keep,
            k_pad,
            mesh=self.mesh,
            axis=self.axis,
            normalize=normalize,
            coarse=coarse,
        )
        return np.asarray(scores)[:B, :k], np.asarray(out_ids)[:B, :k]


def ring_top_k(
    user_vectors,
    item_factors,
    k: int,
    mesh: Mesh,
    axis: str = "data",
    exclude_mask=None,
    exclude_ids=None,
    normalize: bool = False,
):
    """Top-k items for a query batch with mesh-sharded item factors.

    One-shot convenience over :class:`RingCatalog` (which long-lived
    servers should hold instead, to amortize the catalog transfer).

    Args:
      user_vectors: [B, D] query vectors (host or device).
      item_factors: [I, D] full catalog factors, dense or as the int8
        (values, scales) pair (host or device; laid out sharded over
        ``axis``).
      k: results per query.
      mesh: the device mesh; ``axis`` names the ring dimension.
      exclude_mask: optional [I] bool/0-1 array; 1/True = never return
        this item (seen/unavailable filters of the e-commerce template).
      exclude_ids: optional small int array of item indices to exclude —
        the cheap path (on-device scatter; see RingCatalog.top_k).
      normalize: score by cosine similarity instead of dot product
        (similar-product template).

    Returns:
      (scores [B, k], ids [B, k]) numpy arrays, per-query descending.
      Ids are global item indices; -1 marks slots beyond the number of
      eligible items.
    """
    catalog = RingCatalog(item_factors, mesh, axis)
    return catalog.top_k(
        user_vectors,
        k,
        exclude_mask=exclude_mask,
        exclude_ids=exclude_ids,
        normalize=normalize,
    )
