"""Mesh construction helpers and multi-host runtime initialization.

Single-host: ``make_mesh`` arranges this process's devices. Multi-host
(a TPU pod, or several CPU hosts): call :func:`initialize_multihost`
FIRST — it brings up JAX's distributed runtime so ``jax.devices()``
returns the GLOBAL device set and every collective in the sharded
trainers (all_gather/psum over the mesh axis) spans hosts via ICI/DCN.
This replaces the reference's cluster-submission path (spark-submit to
YARN/Mesos masters, tools/.../Runner.scala:193-244): instead of
shipping jars to executors, every host runs the same ``pio train
--multihost`` and the runtime stitches their chips into one mesh.
"""

from __future__ import annotations

import logging
import os
from typing import Sequence

import numpy as np

logger = logging.getLogger(__name__)

_initialized = False


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids: Sequence[int] | None = None,
) -> bool:
    """Join this process into a multi-host JAX runtime (idempotent).

    Arguments default to the ``PIO_COORDINATOR_ADDRESS`` /
    ``PIO_NUM_PROCESSES`` / ``PIO_PROCESS_ID`` environment variables;
    with everything unset, ``jax.distributed.initialize()`` auto-detects
    on TPU pod slices (its own env/metadata discovery). Call before any
    other JAX API — backend initialization pins the device set.

    Returns True if the distributed runtime was (already) initialized.
    """
    global _initialized
    if _initialized:
        return True
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "PIO_COORDINATOR_ADDRESS"
    )
    if num_processes is None and os.environ.get("PIO_NUM_PROCESSES"):
        num_processes = int(os.environ["PIO_NUM_PROCESSES"])
    if process_id is None and os.environ.get("PIO_PROCESS_ID"):
        process_id = int(os.environ["PIO_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    logger.info(
        "multi-host runtime up: process %d/%d, %d global / %d local devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
        len(jax.local_devices()),
    )
    return True


def device_count() -> int:
    import jax

    return len(jax.devices())


def make_mesh(axes: Sequence[tuple[str, int]] | None = None):
    """Build a Mesh from (name, size) axes; one ``-1`` absorbs the rest.

    Default: 1-D ``("data", n_devices)``. Axis sizes must multiply to at
    most the device count.
    """
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if not axes:
        return Mesh(np.array(devices), ("data",))
    names = [n for n, _ in axes]
    sizes = [int(s) for _, s in axes]
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1])) or 1
        inferred = len(devices) // known
        if inferred == 0:
            raise ValueError(
                f"mesh axes {list(zip(names, sizes))}: fixed sizes need "
                f"{known} devices but only {len(devices)} available"
            )
        sizes[sizes.index(-1)] = inferred
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            f"mesh axes {list(zip(names, sizes))} need {total} devices, "
            f"have {len(devices)}"
        )
    return Mesh(np.array(devices[:total]).reshape(sizes), tuple(names))


def factor_sharding(mesh, axis: str = "data"):
    """Row sharding for factor tables / packed bucket tables: ``P(axis)``
    on dim 0. A pytree-prefix of this covers the int8 ``(values, scales)``
    pair too (both leaves row-sharded), which is how the fused trainer
    spells its ``in_shardings``."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(axis))


def replicated_sharding(mesh):
    """Fully replicated placement (scatter row-id vectors, scalars)."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())
