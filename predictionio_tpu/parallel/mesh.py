"""Mesh construction helpers."""

from __future__ import annotations

from typing import Sequence

import numpy as np


def device_count() -> int:
    import jax

    return len(jax.devices())


def make_mesh(axes: Sequence[tuple[str, int]] | None = None):
    """Build a Mesh from (name, size) axes; one ``-1`` absorbs the rest.

    Default: 1-D ``("data", n_devices)``. Axis sizes must multiply to at
    most the device count.
    """
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if not axes:
        return Mesh(np.array(devices), ("data",))
    names = [n for n, _ in axes]
    sizes = [int(s) for _, s in axes]
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1])) or 1
        inferred = len(devices) // known
        if inferred == 0:
            raise ValueError(
                f"mesh axes {list(zip(names, sizes))}: fixed sizes need "
                f"{known} devices but only {len(devices)} available"
            )
        sizes[sizes.index(-1)] = inferred
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            f"mesh axes {list(zip(names, sizes))} need {total} devices, "
            f"have {len(devices)}"
        )
    return Mesh(np.array(devices[:total]).reshape(sizes), tuple(names))
