"""Multi-chip parallelism: meshes, shardings, and collective ALS steps.

The reference's distribution substrate is Spark (RDD partitioning, shuffle,
broadcast — SURVEY §2.7 parallelism note). The TPU-native replacement:

- a ``jax.sharding.Mesh`` over the slice's devices (ICI) / hosts (DCN),
- ``shard_map`` partitioned compute with explicit XLA collectives
  (``all_gather`` for factor exchange, ``psum`` for Gramian reduction) —
  the Spark shuffle of MLlib ALS's factor exchange becomes an all-gather
  riding ICI each half-iteration,
- evaluation-candidate parallelism (the embarrassingly-parallel
  ``batchEval``) maps to independent sharded runs.
"""

from predictionio_tpu.parallel.mesh import make_mesh, device_count

__all__ = ["make_mesh", "device_count"]
