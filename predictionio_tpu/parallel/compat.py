"""jax API compatibility for the shard_map-based parallel paths.

The trainers target the current ``jax.shard_map`` API, where loop
carries that mix ppermute'd shard data with fresh constants need the
constants marked device-varying (``jax.lax.pcast(..., to="varying")``).
Older jax (< 0.5) ships shard_map under ``jax.experimental`` without
the varying/replication type system; there the equivalent is
``check_rep=False`` (no replication tracking, so nothing to mark) —
the compiled collectives are identical either way.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map

    def pcast_varying(x, axis: str):
        return jax.lax.pcast(x, (axis,), to="varying")

else:  # jax < 0.5
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs):
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

    def pcast_varying(x, axis: str):
        return x
