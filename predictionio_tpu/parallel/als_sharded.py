"""Mesh-sharded ALS: the multi-chip training path.

MLlib ALS distributes by blocking users x items across executors and
shuffling factor blocks each half-iteration (external Spark dep; SURVEY
§2.7). The TPU-native design (ALX pattern, PAPERS.md):

- both factor matrices live **sharded row-wise** over the mesh's ``data``
  axis (P("data") on dim 0),
- each half-iteration ``all_gather``s the *opposite* factor matrix over
  ICI inside a ``shard_map`` (it is the smaller working set), solves the
  local shard's normal equations with the same batched bucket math as
  single-chip, and scatters the solutions back into the sharded factors,
- the implicit-feedback Gramian Y^T Y is computed shard-locally and
  ``psum``-reduced — a [D, D] allreduce instead of MLlib's shuffle.

Two properties the round-1 design lacked, now guaranteed:

**Exact hot rows.** Degree-bucketed layouts segment rows hotter than the
widest bucket across several table rows (ops/als.py PaddedBucket). The
shard layout here places **all segments of one solved row on the same
shard** (greedy longest-processing-time assignment balances segment
counts across shards), so the per-segment Gramians are scatter-added
shard-locally before the solve — multi-chip training is bit-for-bit the
same math as single-chip, with no truncation of blockbuster rows.

**One device program.** The whole training run is a single jitted
``lax.fori_loop`` (dynamic trip count) with donated factor buffers; each
half-iteration is one ``shard_map`` region per bucket set. No per-bucket
Python dispatch, no host round-trips of the factors.

**Memory model (the all_gather working set).** Per chip, each
half-iteration holds: (a) its shard of both factor matrices —
``(rows + cols) / n_shards * D * 4`` bytes, shrinking with mesh size;
(b) its shard of the bucket tables (col_ids/ratings/mask ~= 12 bytes per
rating / n_shards), shrinking with mesh size; and (c) the ``all_gather``
of the FULL opposite factor matrix (``_train_fused_sharded.shard_fn``) —
``opposite_rows * D * 4`` bytes, which does NOT shrink with mesh size.
Per-bucket ``[B, K, D]`` factor-gather temps are additionally bounded by
``ALSParams.gather_chunk_bytes`` (the solves run through the same
``_solve_bucket_inline`` as single-chip, so wide buckets at high rank
chunk identically here — see ops/als.py).
(c) is the design ceiling: on 16-GiB v5e chips the gathered side caps at
roughly 10^8 rows at rank 20 or 1.6*10^7 at rank 128 (at half of HBM).
MovieLens-20M (2.7*10^4 items, rank 20 -> 2 MiB gathered) and any
catalog up to ~10^7 entities are far below it; the gather is one fused
ICI collective and is the latency-optimal choice there (ALX makes the
same trade, PAPERS.md). Past that ceiling the half-step must switch to a
blocked gather / ppermute ring over opposite-factor slabs (the
ring-top-k pattern in parallel/ring_topk.py applied to training) —
deliberately NOT implemented until a workload needs it; this docstring
is the recorded decision.
"""

from __future__ import annotations

import functools
import heapq
from dataclasses import dataclass
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.ops import als as als_ops


# ---------------------------------------------------------------------------
# Host-side: shard-aware bucket layout
# ---------------------------------------------------------------------------


@dataclass
class ShardedBucket:
    """One degree bucket laid out as per-shard sub-tables (flattened).

    ``col_ids/ratings/mask/seg_row`` are ``[S*B, ...]`` where shard ``s``
    owns rows ``[s*B, (s+1)*B)`` — exactly what ``P(axis)`` on dim 0
    yields. ``seg_row`` holds **shard-local** solved-row indices in
    ``[0, R)``; all segments of one solved row live on one shard.
    ``row_ids`` is ``[S*R]`` global factor-row ids (``dummy_row`` for
    padding slots), used for the global scatter of solutions.
    """

    row_ids: np.ndarray  # [S*R] int32 global row ids (dummy-padded)
    col_ids: np.ndarray  # [S*B, K] int32
    ratings: np.ndarray  # [S*B, K] float32
    mask: np.ndarray  # [S*B, K] float32
    seg_row: np.ndarray  # [S*B] int32, shard-local in [0, R)
    shards: int
    rows_per_shard: int  # R
    table_rows_per_shard: int  # B


def shard_bucket(
    bucket: als_ops.PaddedBucket, shards: int, dummy_row: int
) -> ShardedBucket:
    """Lay one PaddedBucket out over ``shards`` with row-segment
    colocation and balanced per-shard table sizes."""
    K = bucket.width
    R0 = len(bucket.row_ids)
    if bucket.seg_row is None:
        nseg = np.ones(R0, np.int64)
        seg_starts = np.arange(R0, dtype=np.int64)
    else:
        seg_of = bucket.seg_row.astype(np.int64)
        nseg = np.bincount(seg_of, minlength=R0)
        # segments of row j are contiguous table rows by construction
        # (ops/als.py build_padded_buckets seg_base layout)
        seg_starts = np.concatenate([[0], np.cumsum(nseg)[:-1]])

    if (nseg == 1).all():
        # fast path: one segment per row -> round-robin is perfectly even
        assign = np.arange(R0, dtype=np.int64) % shards
    else:
        # greedy LPT on segment counts so hot rows don't pile on one shard
        assign = np.empty(R0, np.int64)
        heap = [(0, 0, s) for s in range(shards)]
        heapq.heapify(heap)
        for j in np.argsort(-nseg, kind="stable"):
            load, cnt, s = heapq.heappop(heap)
            assign[j] = s
            heapq.heappush(heap, (load + int(nseg[j]), cnt + 1, s))

    per_shard_rows = np.bincount(assign, minlength=shards)
    per_shard_load = np.bincount(assign, weights=nseg, minlength=shards).astype(
        np.int64
    )
    R = max(1, int(per_shard_rows.max()))
    B = max(1, int(per_shard_load.max()))

    row_ids = np.full((shards, R), dummy_row, np.int32)
    col_ids = np.zeros((shards, B, K), np.int32)
    ratings = np.zeros((shards, B, K), np.float32)
    mask = np.zeros((shards, B, K), np.float32)
    seg_row = np.zeros((shards, B), np.int32)
    for s in range(shards):
        js = np.nonzero(assign == s)[0]  # ascending original order
        if len(js) == 0:
            continue
        ns = nseg[js]
        total = int(ns.sum())
        # source table rows: each row's contiguous segment run
        base = np.cumsum(ns) - ns
        within = np.arange(total) - np.repeat(base, ns)
        src = np.repeat(seg_starts[js], ns) + within
        row_ids[s, : len(js)] = bucket.row_ids[js]
        col_ids[s, :total] = bucket.col_ids[src]
        ratings[s, :total] = bucket.ratings[src]
        mask[s, :total] = bucket.mask[src]
        seg_row[s, :total] = np.repeat(np.arange(len(js), dtype=np.int32), ns)
    return ShardedBucket(
        row_ids=row_ids.reshape(-1),
        col_ids=col_ids.reshape(shards * B, K),
        ratings=ratings.reshape(shards * B, K),
        mask=mask.reshape(shards * B, K),
        seg_row=seg_row.reshape(-1),
        shards=shards,
        rows_per_shard=R,
        table_rows_per_shard=B,
    )


def upload_sharded_buckets(
    sharded: Sequence[ShardedBucket], mesh: Mesh, axis: str
) -> tuple:
    """Place the layout on the mesh once per training run: tables sharded
    ``P(axis)``, scatter row-ids replicated."""
    table = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    return tuple(
        (
            jax.device_put(sb.row_ids, repl),
            jax.device_put(sb.col_ids, table),
            jax.device_put(sb.ratings, table),
            jax.device_put(sb.mask, table),
            jax.device_put(sb.seg_row, table),
        )
        for sb in sharded
    )


# ---------------------------------------------------------------------------
# Device-side: fused training program
# ---------------------------------------------------------------------------


@dataclass
class ShardedALSState:
    """Factors resident on the mesh, each with one trailing dummy row."""

    mesh: Mesh
    axis: str
    U: jax.Array  # [num_rows+pad, D] sharded P(axis)
    V: jax.Array  # [num_cols+pad, D] sharded P(axis)
    num_rows: int
    num_cols: int


def _padded_len(n: int, shards: int) -> int:
    return n + 1 + ((-(n + 1)) % shards)  # +1 dummy row, then round up


def init_sharded_factors(
    data: als_ops.RatingsData,
    params: als_ops.ALSParams,
    mesh: Mesh,
    axis: str = "data",
) -> ShardedALSState:
    shards = mesh.shape[axis]
    key_u, key_v = jax.random.split(jax.random.PRNGKey(params.seed))
    u_len = _padded_len(data.num_rows, shards)
    v_len = _padded_len(data.num_cols, shards)
    # draw the TRUE-size init (identical to single-chip als_train for the
    # same seed — the parity tests rely on trajectory equality), then pad
    # with zeros; pad rows contribute nothing to the psum'd Gramian
    U = np.zeros((u_len, params.rank), np.float32)
    V = np.zeros((v_len, params.rank), np.float32)
    U[: data.num_rows] = np.asarray(
        als_ops.init_factors(data.num_rows, params.rank, key_u)
    )
    V[: data.num_cols] = np.asarray(
        als_ops.init_factors(data.num_cols, params.rank, key_v)
    )
    sharding = NamedSharding(mesh, P(axis))
    # factors persist (and all_gather) in storage_dtype: bf16 halves the
    # per-half-iteration ICI traffic and the gathered working set — the
    # (c) term of the memory model above — while solves still accumulate
    # float32 (ops/als.py ALSParams.storage_dtype)
    U_dev = jax.device_put(U, sharding)
    V_dev = jax.device_put(V, sharding)
    if params.storage_dtype != "float32":
        import jax.numpy as jnp

        sd = jnp.dtype(params.storage_dtype)
        U_dev = U_dev.astype(sd)  # elementwise: sharding preserved
        V_dev = V_dev.astype(sd)
    return ShardedALSState(
        mesh=mesh,
        axis=axis,
        U=U_dev,
        V=V_dev,
        num_rows=data.num_rows,
        num_cols=data.num_cols,
    )


@functools.partial(
    jax.jit,
    static_argnames=("params", "mesh", "axis"),
    donate_argnums=(0, 1),
)
def _train_fused_sharded(
    U, V, row_arrays, col_arrays, iterations, params: als_ops.ALSParams, mesh, axis
):
    """The whole sharded training run as ONE device program.

    ``lax.fori_loop`` over iterations (dynamic trip count — one compile
    serves any iteration count); each half-step is a single ``shard_map``
    region solving every bucket (one ``all_gather`` of the opposite
    factors, one ``psum`` for the implicit Gramian), followed by global
    scatters of the solutions into the sharded factor matrix.
    """
    shards = mesh.shape[axis]
    factor_spec = NamedSharding(mesh, P(axis))

    def half(target, other, buckets):
        # per-bucket solved-rows-per-shard, static at trace time
        rows_per = [b[0].shape[0] // shards for b in buckets]

        def shard_fn(other_shard, *flat):
            other_full = jax.lax.all_gather(other_shard, axis, tiled=True)
            gram = None
            if params.implicit:
                gram = jax.lax.psum(
                    als_ops.compute_gram(other_shard, params.compute_dtype), axis
                )
            outs = []
            for bi in range(0, len(flat) // 4):
                col_ids, ratings, mask, seg_row = flat[bi * 4 : bi * 4 + 4]
                outs.append(
                    als_ops._solve_bucket_inline(
                        other_full,
                        gram,
                        (col_ids, ratings, mask),
                        params,
                        seg_row=seg_row,
                        num_solved_rows=rows_per[bi],
                    )
                )
            return tuple(outs)

        flat = []
        for _row_ids, col_ids, ratings, mask, seg_row in buckets:
            flat += [col_ids, ratings, mask, seg_row]
        xs = jax.shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(axis),) + (P(axis),) * len(flat),
            out_specs=(P(axis),) * len(buckets),
        )(other, *flat)
        for x, (row_ids, *_rest) in zip(xs, buckets):
            target = target.at[row_ids].set(x.astype(target.dtype))
        return jax.lax.with_sharding_constraint(target, factor_spec)

    def step(_, carry):
        U, V = carry
        U = half(U, V, row_arrays)
        V = half(V, U, col_arrays)
        return (U, V)

    return jax.lax.fori_loop(0, iterations, step, (U, V))


def sharded_als_train(
    data: als_ops.RatingsData,
    params: als_ops.ALSParams,
    mesh: Mesh,
    axis: str = "data",
) -> tuple[jax.Array, jax.Array]:
    """Full multi-chip ALS with mesh-resident factors.

    Exact on arbitrarily hot rows: segmented buckets are consumed as-is
    (segments colocated per shard — see ``shard_bucket``), so results
    match single-chip ``als_train`` for the same seed. Returns (U, V)
    trimmed to the true row counts (still sharded device arrays)."""
    import dataclasses

    if axis not in mesh.shape:
        raise ValueError(
            f"mesh has axes {tuple(mesh.axis_names)} but the sharded ALS "
            f"trainer shards over {axis!r}; name one mesh axis {axis!r} "
            f"(e.g. --mesh {axis}=N) or pass axis="
        )
    shards = mesh.shape[axis]
    state = init_sharded_factors(data, params, mesh, axis)
    row_sb = [
        shard_bucket(b, shards, state.U.shape[0] - 1) for b in data.row_buckets
    ]
    col_sb = [
        shard_bucket(b, shards, state.V.shape[0] - 1) for b in data.col_buckets
    ]
    row_arrays = upload_sharded_buckets(row_sb, mesh, axis)
    col_arrays = upload_sharded_buckets(col_sb, mesh, axis)
    # iterations rides as a dynamic loop bound (shared compile across
    # iteration counts, like the single-chip _train_fused)
    static_params = dataclasses.replace(params, iterations=0)
    U, V = _train_fused_sharded(
        state.U,
        state.V,
        row_arrays,
        col_arrays,
        params.iterations,
        static_params,
        mesh,
        axis,
    )
    return U[: data.num_rows], V[: data.num_cols]


def train_for_context(
    data: als_ops.RatingsData,
    params: als_ops.ALSParams,
    ctx=None,
    sharded: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Framework dispatch point: the engine-param ``shardedTrain`` knob.

    Templates call this from ``Algorithm.train``; with ``sharded`` the
    run executes on the WorkflowContext's device mesh (the production
    multi-chip path — the TPU replacement for MLlib ALS's Spark-cluster
    execution, reference examples/scala-parallel-recommendation/
    custom-prepartor/src/main/scala/ALSAlgorithm.scala:72), otherwise on
    the single default device.
    """
    if not sharded or ctx is None:
        return als_ops.als_train(data, params)
    mesh = ctx.mesh
    # shard over "data" when present; a 1-D mesh shards over its only axis
    if "data" in mesh.shape:
        axis = "data"
    elif len(mesh.axis_names) == 1:
        axis = mesh.axis_names[0]
    else:
        raise ValueError(
            f"shardedTrain needs a 'data' axis on the mesh; got axes "
            f"{tuple(mesh.axis_names)}"
        )
    U, V = sharded_als_train(data, params, mesh, axis)
    if jax.process_count() > 1:
        # multi-host: shards live on other hosts' devices; templates
        # np.asarray the factors for persistence, so gather them to
        # host-replicated arrays (every host gets the full model)
        from jax.experimental import multihost_utils

        U = multihost_utils.process_allgather(U, tiled=True)
        V = multihost_utils.process_allgather(V, tiled=True)
    return U, V
