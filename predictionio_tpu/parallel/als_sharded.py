"""Mesh-sharded ALS: the multi-chip training path.

MLlib ALS distributes by blocking users x items across executors and
shuffling factor blocks each half-iteration (external Spark dep; SURVEY
§2.7). The TPU-native design here is the ALX execution model
(PAPERS.md, arxiv 2112.02194):

- both factor matrices live **uniformly row-sharded** over the mesh's
  ``data`` axis as ``NamedSharding(mesh, P("data"))``, moved through
  ``jax.jit`` with explicit ``in_shardings``/``out_shardings`` and
  donated buffers — no hand-rolled shard bookkeeping,
- ALL of a half-step's work — every degree bucket, every ring hop — is
  ONE ``shard_map`` region inside one compiled program; the entire
  training run is a single ``lax.fori_loop`` with a dynamic trip count,
- the implicit-feedback Gramian Y^T Y is computed shard-locally and
  ``psum``-reduced — a [D, D] allreduce instead of MLlib's shuffle.

**Packed bucket superstructure.** Instead of one sub-table per degree
bucket (whose count multiplies dispatch and shard_map regions), the
trainer packs the ENTIRE rating set into one padded table per side at a
single width ``K`` chosen to minimize padding (ops/als.py
``choose_pack_width`` / ``pack_entries``). Rows hotter than ``K`` span
several packed rows; ``seg`` maps each packed row to its shard-local
solved row and the per-segment normal equations are scatter-added
before the solve — the exact-hot-row guarantee of the bucketed layout
(all segments of a solved row on ONE shard; serpentine
descending-degree assignment balances load) at uniform shape.

**Two half-step variants, auto-selected** (``choose_sharded_mode``):

- ``gather``: tables are ``[S, B, K]`` (shard-major, global column
  ids). One fused ``all_gather`` of the opposite factors per half-step;
  each shard solves its packed rows against the full gathered matrix.
  Latency-optimal while the gathered side fits
  ``ALSParams.sharded_gather_budget_bytes`` per chip (ALX makes the
  same trade).
- ``ring``: the opposite factor TABLE never materializes whole on any
  chip. The value tables are the same gather-shaped ``[S, B, K]``; a
  routing table ``[S, T, E]`` with ``T = S`` rotation steps lists, per
  step, the slab-local ids of the entries whose opposite factor row is
  owned by shard ``(s - t) mod S`` — a device-side owner layout
  replacing the old host-side ``ring_partition_bucket`` repartition.
  The half-step is a single ``lax.scan`` over ``ppermute`` slab
  rotations: each step reads the slab rows its entries need (slab-local
  ids baked in at pack time), the stacked reads are permuted into
  ``[B, K, D]`` working-set order by one gather off a host-precomputed
  inverse map, and the last slot is peeled so a half-step costs S-1
  collective hops — after which the working set is bit-identical to
  gather's and the IDENTICAL packed solve runs.
  Per-chip memory — slab + the shard's ``~nnz/S``-slot working set —
  SHRINKS with mesh size, like MLlib's block ALS (reference
  examples/scala-parallel-recommendation/custom-prepartor/src/main/
  scala/ALSAlgorithm.scala:72 delegates to that substrate).

Both variants share the single-chip bucket math (ops/als.py
``_bucket_weights`` / ``_gramian_rhs_gathered`` /
``_finish_bucket_solve``), are exact on segmented hot rows, and thread
int8 ``(values, scales)`` / bf16 storage pairs through every collective
(quantized bytes on the wire). Per-row gather temps stay bounded by
``ALSParams.gather_chunk_bytes`` in both.

The legacy host-side layout (``shard_bucket`` / ``ring_partition_bucket``
/ ``resegment_skewed_rows``) is kept below as the REFERENCE
implementation: the property tests check the packed device layout
preserves every (row, col, rating) triple against it, and the skew
analysis in its docstrings documents why the packed layout replaced it.
"""

from __future__ import annotations

import dataclasses
import functools
import heapq
import logging
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.obs import device as obs_device
from predictionio_tpu.obs import metrics as obs_metrics
from predictionio_tpu.obs import progress as obs_progress
from predictionio_tpu.ops import als as als_ops
from predictionio_tpu.parallel.compat import shard_map
from predictionio_tpu.parallel.mesh import factor_sharding, replicated_sharding

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Host-side: packed per-shard layout (the device-side owner layout)
# ---------------------------------------------------------------------------


@dataclass
class SideLayout:
    """Degree-balanced placement of ONE side's factor rows on the mesh.

    Factor tables are stored PERMUTED: row ``u`` lives at table position
    ``assign[u] * rows_per_shard + loc[u]``, with shards assigned
    serpentine over descending degree. This is the uniform-shard layout
    that makes ring mode work under popularity skew: slab ownership
    becomes ``assign[col]`` (balanced entry load per owner — a pareto
    catalog no longer concentrates every hot column on slab 0 the way
    contiguous ``col // slab_rows`` ownership does), and the solved
    side's table position doubles as its accumulator/scatter slot.
    ``rows_per_shard`` includes one guaranteed-free trailing slot per
    shard — the scatter target for padding rows. The final factors are
    un-permuted once per training run (``positions`` gather).
    """

    assign: np.ndarray  # [N] shard of each factor row
    loc: np.ndarray  # [N] shard-local table slot
    rows_per_shard: int  # R: max rows on any shard, +1 dummy slot
    shards: int

    @property
    def positions(self) -> np.ndarray:
        """[N] global (permuted) table position of every factor row."""
        return self.assign * self.rows_per_shard + self.loc

    @property
    def table_len(self) -> int:
        return self.shards * self.rows_per_shard

    def dummy_position(self, shard: int) -> int:
        """The guaranteed-free last slot of ``shard``."""
        return shard * self.rows_per_shard + self.rows_per_shard - 1


def _envelope(n: int) -> int:
    """Smallest power of two >= ``n`` that still leaves ~12.5% free
    headroom past it. The layout-stable warm-retrain path (prep cache)
    sizes every packed dimension to this envelope so a small delta
    splices into the FREE slots without changing any array shape — the
    shape stability that lets a warm solve re-enter the already-compiled
    fused trainer."""
    n = max(1, int(n))
    e = 1 << max(3, (n - 1).bit_length())
    if e - n < max(1, n // 8):
        e *= 2
    return e


def build_side_layout(
    ids: np.ndarray, num_rows: int, shards: int, stable_shapes: bool = False
) -> SideLayout:
    """Lay one side's ``num_rows`` factor rows out over ``shards``.

    ``ids`` are that side's COO ids (degree = occurrence count; rows
    absent from the data get degree 0 and fill the tail slots). The
    serpentine over descending degree balances per-shard entry load to
    within one row's degree; within a shard, slots follow ascending row
    id — a stable layout independent of degree ties.

    ``stable_shapes`` pads ``rows_per_shard`` to the pow2
    :func:`_envelope` so later small deltas can append rows per shard
    without resizing the factor tables (extra slots stay zero and never
    scatter — correctness-free headroom, like the per-shard dummy).
    """
    deg = np.bincount(np.asarray(ids, dtype=np.int64), minlength=num_rows)
    order = np.argsort(-deg, kind="stable")
    pos = np.arange(num_rows)
    blk, off = divmod(pos, shards)
    assign = np.empty(num_rows, np.int64)
    assign[order] = np.where(blk % 2 == 0, off, shards - 1 - off)
    loc = np.empty(num_rows, np.int64)
    max_count = 0
    for s in range(shards):
        js = np.nonzero(assign == s)[0]  # ascending row id
        loc[js] = np.arange(len(js))
        max_count = max(max_count, len(js))
    R = _envelope(max_count + 1) if stable_shapes else max_count + 1
    return SideLayout(
        assign=assign, loc=loc, rows_per_shard=R, shards=shards
    )


def extend_side_layout(
    layout: SideLayout,
    new_num_rows: int,
    delta_ids: np.ndarray,
    shard_loads=None,
) -> SideLayout | None:
    """Append ids ``[len(assign), new_num_rows)`` into an existing layout
    WITHOUT moving any placed row (the layout-reuse half of the warm
    sharded retrain: factor placement — and with it the compiled fused
    program — survives the delta).

    New ids are assigned least-loaded-first (``shard_loads`` seeds the
    heap with the cached pack's per-shard entry counts; each new id adds
    its delta degree), filling each shard's existing order at
    ``loc = current row count``. Returns ``None`` when any shard would
    lose its guaranteed-free trailing slot — the caller falls back to a
    fresh layout (counted as ``layout_drift``)."""
    old_n = len(layout.assign)
    if new_num_rows < old_n:
        return None
    if new_num_rows == old_n:
        return layout
    S, R = layout.shards, layout.rows_per_shard
    row_counts = np.bincount(layout.assign, minlength=S)
    deg = np.bincount(
        np.asarray(delta_ids, dtype=np.int64), minlength=new_num_rows
    )[old_n:]
    loads = (
        np.asarray(shard_loads, dtype=np.float64)
        if shard_loads is not None
        else row_counts.astype(np.float64)
    )
    assign = np.concatenate(
        [layout.assign, np.zeros(new_num_rows - old_n, np.int64)]
    )
    loc = np.concatenate(
        [layout.loc, np.zeros(new_num_rows - old_n, np.int64)]
    )
    heap = [
        (float(loads[s]), int(row_counts[s]), s)
        for s in range(S)
        if row_counts[s] < R - 1  # keep the dummy slot free
    ]
    heapq.heapify(heap)
    for j in np.argsort(-deg, kind="stable"):
        if not heap:
            return None
        load, cnt, s = heapq.heappop(heap)
        u = old_n + int(j)
        assign[u] = s
        loc[u] = cnt
        cnt += 1
        if cnt < R - 1:
            heapq.heappush(heap, (load + float(deg[j]), cnt, s))
    return SideLayout(assign=assign, loc=loc, rows_per_shard=R, shards=S)


@dataclass
class PackedSide:
    """One side's ratings packed for the fused half-step.

    ``ratings``/``mask`` are ``[S, B, K]`` (shard ``s`` owns ``[s]``)
    in BOTH modes — the packed solve is mode-independent. ``col_ids``
    differs: ``mode="gather"`` stores PERMUTED-GLOBAL table positions
    of the opposite layout ``[S, B, K]`` (one lookup against the
    all_gathered table); ``mode="ring"`` stores a routing table
    ``[S, T, E]`` with ``T = S`` rotation steps — step ``[s, t]`` lists
    the slab-local col ids of exactly the entries whose opposite factor
    row is owned by shard ``(s - t) mod S`` under the opposite
    :class:`SideLayout`, and ``seg[:, :, 1:]`` carries the inverse
    gather map (working-set slot ``(b, k)`` -> flat ``[T * E]`` scan
    output position; padding -> the appended zero row), so the scan can
    assemble the same ``[B, K, D]`` working set gather's lookup
    produces, one passing slab at a time, with a single final gather.

    ``seg`` (``[S, B]`` gather; ``[S, B, 1 + K]`` ring, slot ``0``)
    maps each packed row to its shard-local solved slot in
    ``[0, rows_per_shard)`` — the solved side's own table ``loc`` — and
    all packed rows of one solved row live on its ``assign`` shard
    (exact hot rows). ``row_ids`` is ``[S * rows_per_shard]`` permuted
    table positions for the global scatter of solutions (each shard's
    dummy slot absorbs the zero solutions of never-solved slots).
    ``packed_rows`` counts REAL packed rows before the per-shard /
    per-slot max padding — the sizing guard compares ``col_ids.size``
    against it to detect residual owner-skew blowup.
    """

    row_ids: np.ndarray
    col_ids: np.ndarray
    ratings: np.ndarray
    mask: np.ndarray
    seg: np.ndarray
    mode: str
    shards: int
    rows_per_shard: int
    pack_width: int
    packed_rows: int


def _group_positions(g: np.ndarray) -> np.ndarray:
    """Per-element rank within its group (stable order)."""
    order = np.argsort(g, kind="stable")
    gs = g[order]
    if len(gs) == 0:
        return np.zeros(0, np.int64)
    starts = np.concatenate([[0], np.nonzero(np.diff(gs))[0] + 1])
    cnts = np.diff(np.concatenate([starts, [len(gs)]]))
    r = np.arange(len(gs)) - np.repeat(starts, cnts)
    pos = np.empty(len(g), np.int64)
    pos[order] = r
    return pos


def pack_sharded_side(
    t_ids: np.ndarray,
    o_ids: np.ndarray,
    vals: np.ndarray,
    t_layout: SideLayout,
    o_layout: SideLayout,
    shards: int,
    mode: str,
    stable_shapes: bool = False,
) -> PackedSide:
    """Build one side's :class:`PackedSide` from raw COO entries.

    ``t_ids`` are this side's (solved) row ids under ``t_layout``,
    ``o_ids`` the opposite side's column ids under ``o_layout``. Rows
    absent from ``t_ids`` are never solved and keep their init factors —
    same as single-chip. Entries keep their input order within each
    packed group (stable packing), which keeps the accumulation order —
    and thus the float32 trajectory — aligned with single-chip
    ``als_train``.

    ``stable_shapes`` pads the packed-row count ``B`` (and ring's
    rotation-cell width ``E``) to the pow2 :func:`_envelope`, leaving
    free all-zero rows/slots a later :func:`splice_packed_side` fills in
    place. Padding rows carry ``mask=0`` and scatter zeros, so the
    float32 trajectory is unchanged (exact zeros add exactly).
    """
    t_ids = np.asarray(t_ids, dtype=np.int64)
    o_ids = np.asarray(o_ids, dtype=np.int64)
    vals = np.asarray(vals, dtype=np.float32)
    uniq, inv, counts = np.unique(t_ids, return_inverse=True, return_counts=True)
    n_uniq = max(1, len(uniq))
    R = t_layout.rows_per_shard

    # scatter map: solved slots -> their table position; everything else
    # (never-solved slots, the trailing dummy) -> the shard's dummy slot
    row_ids = np.empty((shards, R), np.int32)
    for s in range(shards):
        row_ids[s, :] = t_layout.dummy_position(s)
    if len(uniq):
        row_ids[t_layout.assign[uniq], t_layout.loc[uniq]] = t_layout.positions[
            uniq
        ].astype(np.int32)

    assign_e = t_layout.assign[t_ids]
    loc_u = t_layout.loc[uniq] if len(uniq) else np.zeros(0, np.int64)
    base_key = assign_e * n_uniq + inv

    if mode == "gather":
        K = als_ops.choose_pack_width(counts)
        e_row, e_slot, row_key, n_rows = als_ops.pack_entries(base_key, K)
        row_shard = row_key // n_uniq
        row_loc = loc_u[row_key % n_uniq] if len(uniq) else row_key
        B = (
            max(1, int(np.bincount(row_shard, minlength=shards).max()))
            if n_rows
            else 1
        )
        if stable_shapes:
            B = _envelope(B)
        row_pos = _group_positions(row_shard)
        col_ids = np.zeros((shards, B, K), np.int32)
        ratings = np.zeros((shards, B, K), np.float32)
        mask = np.zeros((shards, B, K), np.float32)
        seg = np.zeros((shards, B), np.int32)
        if n_rows:
            seg[row_shard, row_pos] = row_loc
            rs, rp = row_shard[e_row], row_pos[e_row]
            col_ids[rs, rp, e_slot] = o_layout.positions[o_ids]
            ratings[rs, rp, e_slot] = vals
            mask[rs, rp, e_slot] = 1.0
    elif mode == "ring":
        # SAME value tables as gather ([S, B, K] ratings/mask, one seg
        # per packed row) plus a per-rotation ROUTING table: the scan
        # assembles the gather variant's [B, K, D] working set
        # incrementally, each step writing the slab rows its entries
        # need into their exact (packed row, slot) positions. Both
        # variants then run the identical one-dot packed solve — ring's
        # extra cost is only the S-1 small per-step gathers, not a
        # second (owner-fragmented, padding-heavy) packing.
        K = als_ops.choose_pack_width(counts)
        e_row, e_slot, row_key, n_rows = als_ops.pack_entries(base_key, K)
        row_shard = row_key // n_uniq
        row_loc = loc_u[row_key % n_uniq] if len(uniq) else row_key
        B = (
            max(1, int(np.bincount(row_shard, minlength=shards).max()))
            if n_rows
            else 1
        )
        if stable_shapes:
            B = _envelope(B)
        row_pos = _group_positions(row_shard)
        ratings = np.zeros((shards, B, K), np.float32)
        mask = np.zeros((shards, B, K), np.float32)
        owner_e = o_layout.assign[o_ids]
        rs_e = row_shard[e_row]
        step_e = (rs_e - owner_e) % shards
        cell = rs_e * shards + step_e
        E = (
            max(1, int(np.bincount(cell, minlength=shards * shards).max()))
            if n_rows
            else 1
        )
        if stable_shapes:
            E = _envelope(E)
        e_pos = _group_positions(cell)
        # routing: [S, T, E] slab-local col ids read per rotation step
        # (padding rereads slab row 0 — discarded by the gather map).
        # seg grows an INVERSE gather map: seg[:, :, 1:] holds, per
        # working-set slot (b, k), the flat [T * E] position its row
        # lands at in the scan's stacked outputs (padding slots -> the
        # appended zero row T * E). Assembly is then a pure gather —
        # XLA:CPU lowers row scatters serially, ~10x slower than the
        # equivalent gather, and real-slot order is already known here.
        col_ids = np.zeros((shards, shards, E), np.int32)
        seg = np.full((shards, B, 1 + K), shards * E, np.int32)
        seg[:, :, 0] = 0
        if n_rows:
            seg[row_shard, row_pos, 0] = row_loc
            ratings[rs_e, row_pos[e_row], e_slot] = vals
            mask[rs_e, row_pos[e_row], e_slot] = 1.0
            col_ids[rs_e, step_e, e_pos] = o_layout.loc[o_ids]
            seg[rs_e, row_pos[e_row], 1 + e_slot] = step_e * E + e_pos
    else:
        raise ValueError(f"mode must be gather|ring, got {mode!r}")

    return PackedSide(
        row_ids=row_ids.reshape(-1),
        col_ids=col_ids,
        ratings=ratings,
        mask=mask,
        seg=seg,
        mode=mode,
        shards=shards,
        rows_per_shard=R,
        pack_width=K,
        packed_rows=n_rows,
    )


def splice_packed_side(
    ps: PackedSide,
    t_layout: SideLayout,
    o_layout: SideLayout,
    delta_t: np.ndarray,
    delta_o: np.ndarray,
    delta_vals: np.ndarray,
) -> PackedSide | None:
    """Append delta entries into a cached :class:`PackedSide` under the
    REUSED (possibly :func:`extend_side_layout`-extended) layouts,
    preserving every array shape — the packed half of the
    zero-recompile warm retrain.

    Each delta entry first tops up its solved row's one partial packed
    segment (``pack_entries`` fills slots as a prefix, so occupancy is
    recoverable from the mask), else claims the shard's next free
    envelope row; ring mode additionally claims the next free slot of
    its ``(shard, rotation-step)`` routing cell and extends the inverse
    gather map. Returns ``None`` when the delta outgrows the free
    slots of any dimension — the caller falls back to a fresh pack
    (``layout_drift``). Entry order within a packed row is append
    order, which a fresh repack would not reproduce exactly: the warm
    solve is float-equal to ~1e-6 of a fresh-layout solve, not
    bit-identical (the fallback path stays bit-identical).
    """
    S, R, K = ps.shards, ps.rows_per_shard, ps.pack_width
    mode = ps.mode
    # entry arrays may be read-only mmap views out of the prep cache
    row_ids = np.array(ps.row_ids, dtype=np.int32, copy=True)
    col_ids = np.array(ps.col_ids, copy=True)
    ratings = np.array(ps.ratings, copy=True)
    mask = np.array(ps.mask, copy=True)
    seg = np.array(ps.seg, copy=True)
    B = ratings.shape[1]
    used = (mask > 0).sum(axis=2).astype(np.int64)  # [S, B] filled slots
    n_real = (used > 0).sum(axis=1).astype(np.int64)  # real rows: [0, n_s)
    seg_slot = seg if mode == "gather" else seg[:, :, 0]
    # the at-most-one partial packed row per (shard, solved slot)
    partial = {
        (int(s), int(seg_slot[s, b])): int(b)
        for s, b in zip(*np.nonzero((used > 0) & (used < K)))
    }
    if mode == "ring":
        E = col_ids.shape[2]
        gmap = seg[:, :, 1:]
        cell_fill = np.zeros((S, S), np.int64)
        for s in range(S):
            v = gmap[s][mask[s] > 0]  # real slots: step * E + e_pos
            if v.size:
                cell_fill[s] = np.bincount(v // E, minlength=S)
    delta_t = np.asarray(delta_t, np.int64)
    delta_o = np.asarray(delta_o, np.int64)
    delta_vals = np.asarray(delta_vals, np.float32)
    o_pos = o_layout.positions
    packed_rows = int(ps.packed_rows)
    for t, o, v in zip(delta_t, delta_o, delta_vals):
        s, l = int(t_layout.assign[t]), int(t_layout.loc[t])
        b = partial.get((s, l))
        if b is None:
            b = int(n_real[s])
            if b >= B:
                return None  # envelope exhausted
            n_real[s] += 1
            packed_rows += 1
            seg_slot[s, b] = l
            partial[(s, l)] = b
            k = 0
        else:
            k = int(used[s, b])
        ratings[s, b, k] = v
        mask[s, b, k] = 1.0
        used[s, b] = k + 1
        if used[s, b] >= K:
            partial.pop((s, l), None)
        if mode == "gather":
            col_ids[s, b, k] = o_pos[o]
        else:
            step = (s - int(o_layout.assign[o])) % S
            e = int(cell_fill[s, step])
            if e >= E:
                return None  # routing cell exhausted
            col_ids[s, step, e] = o_layout.loc[o]
            seg[s, b, 1 + k] = step * E + e
            cell_fill[s, step] = e + 1
        row_ids[s * R + l] = s * R + l  # solved slots self-address
    return PackedSide(
        row_ids=row_ids,
        col_ids=col_ids,
        ratings=ratings,
        mask=mask,
        seg=seg,
        mode=mode,
        shards=S,
        rows_per_shard=R,
        pack_width=K,
        packed_rows=packed_rows,
    )


def upload_packed_side(ps: PackedSide, mesh: Mesh, axis: str) -> tuple:
    """Place one packed side on the mesh: tables sharded ``P(axis)`` on
    the shard-major dim, scatter row-ids replicated."""
    obs_device.count_transfer(
        "h2d",
        "train.packed_side",
        ps.row_ids.nbytes + ps.col_ids.nbytes + ps.ratings.nbytes
        + ps.mask.nbytes + ps.seg.nbytes,
    )
    table = factor_sharding(mesh, axis)
    repl = replicated_sharding(mesh)
    return (
        jax.device_put(ps.row_ids, repl),
        jax.device_put(ps.col_ids, table),
        jax.device_put(ps.ratings, table),
        jax.device_put(ps.mask, table),
        jax.device_put(ps.seg, table),
    )


def packed_table_bytes_per_chip(sides: Sequence[PackedSide], shards: int) -> int:
    """Per-chip bytes of the packed tables (4-byte col-id/routing,
    rating, mask, and seg/gather-map slots, including padding)."""
    return (
        sum(
            4 * (ps.col_ids.size + ps.ratings.size + ps.mask.size + ps.seg.size)
            for ps in sides
        )
        // max(1, shards)
    )


def _check_ring_layout(
    row_ps: PackedSide, col_ps: PackedSide, params: als_ops.ALSParams, shards: int
) -> None:
    """Sizing guard for the ring layout's one blowup mode: owner skew.

    The routing table pads every ``[s, t]`` rotation slot to the max
    per-step entry count ``E``. The degree-balanced :class:`SideLayout`
    keeps owner loads near-uniform, so residual skew is rare (it takes
    correlated row/owner structure the serpentine cannot split —
    e.g. every entry of every row pointing at ONE opposite row); when
    it does happen most steps sit empty and the routing costs up to
    ``S`` times the ideally-balanced packing. Past 2x AND past the
    gather budget the run refuses with the knob named.
    """
    packed = packed_table_bytes_per_chip([row_ps, col_ps], shards)
    # balanced cost: value+mask slots (8B) plus one 4B routing id and
    # one 4B gather-map entry per slot, with zero rotation-step padding
    ideal = (
        sum(ps.packed_rows * ps.pack_width * 16 for ps in (row_ps, col_ps))
        // max(1, shards)
    )
    budget = params.sharded_gather_budget_bytes
    if packed > 2 * max(1, ideal):
        if packed > budget:
            raise ValueError(
                f"ring-mode packed tables need {packed} bytes/chip under "
                f"owner skew (balanced packing: {ideal}), over "
                f"sharded_gather_budget_bytes={budget}; raise the budget, "
                "add chips, or use mode='gather'"
            )
        if packed > (1 << 20):
            # tiny problems are always padding-dominated; only flag skew
            # once the tables are big enough for the blowup to matter
            logger.warning(
                "ring-mode packed tables blow up under owner skew: %d "
                "bytes/chip vs %d ideally balanced (within budget %d; "
                "proceeding)",
                packed,
                ideal,
                budget,
            )


# ---------------------------------------------------------------------------
# Device-side: fused training program
# ---------------------------------------------------------------------------


@dataclass
class ShardedALSState:
    """Factors resident on the mesh in :class:`SideLayout` (permuted)
    order, one guaranteed-free dummy slot per shard."""

    mesh: Mesh
    axis: str
    U: jax.Array  # [S * row rows_per_shard, D] sharded P(axis)
    V: jax.Array  # [S * col rows_per_shard, D] sharded P(axis)
    num_rows: int
    num_cols: int


def _padded_len(n: int, shards: int) -> int:
    return n + 1 + ((-(n + 1)) % shards)  # +1 dummy row, then round up


def init_sharded_factors(
    data: als_ops.RatingsData,
    params: als_ops.ALSParams,
    mesh: Mesh,
    axis: str = "data",
    row_layout: SideLayout | None = None,
    col_layout: SideLayout | None = None,
    warm_start=None,
) -> ShardedALSState:
    shards = mesh.shape[axis]
    if row_layout is None:
        row_layout = build_side_layout(data.rows, data.num_rows, shards)
    if col_layout is None:
        col_layout = build_side_layout(data.cols, data.num_cols, shards)
    key_u, key_v = jax.random.split(jax.random.PRNGKey(params.seed))
    # draw the TRUE-size init (identical to single-chip als_train for the
    # same seed — the parity tests rely on trajectory equality), then
    # place each row at its layout position; unfilled slots (per-shard
    # dummies) stay zero and contribute nothing to the psum'd Gramian
    U = np.zeros((row_layout.table_len, params.rank), np.float32)
    V = np.zeros((col_layout.table_len, params.rank), np.float32)
    U_true = np.asarray(
        als_ops.init_factors(data.num_rows, params.rank, key_u)
    )
    V_true = np.asarray(
        als_ops.init_factors(data.num_cols, params.rank, key_v)
    )
    if warm_start is not None:
        # warm factors ride in true row order (NaN rows keep the cold
        # draw — same merge rule as single-chip als_train) and are
        # re-permuted through the SideLayout with everything else
        w_u = np.asarray(warm_start[0], dtype=np.float32)
        w_v = np.asarray(warm_start[1], dtype=np.float32)
        U_true = np.where(np.isnan(w_u), U_true, w_u)
        V_true = np.where(np.isnan(w_v), V_true, w_v)
    U[row_layout.positions] = U_true
    V[col_layout.positions] = V_true
    sharding = factor_sharding(mesh, axis)
    # factors persist (and all_gather/ppermute) in storage_dtype: bf16
    # halves the per-half-iteration ICI traffic and the gathered working
    # set while solves still accumulate float32 (ops/als.py
    # ALSParams.storage_dtype)
    U_dev = jax.device_put(U, sharding)
    V_dev = jax.device_put(V, sharding)
    if params.storage_dtype == "int8":
        # per-row quantization reduces over the (unsharded) rank dim
        # only, so the row sharding of both values and scales is
        # preserved; the all_gather/ppermute'd working set becomes the
        # (int8 values, f32 scales) pair — ~4x fewer ICI bytes than f32
        U_dev = als_ops.quantize_rows(U_dev)
        V_dev = als_ops.quantize_rows(V_dev)
    elif params.storage_dtype != "float32":
        sd = jnp.dtype(params.storage_dtype)
        U_dev = U_dev.astype(sd)  # elementwise: sharding preserved
        V_dev = V_dev.astype(sd)
    return ShardedALSState(
        mesh=mesh,
        axis=axis,
        U=U_dev,
        V=V_dev,
        num_rows=data.num_rows,
        num_cols=data.num_cols,
    )


def _gather_table_rows(table, positions: np.ndarray, sharding):
    """Un-permute a trained factor table: gather ``positions`` (original
    row order) out of the layout-ordered table, keeping the storage
    representation (int8 ``(values, scales)`` gathers both leaves).
    The gather runs at full table length (dummy-padded tail) so the
    result can be re-placed under the row ``sharding``, then is trimmed
    to the true row count."""
    n = len(positions)
    table_len = als_ops.table_rows(table)
    pos_full = np.full(table_len, table_len - 1, np.int32)
    pos_full[:n] = positions
    pos = jnp.asarray(pos_full)
    gathered = jax.tree_util.tree_map(
        lambda t: jax.device_put(t[pos], sharding), table
    )
    return als_ops.slice_rows(gathered, n)


@functools.lru_cache(maxsize=None)
def _fused_trainer(mesh: Mesh, axis: str, mode: str, params: als_ops.ALSParams):
    """Build (and cache) the jitted trainer for one (mesh, mode, params).

    The returned function runs the WHOLE training run as one XLA
    program: ``jax.jit`` with explicit ``in_shardings``/``out_shardings``
    (uniform ``NamedSharding(mesh, P(axis))`` on every factor/table
    leaf; a pytree-prefix covers the int8 ``(values, scales)`` pairs)
    and donated factor buffers, a ``lax.fori_loop`` over a DYNAMIC
    iteration count (one compile serves any count — the lru_cache key
    is the iteration-normalized params), and ONE ``shard_map`` region
    per half-step:

    - ``mode="gather"``: all_gather the opposite factors (tiled — one
      fused ICI collective), then a single packed-table solve via the
      single-chip bucket math.
    - ``mode="ring"``: a single ``lax.scan`` over ``ppermute`` slab
      rotations. Step ``t`` consumes rotation slot ``t`` of the routing
      table — whose slab-local column ids index the passing slab
      directly — and stacks the reads as scan outputs; one final gather
      off the host-precomputed inverse map lands them in gather's exact
      ``[B, K, D]`` working-set order, and the identical packed solve
      runs. The final slot is peeled out of the scan so a half-step
      costs S-1 hops, and only the slab rides the carry (donated, no
      per-hop re-materialization).
    """
    shards = mesh.shape[axis]
    factor = factor_sharding(mesh, axis)
    repl = replicated_sharding(mesh)
    dt = jnp.dtype(params.compute_dtype)
    perm = [(i, (i + 1) % shards) for i in range(shards)]

    def opposite_gram(other_shard):
        if not params.implicit:
            return None
        return jax.lax.psum(
            als_ops.compute_gram(other_shard, params.compute_dtype), axis
        )

    def gather_fn(R, other_shard, col_ids, ratings, mask, seg):
        # int8 storage: other_shard is the (values, scales) pair; gather
        # both leaves so the ICI collective moves quantized bytes
        other_full = jax.tree_util.tree_map(
            lambda t: jax.lax.all_gather(t, axis, tiled=True), other_shard
        )
        return als_ops._solve_bucket_inline(
            other_full,
            opposite_gram(other_shard),
            (col_ids[0], ratings[0], mask[0]),
            params,
            seg_row=seg[0],
            num_solved_rows=R,
        )

    def ring_fn(R, other_shard, col_ids, ratings, mask, seg):
        # col_ids is the ROUTING table [T, E] of slab-local col ids to
        # read per rotation step; ratings/mask are the exact
        # gather-shaped [B, K] tables and seg is [B, 1 + K] (solved
        # slot, then the inverse gather map). The scan ASSEMBLES the
        # gather variant's [B, K, D] working set: step t reads the rows
        # this shard's entries need from the slab passing by (their
        # owner's rotation); the stacked reads are then permuted into
        # (row, slot) order by ONE gather off the inverse map — padding
        # slots pull the appended zero row. After S-1 hops the working
        # set is bit-identical to what gather's all_gather + table
        # lookup produces, and the SAME packed solve runs — one dot,
        # one segment scatter, one batched Cholesky.
        lcol, rt, mt = col_ids[0], ratings[0], mask[0]
        sg, ginv = seg[0][:, 0], seg[0][:, 1:]
        D = als_ops.table_dim(other_shard)
        T, E = lcol.shape
        gram = opposite_gram(other_shard)

        def assemble(slab, lc):
            rows = als_ops._read_rows(slab, lc, dt)
            # int8 slabs rotate as (values, scales) — quantized ICI hops
            slab = jax.tree_util.tree_map(
                lambda x: jax.lax.ppermute(x, axis, perm), slab
            )
            return slab, rows

        # S-1 rotate-and-read steps in ONE scan, final slot peeled (the
        # last rotation's hop would be unused): S-1 hops per half-step,
        # all inside this program. Only the slab rides the carry; the
        # per-step reads stack as scan outputs.
        slab, rows_t = jax.lax.scan(assemble, other_shard, lcol[:-1])
        rows_all = jnp.concatenate(
            [rows_t, als_ops._read_rows(slab, lcol[-1], dt)[None]], axis=0
        )
        flat = jnp.concatenate(
            [rows_all.reshape(T * E, D), jnp.zeros((1, D), dt)], axis=0
        )
        vg = flat[ginv]
        w, rr = als_ops._bucket_weights(rt, mt, params, params.alpha)
        A, b = als_ops._gramian_rhs(vg, w, rr)
        return als_ops._finish_bucket_solve(
            A, b, mt.sum(axis=1), gram, params, sg, R, params.reg
        )

    shard_fn = {"gather": gather_fn, "ring": ring_fn}[mode]

    def half(target, other, pack):
        row_ids, col_ids, ratings, mask, seg = pack
        R = row_ids.shape[0] // shards
        # int8 factor tables are (values, scales) pairs: spell out the
        # matching spec structure (both leaves row-sharded over axis)
        other_spec = (P(axis), P(axis)) if isinstance(other, tuple) else P(axis)
        x = shard_map(
            functools.partial(shard_fn, R),
            mesh=mesh,
            in_specs=(other_spec, P(axis), P(axis), P(axis), P(axis)),
            out_specs=P(axis),
        )(other, col_ids, ratings, mask, seg)
        # solves come back float32 [S*R, D]; factors persist in
        # storage_dtype (int8 requantizes here, fresh per-row scales)
        target = als_ops._scatter_rows(target, row_ids, x)
        return jax.tree_util.tree_map(
            lambda t: jax.lax.with_sharding_constraint(t, factor), target
        )

    def train(U, V, row_pack, col_pack, iterations):
        def step(_, carry):
            U, V = carry
            U = half(U, V, row_pack)
            V = half(V, U, col_pack)
            return (U, V)

        return jax.lax.fori_loop(0, iterations, step, (U, V))

    pack_s = (repl, factor, factor, factor, factor)
    return obs_device.track_jit(f"sharded.train.{mode}")(
        jax.jit(
            train,
            donate_argnums=(0, 1),
            in_shardings=(factor, factor, pack_s, pack_s, repl),
            out_shardings=(factor, factor),
        )
    )


def choose_sharded_mode(
    data: als_ops.RatingsData, params: als_ops.ALSParams, shards: int
) -> str:
    """Pick the half-step variant for a run: ``gather`` while the larger
    gathered side fits ``params.sharded_gather_budget_bytes`` per chip,
    ``ring`` past it (module docstring, "Two half-step variants")."""
    rows = max(
        _padded_len(data.num_rows, shards), _padded_len(data.num_cols, shards)
    )
    gathered = rows * _factor_row_bytes(params)
    return "ring" if gathered > params.sharded_gather_budget_bytes else "gather"


def _factor_row_bytes(params: als_ops.ALSParams) -> int:
    """Bytes one gathered factor row costs in storage form (int8 rows
    carry their f32 per-row scale alongside the quantized values)."""
    if params.storage_dtype == "int8":
        return params.rank + 4
    return params.rank * jnp.dtype(params.storage_dtype).itemsize


def halfstep_collective_bytes(
    num_rows: int, num_cols: int, shards: int, params: als_ops.ALSParams, mode: str
) -> dict:
    """Per-chip ICI traffic of ONE half-step (the larger, opposite-side
    gather; both halves of an iteration together move both sides).

    ``gather``: one fused all_gather — each chip receives the other
    S-1 slabs of the opposite table in a single collective. ``ring``:
    S-1 ``ppermute`` hops, each moving one opposite-factor slab. Total
    bytes match; the ring trades the fused collective for S-1 smaller
    hops (and never materializes the full table).
    """
    opp = max(_padded_len(num_rows, shards), _padded_len(num_cols, shards))
    row_bytes = _factor_row_bytes(params)
    slab_bytes = (opp // shards) * row_bytes
    hops = 1 if mode == "gather" else max(1, shards - 1)
    per_hop = slab_bytes * (shards - 1) if mode == "gather" else slab_bytes
    return {
        "mode": mode,
        "hops_per_halfstep": hops,
        "bytes_per_hop": int(per_hop),
        "total_bytes_per_halfstep": int(per_hop * hops),
    }


def sharded_memory_estimate(
    num_rows: int,
    num_cols: int,
    nnz: int,
    shards: int,
    params: als_ops.ALSParams,
    mode: str,
) -> dict:
    """Analytic peak-HBM estimate per chip for one training run (bytes).

    Counts the resident terms of the memory model: both factor shards,
    the packed tables (12 bytes/entry/side gather, 16 ring — routing
    ids and the inverse gather map ride along; padding ignored), and
    the mode's working set.
    ``gather`` holds the full gathered opposite table — it does NOT
    shrink with mesh size. ``ring`` holds one rotating slab plus the
    shard's assembled f32 ``[B, K, D]`` working set (~``nnz/S`` slots),
    both of which DO shrink with mesh size — that 1/S scaling, not the
    absolute size, is what lets ring outlive the gather budget.
    """
    row_bytes = _factor_row_bytes(params)
    u_len = _padded_len(num_rows, shards)
    v_len = _padded_len(num_cols, shards)
    D = params.rank
    factors = (u_len + v_len) * row_bytes // shards
    tables = 2 * nnz * (12 if mode == "gather" else 16) // shards
    opp = max(u_len, v_len)
    if mode == "gather":
        working = opp * row_bytes
    else:
        working = (opp // shards) * row_bytes + (nnz // shards) * D * 4
    return {
        "mode": mode,
        "factors_bytes": int(factors),
        "tables_bytes": int(tables),
        "working_set_bytes": int(working),
        "peak_bytes": int(factors + tables + working),
    }


def prepare_sharded_pack(
    data: als_ops.RatingsData,
    params: als_ops.ALSParams,
    shards: int,
    mode: str = "auto",
    stable_shapes: bool = False,
):
    """Build the host-side sharded prep — resolved mode, both
    :class:`SideLayout`\\ s, and both :class:`PackedSide`\\ s — WITHOUT
    training. This is the scan+pack work :func:`sharded_als_train`
    normally does inline; split out so the packed-prep cache
    (core/prep_cache.py) can persist and restore it, handing the result
    back via ``prepacked=``. ``stable_shapes`` (set by the prep cache)
    pads every packed dimension to its pow2 :func:`_envelope` so a
    cached pack can absorb small deltas shape-stably. Returns ``(mode,
    row_layout, col_layout, row_ps, col_ps)``."""
    if mode == "auto":
        mode = choose_sharded_mode(data, params, shards)
    elif mode not in ("gather", "ring"):
        raise ValueError(f"mode must be auto|gather|ring, got {mode!r}")
    row_layout = build_side_layout(
        data.rows, data.num_rows, shards, stable_shapes=stable_shapes
    )
    col_layout = build_side_layout(
        data.cols, data.num_cols, shards, stable_shapes=stable_shapes
    )
    row_ps = pack_sharded_side(
        data.rows, data.cols, data.vals, row_layout, col_layout, shards,
        mode, stable_shapes=stable_shapes,
    )
    col_ps = pack_sharded_side(
        data.cols, data.rows, data.vals, col_layout, row_layout, shards,
        mode, stable_shapes=stable_shapes,
    )
    return mode, row_layout, col_layout, row_ps, col_ps


def sharded_als_train(
    data: als_ops.RatingsData,
    params: als_ops.ALSParams,
    mesh: Mesh,
    axis: str = "data",
    mode: str = "auto",
    checkpoint_cfg=None,
    warm_start=None,
    tol: float = 0.0,
    prepacked=None,
    progress_extra: dict | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Full multi-chip ALS with mesh-resident factors.

    Exact on arbitrarily hot rows: packed segments of one solved row are
    colocated per shard and scatter-added before the solve, so results
    match single-chip ``als_train`` for the same seed. ``mode`` is
    ``"gather"``, ``"ring"``, or ``"auto"`` (default: pick by the
    per-chip budget — ``choose_sharded_mode``). Returns (U, V) trimmed
    to the true row counts (still sharded device arrays).

    Checkpointing (``checkpoint_cfg`` or PIO_CHECKPOINT_*; see
    core/checkpoint.py): like single-chip ``als_train``, the dynamic
    fori_loop bound lets the run dispatch in ``every``-iteration
    segments through the one cached trainer, persisting the
    layout-ordered sharded carry at each boundary. The fingerprint
    carries the mesh descriptor (axis size + half-step mode), so a
    snapshot can only resume onto the identical layout. Single-host
    only: multi-host runs skip checkpointing with a warning."""
    if axis not in mesh.shape:
        raise ValueError(
            f"mesh has axes {tuple(mesh.axis_names)} but the sharded ALS "
            f"trainer shards over {axis!r}; name one mesh axis {axis!r} "
            f"(e.g. --mesh {axis}=N) or pass axis="
        )
    shards = mesh.shape[axis]
    if tol > 0.0:
        logger.warning(
            "RMSE-plateau early stop (tol=%g) is unavailable on the "
            "sharded trainer: mid-run tables are in SideLayout order, so "
            "no per-segment RMSE exists to ride; running the configured "
            "%d iterations", tol, params.iterations,
        )
    if prepacked is not None:
        mode, row_layout, col_layout, row_ps, col_ps = prepacked
    else:
        mode, row_layout, col_layout, row_ps, col_ps = prepare_sharded_pack(
            data, params, shards, mode
        )
    state = init_sharded_factors(
        data, params, mesh, axis, row_layout, col_layout,
        warm_start=warm_start,
    )
    if mode == "ring":
        _check_ring_layout(row_ps, col_ps, params, shards)
    row_pack = upload_packed_side(row_ps, mesh, axis)
    col_pack = upload_packed_side(col_ps, mesh, axis)
    # iterations rides as a dynamic loop bound (shared compile across
    # iteration counts, like the single-chip _train_fused)
    static_params = dataclasses.replace(params, iterations=0)
    trainer = _fused_trainer(mesh, axis, mode, static_params)
    import time as _time

    from predictionio_tpu import faults
    from predictionio_tpu.core import checkpoint as ckpt

    cfg = checkpoint_cfg if checkpoint_cfg is not None else ckpt.from_env()
    if cfg is not None and cfg.active and jax.process_count() > 1:
        logger.warning(
            "checkpointing is single-host only; disabling for this "
            "multi-host run"
        )
        cfg = None
    factor = factor_sharding(mesh, axis)
    start_iter = 0
    fingerprint = None
    mesh_desc = f"sharded:{axis}={shards}:{mode}"
    if cfg is not None and cfg.active:
        fingerprint = ckpt.data_fingerprint(
            data.rows, data.cols, data.vals, static_params, mesh=mesh_desc
        )
        if cfg.resume:
            snap = ckpt.load_checkpoint(cfg, fingerprint)
            if snap is not None and snap.iteration <= params.iterations:
                # the snapshot holds the layout-ordered padded tables;
                # layouts derive deterministically from the (fingerprint-
                # matched) data, so positions line up exactly
                state.U = jax.device_put(snap.U, factor)
                state.V = jax.device_put(snap.V, factor)
                start_iter = snap.iteration

    # per-segment RMSE is skipped here on purpose: mid-run tables are in
    # SideLayout (degree-balanced) order, so scoring them against the
    # original-order (rows, cols) pairs would be wrong
    prog = obs_progress.ProgressPublisher(
        params.iterations, mesh=mesh_desc, trainer="sharded",
        warm_start=warm_start is not None, **(progress_extra or {}),
    )
    # multi-host: every host runs this loop; one writer is enough
    prog.enabled = prog.enabled and jax.process_index() == 0
    nnz = len(data.vals)
    t0 = _time.perf_counter()
    if cfg is None or cfg.every <= 0:
        prog.publish(start_iter)
        faults.fault_point("device.dispatch")
        U, V = trainer(
            state.U, state.V, row_pack, col_pack,
            params.iterations - start_iter,
        )
    else:
        prog.publish(start_iter)
        U, V = state.U, state.V
        it = start_iter
        epochs = 0
        while it < params.iterations:
            seg = min(cfg.every, params.iterations - it)
            faults.fault_point("device.dispatch")
            t_seg = _time.perf_counter()
            U, V = trainer(U, V, row_pack, col_pack, seg)
            it += seg
            if it < params.iterations:
                # save_checkpoint host-copies the carry (np.asarray)
                # before the next dispatch donates its buffers
                jax.block_until_ready((U, V))
                ckpt.save_checkpoint(
                    cfg, fingerprint, U, V, it, params.seed, mesh=mesh_desc
                )
                epochs += 1
            seg_wall = _time.perf_counter() - t_seg
            prog.publish(
                it,
                events_per_s=nnz * seg / seg_wall if seg_wall > 0 else None,
                segment_wall_s=seg_wall,
                checkpoint_epoch=epochs,
            )
    jax.block_until_ready((U, V))
    prog.done(params.iterations)
    als_ops.LAST_TRAIN_INFO.clear()
    als_ops.LAST_TRAIN_INFO.update(
        iterations_run=params.iterations - start_iter,
        early_stopped=False,
        final_rmse=None,
        warm_start=warm_start is not None,
    )
    total = _time.perf_counter() - t0
    # the whole loop is ONE scan-fused jit program, so per-half-step
    # timing is derived: total / (2 * iterations). First-call totals
    # include the XLA compile — read p50, not max.
    if params.iterations > start_iter:
        obs_metrics.histogram(
            "pio_als_halfstep_seconds",
            "Derived per-half-step time of the fused sharded ALS loop",
            mode=mode,
        ).observe(total / (2 * (params.iterations - start_iter)))
    obs_metrics.histogram(
        "pio_als_train_seconds",
        "Whole-run ALS training time",
        path="sharded",
    ).observe(total)
    # tables are in SideLayout (degree-balanced) order: un-permute ONCE
    # per training run back to original row order
    factor = factor_sharding(mesh, axis)
    return (
        _gather_table_rows(U, row_layout.positions, factor),
        _gather_table_rows(V, col_layout.positions, factor),
    )


def train_for_context(
    data: als_ops.RatingsData,
    params: als_ops.ALSParams,
    ctx=None,
    sharded: bool = False,
    mode: str = "auto",
    warm_start=None,
    tol: float = 0.0,
    prepacked=None,
    progress_extra: dict | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Framework dispatch point: the engine-param ``shardedTrain`` knob.

    Templates call this from ``Algorithm.train``; with ``sharded`` the
    run executes on the WorkflowContext's device mesh (the production
    multi-chip path — the TPU replacement for MLlib ALS's Spark-cluster
    execution, reference examples/scala-parallel-recommendation/
    custom-prepartor/src/main/scala/ALSAlgorithm.scala:72), otherwise on
    the single default device. ``mode`` forwards to
    :func:`sharded_als_train` (the engine-param ``shardedMode`` knob:
    auto|gather|ring).
    """
    if not sharded or ctx is None:
        return als_ops.als_train(
            data, params, warm_start=warm_start, tol=tol,
            progress_extra=progress_extra,
        )
    mesh = ctx.mesh
    # shard over "data" when present; a 1-D mesh shards over its only axis
    if "data" in mesh.shape:
        axis = "data"
    elif len(mesh.axis_names) == 1:
        axis = mesh.axis_names[0]
    else:
        raise ValueError(
            f"shardedTrain needs a 'data' axis on the mesh; got axes "
            f"{tuple(mesh.axis_names)}"
        )
    U, V = sharded_als_train(
        data, params, mesh, axis, mode=mode, warm_start=warm_start,
        tol=tol, prepacked=prepacked, progress_extra=progress_extra,
    )
    if jax.process_count() > 1:
        # multi-host: shards live on other hosts' devices; templates
        # np.asarray the factors for persistence, so gather them to
        # host-replicated arrays (every host gets the full model)
        from jax.experimental import multihost_utils

        U = multihost_utils.process_allgather(U, tiled=True)
        V = multihost_utils.process_allgather(V, tiled=True)
    return U, V


# ---------------------------------------------------------------------------
# Legacy host-side layout (reference implementation)
# ---------------------------------------------------------------------------
#
# The pre-fusion layout: one sub-table per degree bucket, repartitioned
# host-side by slab owner for ring mode. Kept as the REFERENCE the
# property tests check the packed device layout against (every
# (row, col, rating) triple must survive both layouts identically), and
# because its docstrings document the owner-skew failure mode the packed
# layout absorbs. Not used by the training path.


@dataclass
class ShardedBucket:
    """One degree bucket laid out as per-shard sub-tables (flattened).

    ``col_ids/ratings/mask/seg_row`` are ``[S*B, ...]`` where shard ``s``
    owns rows ``[s*B, (s+1)*B)`` — exactly what ``P(axis)`` on dim 0
    yields. ``seg_row`` holds **shard-local** solved-row indices in
    ``[0, R)``; all segments of one solved row live on one shard.
    ``row_ids`` is ``[S*R]`` global factor-row ids (``dummy_row`` for
    padding slots), used for the global scatter of solutions.
    """

    row_ids: np.ndarray  # [S*R] int32 global row ids (dummy-padded)
    col_ids: np.ndarray  # [S*B, K] int32
    ratings: np.ndarray  # [S*B, K] float32
    mask: np.ndarray  # [S*B, K] float32
    seg_row: np.ndarray  # [S*B] int32, shard-local in [0, R)
    shards: int
    rows_per_shard: int  # R
    table_rows_per_shard: int  # B


def shard_bucket(
    bucket: als_ops.PaddedBucket, shards: int, dummy_row: int
) -> ShardedBucket:
    """Lay one PaddedBucket out over ``shards`` with row-segment
    colocation and balanced per-shard table sizes."""
    K = bucket.width
    R0 = len(bucket.row_ids)
    if bucket.seg_row is None:
        nseg = np.ones(R0, np.int64)
        seg_starts = np.arange(R0, dtype=np.int64)
    else:
        seg_of = bucket.seg_row.astype(np.int64)
        nseg = np.bincount(seg_of, minlength=R0)
        # segments of row j are contiguous table rows by construction
        # (ops/als.py build_padded_buckets seg_base layout)
        seg_starts = np.concatenate([[0], np.cumsum(nseg)[:-1]])

    if (nseg == 1).all():
        # fast path: one segment per row -> round-robin is perfectly even
        assign = np.arange(R0, dtype=np.int64) % shards
    else:
        # greedy LPT on segment counts so hot rows don't pile on one shard
        assign = np.empty(R0, np.int64)
        heap = [(0, 0, s) for s in range(shards)]
        heapq.heapify(heap)
        for j in np.argsort(-nseg, kind="stable"):
            load, cnt, s = heapq.heappop(heap)
            assign[j] = s
            heapq.heappush(heap, (load + int(nseg[j]), cnt + 1, s))

    per_shard_rows = np.bincount(assign, minlength=shards)
    per_shard_load = np.bincount(assign, weights=nseg, minlength=shards).astype(
        np.int64
    )
    R = max(1, int(per_shard_rows.max()))
    B = max(1, int(per_shard_load.max()))

    row_ids = np.full((shards, R), dummy_row, np.int32)
    col_ids = np.zeros((shards, B, K), np.int32)
    ratings = np.zeros((shards, B, K), np.float32)
    mask = np.zeros((shards, B, K), np.float32)
    seg_row = np.zeros((shards, B), np.int32)
    for s in range(shards):
        js = np.nonzero(assign == s)[0]  # ascending original order
        if len(js) == 0:
            continue
        ns = nseg[js]
        total = int(ns.sum())
        # source table rows: each row's contiguous segment run
        base = np.cumsum(ns) - ns
        within = np.arange(total) - np.repeat(base, ns)
        src = np.repeat(seg_starts[js], ns) + within
        row_ids[s, : len(js)] = bucket.row_ids[js]
        col_ids[s, :total] = bucket.col_ids[src]
        ratings[s, :total] = bucket.ratings[src]
        mask[s, :total] = bucket.mask[src]
        seg_row[s, :total] = np.repeat(np.arange(len(js), dtype=np.int32), ns)
    return ShardedBucket(
        row_ids=row_ids.reshape(-1),
        col_ids=col_ids.reshape(shards * B, K),
        ratings=ratings.reshape(shards * B, K),
        mask=mask.reshape(shards * B, K),
        seg_row=seg_row.reshape(-1),
        shards=shards,
        rows_per_shard=R,
        table_rows_per_shard=B,
    )


def resegment_skewed_rows(
    sb: ShardedBucket, opp_rows_loc: int, shards: int
) -> ShardedBucket:
    """Split table rows whose entries concentrate on one opposite-slab
    owner, BEFORE ring partitioning.

    ``ring_partition_bucket`` pads every (table row, owner) cell to the
    bucket-wide max ``K_sub``, so a single row with ~K entries on one
    owner drives ``K_sub -> K`` and the whole bucket to S x the flat
    bytes (its docstring's adversarial case). Splitting just the
    offending rows into sub-rows of at most ``ceil(K / S)`` entries per
    owner — more segments of the same solved row, scatter-added by
    ``seg_row`` exactly like hot-row segments — caps ``K_sub`` at the
    spread-case value, so only the skewed rows grow (by their segment
    count) instead of every row paying the padding. (The packed layout
    makes this moot: ``pack_entries`` pads per (row, owner) GROUP, so a
    skewed row only grows its own cell.)
    """
    S, B, K = sb.shards, sb.table_rows_per_shard, sb.col_ids.shape[1]
    T = max(1, -(-K // shards))
    col3 = sb.col_ids.reshape(S, B, K)
    rat3 = sb.ratings.reshape(S, B, K)
    msk3 = sb.mask.reshape(S, B, K)
    seg2 = sb.seg_row.reshape(S, B)
    per_shard: list[list[tuple]] = []
    for s in range(S):
        out_rows: list[tuple] = []
        for b in range(B):
            m = msk3[s, b] > 0
            n = int(m.sum())
            if n == 0:
                continue  # padding slot; re-padded below
            own = col3[s, b][m].astype(np.int64) // opp_rows_loc
            if np.bincount(own, minlength=shards).max() <= T:
                out_rows.append((col3[s, b], rat3[s, b], msk3[s, b], seg2[s, b]))
                continue
            # within-owner rank -> sub-row index; each sub-row holds at
            # most T entries of any one owner
            order = np.argsort(own, kind="stable")
            oo = own[order]
            starts = np.concatenate([[0], np.nonzero(np.diff(oo))[0] + 1])
            counts = np.diff(np.concatenate([starts, [len(oo)]]))
            rank = np.arange(len(oo)) - np.repeat(starts, counts)
            sub = rank // T
            cols_m = col3[s, b][m][order]
            rats_m = rat3[s, b][m][order]
            for i in range(int(sub.max()) + 1):
                pick = sub == i
                c = np.zeros(K, np.int32)
                r = np.zeros(K, np.float32)
                mk = np.zeros(K, np.float32)
                c[: pick.sum()] = cols_m[pick]
                r[: pick.sum()] = rats_m[pick]
                mk[: pick.sum()] = 1.0
                out_rows.append((c, r, mk, seg2[s, b]))
        per_shard.append(out_rows)
    B2 = max(1, max(len(rows) for rows in per_shard))
    col_ids = np.zeros((S, B2, K), np.int32)
    ratings = np.zeros((S, B2, K), np.float32)
    mask = np.zeros((S, B2, K), np.float32)
    seg_row = np.zeros((S, B2), np.int32)
    for s, rows in enumerate(per_shard):
        for j, (c, r, mk, sg) in enumerate(rows):
            col_ids[s, j] = c
            ratings[s, j] = r
            mask[s, j] = mk
            seg_row[s, j] = sg
    return ShardedBucket(
        row_ids=sb.row_ids,
        col_ids=col_ids.reshape(S * B2, K),
        ratings=ratings.reshape(S * B2, K),
        mask=mask.reshape(S * B2, K),
        seg_row=seg_row.reshape(-1),
        shards=S,
        rows_per_shard=sb.rows_per_shard,
        table_rows_per_shard=B2,
    )


def ring_partition_bucket(
    sb: ShardedBucket, opp_rows_loc: int, shards: int
) -> ShardedBucket:
    """Repartition one sharded bucket's tables by opposite-slab OWNER for
    the ring half-step: ``col_ids/ratings/mask`` become ``[S*B, S_owner,
    K_sub]`` where slot ``[b, s, :]`` packs exactly the entries of table
    row ``b`` whose opposite factor row lives on shard ``s`` (owner =
    ``col_id // opp_rows_loc``; factors are row-contiguous over shards).

    Reference semantics for the packed ring layout (the property tests
    compare the two): the per-(row, owner) triples must be identical.
    Its weakness — and why the packed layout replaced it — is the shared
    ``K_sub``: one row with ~K entries on one owner drives ``K_sub -> K``
    and the WHOLE bucket to S x the flat bytes, whereas ``pack_entries``
    pads per group.
    """
    SB, K = sb.col_ids.shape
    m_flat = sb.mask.reshape(-1) > 0
    rows_idx = np.repeat(np.arange(SB, dtype=np.int64), K)[m_flat]
    own = (sb.col_ids.reshape(-1)[m_flat].astype(np.int64)) // opp_rows_loc
    cnt = np.zeros((SB, shards), np.int64)
    np.add.at(cnt, (rows_idx, own), 1)
    K_sub = max(1, int(cnt.max()))
    # within-(row, owner) rank: stable sort by the group key, then
    # position minus group start
    key = rows_idx * shards + own
    order = np.argsort(key, kind="stable")
    ks = key[order]
    starts = np.concatenate([[0], np.nonzero(np.diff(ks))[0] + 1])
    counts = np.diff(np.concatenate([starts, [len(ks)]]))
    rank = np.arange(len(ks)) - np.repeat(starts, counts)
    rr, oo = rows_idx[order], own[order]
    col_ids = np.zeros((SB, shards, K_sub), np.int32)
    ratings = np.zeros((SB, shards, K_sub), np.float32)
    mask = np.zeros((SB, shards, K_sub), np.float32)
    col_ids[rr, oo, rank] = sb.col_ids.reshape(-1)[m_flat][order]
    ratings[rr, oo, rank] = sb.ratings.reshape(-1)[m_flat][order]
    mask[rr, oo, rank] = 1.0
    return ShardedBucket(
        row_ids=sb.row_ids,
        col_ids=col_ids,
        ratings=ratings,
        mask=mask,
        seg_row=sb.seg_row,
        shards=sb.shards,
        rows_per_shard=sb.rows_per_shard,
        table_rows_per_shard=sb.table_rows_per_shard,
    )


def upload_sharded_buckets(
    sharded: Sequence[ShardedBucket], mesh: Mesh, axis: str
) -> tuple:
    """Place the legacy layout on the mesh: tables sharded ``P(axis)``,
    scatter row-ids replicated."""
    table = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    return tuple(
        (
            jax.device_put(sb.row_ids, repl),
            jax.device_put(sb.col_ids, table),
            jax.device_put(sb.ratings, table),
            jax.device_put(sb.mask, table),
            jax.device_put(sb.seg_row, table),
        )
        for sb in sharded
    )


def _table_bytes_per_chip(sbs: Sequence[ShardedBucket], shards: int) -> int:
    """Per-chip bytes of a legacy bucket-table set (col_ids/ratings/mask
    at 12 bytes per slot) — same formula for the flat ``[S*B, K]`` layout
    and the ring-partitioned ``[S*B, S, K_sub]`` one, so the two layouts
    are directly comparable."""
    return sum(sb.col_ids.size * 12 for sb in sbs) // max(1, shards)
