"""Mesh-sharded ALS: the multi-chip training path.

MLlib ALS distributes by blocking users x items across executors and
shuffling factor blocks each half-iteration (external Spark dep; SURVEY
§2.7). The TPU-native design (ALX pattern, PAPERS.md):

- both factor matrices live **sharded row-wise** over the mesh's ``data``
  axis (P("data") on dim 0),
- each half-iteration ``all_gather``s the *opposite* factor matrix over
  ICI inside a ``shard_map`` (it is the smaller working set), solves the
  local shard's normal equations with the same batched bucket math as
  single-chip, and scatters the solutions back into the sharded factors,
- the implicit-feedback Gramian Y^T Y is computed shard-locally and
  ``psum``-reduced — a [D, D] allreduce instead of MLlib's shuffle.

Two properties the round-1 design lacked, now guaranteed:

**Exact hot rows.** Degree-bucketed layouts segment rows hotter than the
widest bucket across several table rows (ops/als.py PaddedBucket). The
shard layout here places **all segments of one solved row on the same
shard** (greedy longest-processing-time assignment balances segment
counts across shards), so the per-segment Gramians are scatter-added
shard-locally before the solve — multi-chip training is bit-for-bit the
same math as single-chip, with no truncation of blockbuster rows.

**One device program.** The whole training run is a single jitted
``lax.fori_loop`` (dynamic trip count) with donated factor buffers; each
half-iteration is one ``shard_map`` region per bucket set. No per-bucket
Python dispatch, no host round-trips of the factors.

**Memory model — two half-step variants, auto-selected.** Per chip, each
half-iteration holds: (a) its shard of both factor matrices —
``(rows + cols) / n_shards * D * itemsize`` bytes, shrinking with mesh
size; (b) its shard of the bucket tables (col_ids/ratings/mask ~= 12
bytes per rating / n_shards), shrinking with mesh size; and (c) the
working set of the opposite factor matrix, which depends on the variant:

- ``gather`` (``all_gather`` of the FULL opposite side): (c) =
  ``opposite_rows * D * itemsize`` bytes per chip, NOT shrinking with
  mesh size. One fused ICI collective — the latency-optimal choice while
  it fits (ALX makes the same trade, PAPERS.md). On 16-GiB v5e the
  gathered side caps at roughly 10^8 rows at rank 20 or 1.6*10^7 at
  rank 128 (at half of HBM). MovieLens-20M (2.7*10^4 items, rank 20 ->
  2 MiB gathered) is far below it.
- ``ring`` (blocked ``ppermute`` rotation, the ring-top-k pattern of
  parallel/ring_topk.py applied to training): each chip keeps only ONE
  opposite-factor slab (``opposite_rows / n_shards * D``) resident;
  slabs rotate around the mesh once per half-step, and each bucket's
  normal equations ``(A, b)`` accumulate in place against the passing
  slabs. (c) becomes slab + accumulators —
  ``opposite_rows/S * D + target_table_rows/S * D^2`` floats — which
  SHRINKS with mesh size, like MLlib's block ALS (whose executors hold
  per-user triangular systems the same way; reference
  examples/scala-parallel-recommendation/custom-prepartor/src/main/
  scala/ALSAlgorithm.scala:72 delegates to that substrate). Bucket
  tables are repartitioned host-side by slab owner
  (``ring_partition_bucket``) so each rotation computes only against
  the entries the passing slab can serve — total gather/Gramian work
  stays at parity with gather mode (up to sub-table padding slop), and
  the real price is S collective hops of the slabs per half-step
  instead of one fused all_gather.

``sharded_als_train`` picks the variant per run: ``gather`` while the
gathered side fits ``ALSParams.sharded_gather_budget_bytes``, ``ring``
past it (``mode=`` overrides). Per-bucket ``[B, K, D]`` factor-gather
temps are bounded by ``ALSParams.gather_chunk_bytes`` in BOTH variants
(the ring gathers from its resident slab through the same chunked
helper). Both variants are exact on segmented hot rows and share the
single-chip bucket math (ops/als.py `_bucket_weights` /
`_finish_bucket_solve`).
"""

from __future__ import annotations

import functools
import heapq
import logging
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.ops import als as als_ops
from predictionio_tpu.parallel.compat import pcast_varying, shard_map

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Host-side: shard-aware bucket layout
# ---------------------------------------------------------------------------


@dataclass
class ShardedBucket:
    """One degree bucket laid out as per-shard sub-tables (flattened).

    ``col_ids/ratings/mask/seg_row`` are ``[S*B, ...]`` where shard ``s``
    owns rows ``[s*B, (s+1)*B)`` — exactly what ``P(axis)`` on dim 0
    yields. ``seg_row`` holds **shard-local** solved-row indices in
    ``[0, R)``; all segments of one solved row live on one shard.
    ``row_ids`` is ``[S*R]`` global factor-row ids (``dummy_row`` for
    padding slots), used for the global scatter of solutions.
    """

    row_ids: np.ndarray  # [S*R] int32 global row ids (dummy-padded)
    col_ids: np.ndarray  # [S*B, K] int32
    ratings: np.ndarray  # [S*B, K] float32
    mask: np.ndarray  # [S*B, K] float32
    seg_row: np.ndarray  # [S*B] int32, shard-local in [0, R)
    shards: int
    rows_per_shard: int  # R
    table_rows_per_shard: int  # B


def shard_bucket(
    bucket: als_ops.PaddedBucket, shards: int, dummy_row: int
) -> ShardedBucket:
    """Lay one PaddedBucket out over ``shards`` with row-segment
    colocation and balanced per-shard table sizes."""
    K = bucket.width
    R0 = len(bucket.row_ids)
    if bucket.seg_row is None:
        nseg = np.ones(R0, np.int64)
        seg_starts = np.arange(R0, dtype=np.int64)
    else:
        seg_of = bucket.seg_row.astype(np.int64)
        nseg = np.bincount(seg_of, minlength=R0)
        # segments of row j are contiguous table rows by construction
        # (ops/als.py build_padded_buckets seg_base layout)
        seg_starts = np.concatenate([[0], np.cumsum(nseg)[:-1]])

    if (nseg == 1).all():
        # fast path: one segment per row -> round-robin is perfectly even
        assign = np.arange(R0, dtype=np.int64) % shards
    else:
        # greedy LPT on segment counts so hot rows don't pile on one shard
        assign = np.empty(R0, np.int64)
        heap = [(0, 0, s) for s in range(shards)]
        heapq.heapify(heap)
        for j in np.argsort(-nseg, kind="stable"):
            load, cnt, s = heapq.heappop(heap)
            assign[j] = s
            heapq.heappush(heap, (load + int(nseg[j]), cnt + 1, s))

    per_shard_rows = np.bincount(assign, minlength=shards)
    per_shard_load = np.bincount(assign, weights=nseg, minlength=shards).astype(
        np.int64
    )
    R = max(1, int(per_shard_rows.max()))
    B = max(1, int(per_shard_load.max()))

    row_ids = np.full((shards, R), dummy_row, np.int32)
    col_ids = np.zeros((shards, B, K), np.int32)
    ratings = np.zeros((shards, B, K), np.float32)
    mask = np.zeros((shards, B, K), np.float32)
    seg_row = np.zeros((shards, B), np.int32)
    for s in range(shards):
        js = np.nonzero(assign == s)[0]  # ascending original order
        if len(js) == 0:
            continue
        ns = nseg[js]
        total = int(ns.sum())
        # source table rows: each row's contiguous segment run
        base = np.cumsum(ns) - ns
        within = np.arange(total) - np.repeat(base, ns)
        src = np.repeat(seg_starts[js], ns) + within
        row_ids[s, : len(js)] = bucket.row_ids[js]
        col_ids[s, :total] = bucket.col_ids[src]
        ratings[s, :total] = bucket.ratings[src]
        mask[s, :total] = bucket.mask[src]
        seg_row[s, :total] = np.repeat(np.arange(len(js), dtype=np.int32), ns)
    return ShardedBucket(
        row_ids=row_ids.reshape(-1),
        col_ids=col_ids.reshape(shards * B, K),
        ratings=ratings.reshape(shards * B, K),
        mask=mask.reshape(shards * B, K),
        seg_row=seg_row.reshape(-1),
        shards=shards,
        rows_per_shard=R,
        table_rows_per_shard=B,
    )


def upload_sharded_buckets(
    sharded: Sequence[ShardedBucket], mesh: Mesh, axis: str
) -> tuple:
    """Place the layout on the mesh once per training run: tables sharded
    ``P(axis)``, scatter row-ids replicated."""
    table = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    return tuple(
        (
            jax.device_put(sb.row_ids, repl),
            jax.device_put(sb.col_ids, table),
            jax.device_put(sb.ratings, table),
            jax.device_put(sb.mask, table),
            jax.device_put(sb.seg_row, table),
        )
        for sb in sharded
    )


def resegment_skewed_rows(
    sb: ShardedBucket, opp_rows_loc: int, shards: int
) -> ShardedBucket:
    """Split table rows whose entries concentrate on one opposite-slab
    owner, BEFORE ring partitioning.

    ``ring_partition_bucket`` pads every (table row, owner) cell to the
    bucket-wide max ``K_sub``, so a single row with ~K entries on one
    owner drives ``K_sub -> K`` and the whole bucket to S x the flat
    bytes (its docstring's adversarial case). Splitting just the
    offending rows into sub-rows of at most ``ceil(K / S)`` entries per
    owner — more segments of the same solved row, scatter-added by
    ``seg_row`` exactly like hot-row segments — caps ``K_sub`` at the
    spread-case value, so only the skewed rows grow (by their segment
    count) instead of every row paying the padding.
    """
    S, B, K = sb.shards, sb.table_rows_per_shard, sb.col_ids.shape[1]
    T = max(1, -(-K // shards))
    col3 = sb.col_ids.reshape(S, B, K)
    rat3 = sb.ratings.reshape(S, B, K)
    msk3 = sb.mask.reshape(S, B, K)
    seg2 = sb.seg_row.reshape(S, B)
    per_shard: list[list[tuple]] = []
    for s in range(S):
        out_rows: list[tuple] = []
        for b in range(B):
            m = msk3[s, b] > 0
            n = int(m.sum())
            if n == 0:
                continue  # padding slot; re-padded below
            own = col3[s, b][m].astype(np.int64) // opp_rows_loc
            if np.bincount(own, minlength=shards).max() <= T:
                out_rows.append((col3[s, b], rat3[s, b], msk3[s, b], seg2[s, b]))
                continue
            # within-owner rank -> sub-row index; each sub-row holds at
            # most T entries of any one owner
            order = np.argsort(own, kind="stable")
            oo = own[order]
            starts = np.concatenate([[0], np.nonzero(np.diff(oo))[0] + 1])
            counts = np.diff(np.concatenate([starts, [len(oo)]]))
            rank = np.arange(len(oo)) - np.repeat(starts, counts)
            sub = rank // T
            cols_m = col3[s, b][m][order]
            rats_m = rat3[s, b][m][order]
            for i in range(int(sub.max()) + 1):
                pick = sub == i
                c = np.zeros(K, np.int32)
                r = np.zeros(K, np.float32)
                mk = np.zeros(K, np.float32)
                c[: pick.sum()] = cols_m[pick]
                r[: pick.sum()] = rats_m[pick]
                mk[: pick.sum()] = 1.0
                out_rows.append((c, r, mk, seg2[s, b]))
        per_shard.append(out_rows)
    B2 = max(1, max(len(rows) for rows in per_shard))
    col_ids = np.zeros((S, B2, K), np.int32)
    ratings = np.zeros((S, B2, K), np.float32)
    mask = np.zeros((S, B2, K), np.float32)
    seg_row = np.zeros((S, B2), np.int32)
    for s, rows in enumerate(per_shard):
        for j, (c, r, mk, sg) in enumerate(rows):
            col_ids[s, j] = c
            ratings[s, j] = r
            mask[s, j] = mk
            seg_row[s, j] = sg
    return ShardedBucket(
        row_ids=sb.row_ids,
        col_ids=col_ids.reshape(S * B2, K),
        ratings=ratings.reshape(S * B2, K),
        mask=mask.reshape(S * B2, K),
        seg_row=seg_row.reshape(-1),
        shards=S,
        rows_per_shard=sb.rows_per_shard,
        table_rows_per_shard=B2,
    )


def ring_partition_bucket(
    sb: ShardedBucket, opp_rows_loc: int, shards: int
) -> ShardedBucket:
    """Repartition one sharded bucket's tables by opposite-slab OWNER for
    the ring half-step: ``col_ids/ratings/mask`` become ``[S*B, S_owner,
    K_sub]`` where slot ``[b, s, :]`` packs exactly the entries of table
    row ``b`` whose opposite factor row lives on shard ``s`` (owner =
    ``col_id // opp_rows_loc``; factors are row-contiguous over shards).

    This is what keeps ring-mode COMPUTE at parity with gather mode: each
    rotation consumes only its ``[B, K_sub]`` sub-table (``K_sub`` = max
    entries any (row, owner) pair holds) instead of re-gathering the full
    ``[B, K]`` table with (S-1)/S of the weights zeroed. Total ring work
    is ``S * B * K_sub * D^2`` vs gather's ``B * K * D^2`` — parity up to
    padding slop when entries spread across owners (random id layouts;
    the common case), degrading only for adversarial skew where one
    (row, owner) pair holds most of a row's entries. Table memory is
    ``S * K_sub / K`` times the flat layout — near parity in the common
    spread case (``K_sub ~= K/S``), but up to S times under the same
    adversarial skew (``K_sub -> K``); size ring-mode runs accordingly.
    """
    SB, K = sb.col_ids.shape
    m_flat = sb.mask.reshape(-1) > 0
    rows_idx = np.repeat(np.arange(SB, dtype=np.int64), K)[m_flat]
    own = (sb.col_ids.reshape(-1)[m_flat].astype(np.int64)) // opp_rows_loc
    cnt = np.zeros((SB, shards), np.int64)
    np.add.at(cnt, (rows_idx, own), 1)
    K_sub = max(1, int(cnt.max()))
    # within-(row, owner) rank: stable sort by the group key, then
    # position minus group start
    key = rows_idx * shards + own
    order = np.argsort(key, kind="stable")
    ks = key[order]
    starts = np.concatenate([[0], np.nonzero(np.diff(ks))[0] + 1])
    counts = np.diff(np.concatenate([starts, [len(ks)]]))
    rank = np.arange(len(ks)) - np.repeat(starts, counts)
    rr, oo = rows_idx[order], own[order]
    col_ids = np.zeros((SB, shards, K_sub), np.int32)
    ratings = np.zeros((SB, shards, K_sub), np.float32)
    mask = np.zeros((SB, shards, K_sub), np.float32)
    col_ids[rr, oo, rank] = sb.col_ids.reshape(-1)[m_flat][order]
    ratings[rr, oo, rank] = sb.ratings.reshape(-1)[m_flat][order]
    mask[rr, oo, rank] = 1.0
    return ShardedBucket(
        row_ids=sb.row_ids,
        col_ids=col_ids,
        ratings=ratings,
        mask=mask,
        seg_row=sb.seg_row,
        shards=sb.shards,
        rows_per_shard=sb.rows_per_shard,
        table_rows_per_shard=sb.table_rows_per_shard,
    )


# ---------------------------------------------------------------------------
# Device-side: fused training program
# ---------------------------------------------------------------------------


@dataclass
class ShardedALSState:
    """Factors resident on the mesh, each with one trailing dummy row."""

    mesh: Mesh
    axis: str
    U: jax.Array  # [num_rows+pad, D] sharded P(axis)
    V: jax.Array  # [num_cols+pad, D] sharded P(axis)
    num_rows: int
    num_cols: int


def _padded_len(n: int, shards: int) -> int:
    return n + 1 + ((-(n + 1)) % shards)  # +1 dummy row, then round up


def init_sharded_factors(
    data: als_ops.RatingsData,
    params: als_ops.ALSParams,
    mesh: Mesh,
    axis: str = "data",
) -> ShardedALSState:
    shards = mesh.shape[axis]
    key_u, key_v = jax.random.split(jax.random.PRNGKey(params.seed))
    u_len = _padded_len(data.num_rows, shards)
    v_len = _padded_len(data.num_cols, shards)
    # draw the TRUE-size init (identical to single-chip als_train for the
    # same seed — the parity tests rely on trajectory equality), then pad
    # with zeros; pad rows contribute nothing to the psum'd Gramian
    U = np.zeros((u_len, params.rank), np.float32)
    V = np.zeros((v_len, params.rank), np.float32)
    U[: data.num_rows] = np.asarray(
        als_ops.init_factors(data.num_rows, params.rank, key_u)
    )
    V[: data.num_cols] = np.asarray(
        als_ops.init_factors(data.num_cols, params.rank, key_v)
    )
    sharding = NamedSharding(mesh, P(axis))
    # factors persist (and all_gather) in storage_dtype: bf16 halves the
    # per-half-iteration ICI traffic and the gathered working set — the
    # (c) term of the memory model above — while solves still accumulate
    # float32 (ops/als.py ALSParams.storage_dtype)
    U_dev = jax.device_put(U, sharding)
    V_dev = jax.device_put(V, sharding)
    if params.storage_dtype == "int8":
        # per-row quantization reduces over the (unsharded) rank dim
        # only, so the row sharding of both values and scales is
        # preserved; the all_gather/ppermute'd working set becomes the
        # (int8 values, f32 scales) pair — ~4x fewer ICI bytes than f32
        U_dev = als_ops.quantize_rows(U_dev)
        V_dev = als_ops.quantize_rows(V_dev)
    elif params.storage_dtype != "float32":
        sd = jnp.dtype(params.storage_dtype)
        U_dev = U_dev.astype(sd)  # elementwise: sharding preserved
        V_dev = V_dev.astype(sd)
    return ShardedALSState(
        mesh=mesh,
        axis=axis,
        U=U_dev,
        V=V_dev,
        num_rows=data.num_rows,
        num_cols=data.num_cols,
    )


@functools.partial(
    jax.jit,
    static_argnames=("params", "mesh", "axis", "mode"),
    donate_argnums=(0, 1),
)
def _train_fused_sharded(
    U,
    V,
    row_arrays,
    col_arrays,
    iterations,
    params: als_ops.ALSParams,
    mesh,
    axis,
    mode: str = "gather",
):
    """The whole sharded training run as ONE device program.

    ``lax.fori_loop`` over iterations (dynamic trip count — one compile
    serves any iteration count); each half-step is a single ``shard_map``
    region solving every bucket, followed by global scatters of the
    solutions into the sharded factor matrix. Two half-step variants
    (module docstring, "Memory model"):

    - ``mode="gather"``: one ``all_gather`` of the opposite factors; each
      bucket solves against the full gathered matrix.
    - ``mode="ring"``: the opposite factors never materialize whole on
      any chip. A ``fori_loop`` over the mesh size rotates opposite
      slabs with ``ppermute``; per rotation each bucket masks its
      entries down to the ones owned by the passing slab and
      accumulates their Gramian/rhs contribution into persistent
      ``(A, b)`` normal equations, which are solved once the ring
      completes. Entry ownership is index arithmetic: factors are
      row-contiguous over shards, so global column id ``g`` lives on
      shard ``g // rows_per_shard`` at offset ``g % rows_per_shard``.

    The implicit-feedback Gramian is psum'd from shard-local factors in
    both variants (it never needed the gather).
    """
    shards = mesh.shape[axis]
    factor_spec = NamedSharding(mesh, P(axis))
    dt = jnp.dtype(params.compute_dtype)

    def gather_shard_fn(rows_per, other_shard, *flat):
        # int8 storage: other_shard is the (values, scales) pair; gather
        # both leaves so the ICI collective moves quantized bytes
        other_full = jax.tree_util.tree_map(
            lambda t: jax.lax.all_gather(t, axis, tiled=True), other_shard
        )
        gram = None
        if params.implicit:
            gram = jax.lax.psum(
                als_ops.compute_gram(other_shard, params.compute_dtype), axis
            )
        outs = []
        for bi in range(0, len(flat) // 4):
            col_ids, ratings, mask, seg_row = flat[bi * 4 : bi * 4 + 4]
            outs.append(
                als_ops._solve_bucket_inline(
                    other_full,
                    gram,
                    (col_ids, ratings, mask),
                    params,
                    seg_row=seg_row,
                    num_solved_rows=rows_per[bi],
                )
            )
        return tuple(outs)

    def ring_shard_fn(rows_per, other_shard, *flat):
        # tables arrive OWNER-PARTITIONED (`ring_partition_bucket`):
        # [B_loc, S, K_sub], slot [:, s, :] holding the entries whose
        # opposite factor row lives on shard s — each rotation slices out
        # exactly the sub-table the passing slab can serve, keeping ring
        # compute at parity with gather mode.
        slab_rows = als_ops.table_rows(other_shard)
        D = als_ops.table_dim(other_shard)
        me = jax.lax.axis_index(axis)
        gram = None
        if params.implicit:
            gram = jax.lax.psum(
                als_ops.compute_gram(other_shard, params.compute_dtype), axis
            )
        nb = len(flat) // 4
        # zero accumulators are constants; mark them device-varying so
        # they sit in the fori_loop carry beside the ppermute'd slab
        varying = lambda x: pcast_varying(x, axis)
        buckets3 = [flat[bi * 4 : bi * 4 + 3] for bi in range(nb)]
        accs = tuple(
            (
                varying(jnp.zeros((col_ids.shape[0], D, D), jnp.float32)),
                varying(jnp.zeros((col_ids.shape[0], D), jnp.float32)),
            )
            for col_ids, _r, _m in buckets3
        )
        # send my slab to the next shard each step; after t rotations I
        # hold the slab of shard (me - t) mod S
        perm = [(i, (i + 1) % shards) for i in range(shards)]

        def owner_slice(x, owner):
            # [B, S, K_sub] -> the current owner's [B, K_sub] sub-table
            return jax.lax.dynamic_slice_in_dim(x, owner, 1, axis=1)[:, 0]

        def accumulate(owner, slab, accs):
            new_accs = []
            for (col_ids, ratings, mask), (A, b) in zip(buckets3, accs):
                sub_ids = owner_slice(col_ids, owner)
                # weights are computed on the sliced [B, K_sub] sub-table
                # per rotation (elementwise, negligible) rather than
                # precomputed whole — ring mode exists for HBM relief
                w, r = als_ops._bucket_weights(
                    owner_slice(ratings, owner),
                    owner_slice(mask, owner),
                    params,
                    params.alpha,
                )
                # padding slots hold col_id 0 with zero weight; clip keeps
                # their local index in range, the weight kills the term
                lid = jnp.clip(sub_ids - owner * slab_rows, 0, slab_rows - 1)
                A_c, b_c = als_ops._gramian_rhs_gathered(
                    slab, lid, w, r, dt, params.gather_chunk_bytes
                )
                new_accs.append((A + A_c, b + b_c))
            return tuple(new_accs)

        def rotate(t, carry):
            slab, accs = carry
            accs = accumulate(jnp.mod(me - t, shards), slab, accs)
            # int8 slabs rotate as (values, scales) — quantized ICI hops
            slab = jax.tree_util.tree_map(
                lambda x: jax.lax.ppermute(x, axis, perm), slab
            )
            return slab, accs

        # S-1 rotate-and-accumulate steps, then the final slab's
        # accumulation peeled out of the loop: S-1 collective hops per
        # half-step, not S (the last rotation's result would be unused)
        slab, accs = jax.lax.fori_loop(
            0, shards - 1, rotate, (other_shard, accs)
        )
        accs = accumulate(jnp.mod(me - (shards - 1), shards), slab, accs)
        outs = []
        for bi, (A, b) in enumerate(accs):
            mask, seg_row = flat[bi * 4 + 2], flat[bi * 4 + 3]
            outs.append(
                als_ops._finish_bucket_solve(
                    A,
                    b,
                    mask.sum(axis=(1, 2)),
                    gram,
                    params,
                    seg_row,
                    rows_per[bi],
                    params.reg,
                )
            )
        return tuple(outs)

    shard_fn = {"gather": gather_shard_fn, "ring": ring_shard_fn}[mode]

    def half(target, other, buckets):
        # per-bucket solved-rows-per-shard, static at trace time
        rows_per = [b[0].shape[0] // shards for b in buckets]
        flat = []
        for _row_ids, col_ids, ratings, mask, seg_row in buckets:
            flat += [col_ids, ratings, mask, seg_row]
        # int8 factor tables are (values, scales) pairs: spell out the
        # matching spec structure (both leaves row-sharded over axis)
        other_spec = (
            (P(axis), P(axis)) if isinstance(other, tuple) else P(axis)
        )
        xs = shard_map(
            functools.partial(shard_fn, rows_per),
            mesh=mesh,
            in_specs=(other_spec,) + (P(axis),) * len(flat),
            out_specs=(P(axis),) * len(buckets),
        )(other, *flat)
        for x, (row_ids, *_rest) in zip(xs, buckets):
            target = als_ops._scatter_rows(target, row_ids, x)
        return jax.tree_util.tree_map(
            lambda t: jax.lax.with_sharding_constraint(t, factor_spec), target
        )

    def step(_, carry):
        U, V = carry
        U = half(U, V, row_arrays)
        V = half(V, U, col_arrays)
        return (U, V)

    return jax.lax.fori_loop(0, iterations, step, (U, V))


def choose_sharded_mode(
    data: als_ops.RatingsData, params: als_ops.ALSParams, shards: int
) -> str:
    """Pick the half-step variant for a run: ``gather`` while the larger
    gathered side fits ``params.sharded_gather_budget_bytes`` per chip,
    ``ring`` past it (module docstring, "Memory model")."""
    rows = max(
        _padded_len(data.num_rows, shards), _padded_len(data.num_cols, shards)
    )
    gathered = rows * _factor_row_bytes(params)
    return "ring" if gathered > params.sharded_gather_budget_bytes else "gather"


def _factor_row_bytes(params: als_ops.ALSParams) -> int:
    """Bytes one gathered factor row costs in storage form (int8 rows
    carry their f32 per-row scale alongside the quantized values)."""
    if params.storage_dtype == "int8":
        return params.rank + 4
    return params.rank * jnp.dtype(params.storage_dtype).itemsize


def _table_bytes_per_chip(sbs: Sequence[ShardedBucket], shards: int) -> int:
    """Per-chip bytes of a bucket-table set (col_ids/ratings/mask at 12
    bytes per slot) — same formula for the flat ``[S*B, K]`` layout and
    the ring-partitioned ``[S*B, S, K_sub]`` one, so the two layouts are
    directly comparable."""
    return sum(sb.col_ids.size * 12 for sb in sbs) // max(1, shards)


def sharded_als_train(
    data: als_ops.RatingsData,
    params: als_ops.ALSParams,
    mesh: Mesh,
    axis: str = "data",
    mode: str = "auto",
) -> tuple[jax.Array, jax.Array]:
    """Full multi-chip ALS with mesh-resident factors.

    Exact on arbitrarily hot rows: segmented buckets are consumed as-is
    (segments colocated per shard — see ``shard_bucket``), so results
    match single-chip ``als_train`` for the same seed. ``mode`` is
    ``"gather"``, ``"ring"``, or ``"auto"`` (default: pick by the
    per-chip budget — ``choose_sharded_mode``). Returns (U, V) trimmed
    to the true row counts (still sharded device arrays)."""
    import dataclasses

    if axis not in mesh.shape:
        raise ValueError(
            f"mesh has axes {tuple(mesh.axis_names)} but the sharded ALS "
            f"trainer shards over {axis!r}; name one mesh axis {axis!r} "
            f"(e.g. --mesh {axis}=N) or pass axis="
        )
    shards = mesh.shape[axis]
    if mode == "auto":
        mode = choose_sharded_mode(data, params, shards)
    elif mode not in ("gather", "ring"):
        raise ValueError(f"mode must be auto|gather|ring, got {mode!r}")
    state = init_sharded_factors(data, params, mesh, axis)
    u_len = als_ops.table_rows(state.U)
    v_len = als_ops.table_rows(state.V)
    row_sb = [shard_bucket(b, shards, u_len - 1) for b in data.row_buckets]
    col_sb = [shard_bucket(b, shards, v_len - 1) for b in data.col_buckets]
    if mode == "ring":
        # partition each table by opposite-slab owner so every rotation
        # consumes only the sub-table the passing slab can serve
        def partition(rsb, csb):
            return (
                [ring_partition_bucket(sb, v_len // shards, shards) for sb in rsb],
                [ring_partition_bucket(sb, u_len // shards, shards) for sb in csb],
            )

        flat_bytes = _table_bytes_per_chip(row_sb + col_sb, shards)
        row_rp, col_rp = partition(row_sb, col_sb)
        part_bytes = _table_bytes_per_chip(row_rp + col_rp, shards)
        budget = params.sharded_gather_budget_bytes
        if part_bytes > 2 * flat_bytes and part_bytes > budget:
            # adversarial owner skew: some (row, owner) pair concentrates
            # most of a row's entries, so K_sub -> K and EVERY table row
            # pays S * K_sub slots (ring_partition_bucket docstring).
            # Re-segment just the offending rows through the hot-row
            # machinery (seg_row scatter-add): splitting them into
            # sub-rows capped at ceil(K/S) entries per owner restores
            # K_sub to the spread-case value, so only the skewed rows
            # grow (extra segments) instead of the whole table.
            logger.warning(
                "ring-mode bucket tables blow up under owner skew: %d "
                "bytes/chip partitioned vs %d flat (budget %d); "
                "re-segmenting skewed rows",
                part_bytes, flat_bytes, budget,
            )
            row_sb2 = [
                resegment_skewed_rows(sb, v_len // shards, shards)
                for sb in row_sb
            ]
            col_sb2 = [
                resegment_skewed_rows(sb, u_len // shards, shards)
                for sb in col_sb
            ]
            row_rp2, col_rp2 = partition(row_sb2, col_sb2)
            part2 = _table_bytes_per_chip(row_rp2 + col_rp2, shards)
            if part2 < part_bytes:
                # narrower segments contained the skew (only the
                # offending rows multiplied, the rest shrank)
                row_rp, col_rp, part_bytes = row_rp2, col_rp2, part2
            if part_bytes > budget:
                raise ValueError(
                    f"ring-mode bucket tables need {part_bytes} bytes/chip "
                    f"even after re-segmentation (flat layout: {flat_bytes}), "
                    f"over sharded_gather_budget_bytes={budget}; raise the "
                    "budget, add chips, or thin the skewed rows"
                )
        row_sb, col_sb = row_rp, col_rp
    row_arrays = upload_sharded_buckets(row_sb, mesh, axis)
    col_arrays = upload_sharded_buckets(col_sb, mesh, axis)
    # iterations rides as a dynamic loop bound (shared compile across
    # iteration counts, like the single-chip _train_fused)
    static_params = dataclasses.replace(params, iterations=0)
    U, V = _train_fused_sharded(
        state.U,
        state.V,
        row_arrays,
        col_arrays,
        params.iterations,
        static_params,
        mesh,
        axis,
        mode,
    )
    return (
        als_ops.slice_rows(U, data.num_rows),
        als_ops.slice_rows(V, data.num_cols),
    )


def train_for_context(
    data: als_ops.RatingsData,
    params: als_ops.ALSParams,
    ctx=None,
    sharded: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Framework dispatch point: the engine-param ``shardedTrain`` knob.

    Templates call this from ``Algorithm.train``; with ``sharded`` the
    run executes on the WorkflowContext's device mesh (the production
    multi-chip path — the TPU replacement for MLlib ALS's Spark-cluster
    execution, reference examples/scala-parallel-recommendation/
    custom-prepartor/src/main/scala/ALSAlgorithm.scala:72), otherwise on
    the single default device.
    """
    if not sharded or ctx is None:
        return als_ops.als_train(data, params)
    mesh = ctx.mesh
    # shard over "data" when present; a 1-D mesh shards over its only axis
    if "data" in mesh.shape:
        axis = "data"
    elif len(mesh.axis_names) == 1:
        axis = mesh.axis_names[0]
    else:
        raise ValueError(
            f"shardedTrain needs a 'data' axis on the mesh; got axes "
            f"{tuple(mesh.axis_names)}"
        )
    U, V = sharded_als_train(data, params, mesh, axis)
    if jax.process_count() > 1:
        # multi-host: shards live on other hosts' devices; templates
        # np.asarray the factors for persistence, so gather them to
        # host-replicated arrays (every host gets the full model)
        from jax.experimental import multihost_utils

        U = multihost_utils.process_allgather(U, tiled=True)
        V = multihost_utils.process_allgather(V, tiled=True)
    return U, V
