"""Mesh-sharded ALS: the multi-chip training step.

MLlib ALS distributes by blocking users x items across executors and
shuffling factor blocks each half-iteration (external Spark dep; SURVEY
§2.7). The TPU-native design (ALX pattern, PAPERS.md):

- both factor matrices live **sharded row-wise** over the mesh's ``data``
  axis (P("data") on dim 0),
- each half-iteration ``all_gather``s the *opposite* factor matrix over
  ICI (it is the smaller working set), solves the local shard's normal
  equations with the same batched bucket solves as single-chip, and leaves
  the updated factors sharded in place,
- the implicit-feedback Gramian Y^T Y is computed shard-locally and
  ``psum``-reduced — a [D, D] allreduce instead of MLlib's shuffle.

Bucket arrays are padded and uploaded to the mesh **once** before the
iteration loop (they are training-constant); padding rows solve an
identity system and scatter into a dummy factor row. Factor rows beyond
the true count are zero-initialized so they contribute nothing to the
psum'd Gramian.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from predictionio_tpu.ops import als as als_ops


# ---------------------------------------------------------------------------
# Host-side: pad buckets for even sharding, upload once
# ---------------------------------------------------------------------------


@dataclass
class DeviceBucket:
    """A PaddedBucket padded to the shard count and resident on the mesh.

    Padding rows have mask == 0 and scatter into ``dummy_row`` (an extra
    factor row appended for this purpose).
    """

    row_ids: jax.Array  # [B'] int32 (replicated; used for host-side scatter)
    col_ids: jax.Array  # [B', K] sharded P(axis)
    ratings: jax.Array  # [B', K] sharded P(axis)
    mask: jax.Array  # [B', K] sharded P(axis)


def upload_buckets(
    buckets: Sequence[als_ops.PaddedBucket],
    mesh: Mesh,
    axis: str,
    dummy_row: int,
) -> list[DeviceBucket]:
    """Pad each bucket so B is divisible by the mesh axis size and place
    the arrays sharded on the mesh. Done once per training run."""
    shards = mesh.shape[axis]
    sharding = NamedSharding(mesh, P(axis))
    out = []
    for bucket in buckets:
        if bucket.seg_row is not None:
            raise ValueError(
                "mesh-sharded ALS cannot consume segmented buckets (segments "
                "of one row may land on different shards); build the ratings "
                "data with segment=False"
            )
        B, K = bucket.col_ids.shape
        pad = (-B) % shards
        row_ids = np.concatenate(
            [bucket.row_ids, np.full(pad, dummy_row, dtype=np.int32)]
        )
        col_ids = np.concatenate([bucket.col_ids, np.zeros((pad, K), np.int32)])
        ratings = np.concatenate([bucket.ratings, np.zeros((pad, K), np.float32)])
        mask = np.concatenate([bucket.mask, np.zeros((pad, K), np.float32)])
        out.append(
            DeviceBucket(
                row_ids=jnp.asarray(row_ids),
                col_ids=jax.device_put(col_ids, sharding),
                ratings=jax.device_put(ratings, sharding),
                mask=jax.device_put(mask, sharding),
            )
        )
    return out


# ---------------------------------------------------------------------------
# Device-side: shard_map'ed half-step
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh",
        "axis",
        "implicit",
        "alpha",
        "weighted_reg",
        "implicit_weighted_reg",
        "compute_dtype",
        "use_pallas",
    ),
)
def sharded_solve_bucket(
    factors_other,  # [R+pad, D] sharded P(axis) on dim 0
    col_ids,  # [B', K] sharded P(axis)
    ratings,
    mask,
    reg: float,
    *,
    mesh: Mesh,
    axis: str = "data",
    implicit: bool = False,
    alpha: float = 1.0,
    weighted_reg: bool = True,
    implicit_weighted_reg: bool = False,
    compute_dtype: str = "float32",
    use_pallas: bool = False,
):
    """Solve one bucket with factors_other sharded row-wise.

    Inside each shard: all_gather(factors_other) over ICI -> local batched
    solve. For implicit feedback the global Gramian is psum-reduced from
    shard-local partial Gramians first.
    """

    def local(f_other_shard, col_ids_l, ratings_l, mask_l):
        f_other = jax.lax.all_gather(f_other_shard, axis, tiled=True)
        if implicit:
            part = als_ops.compute_gram(f_other_shard, compute_dtype)
            gram = jax.lax.psum(part, axis)
            return als_ops.solve_bucket_implicit(
                f_other,
                gram,
                col_ids_l,
                ratings_l,
                mask_l,
                reg=reg,
                alpha=alpha,
                weighted_reg=implicit_weighted_reg,
                compute_dtype=compute_dtype,
                use_pallas=use_pallas,
            )
        return als_ops.solve_bucket_explicit(
            f_other,
            col_ids_l,
            ratings_l,
            mask_l,
            reg=reg,
            weighted_reg=weighted_reg,
            compute_dtype=compute_dtype,
            use_pallas=use_pallas,
        )

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=P(axis),
    )(factors_other, col_ids, ratings, mask)


# ---------------------------------------------------------------------------
# Full sharded training
# ---------------------------------------------------------------------------


@dataclass
class ShardedALSState:
    """Factors resident on the mesh, each with one trailing dummy row."""

    mesh: Mesh
    axis: str
    U: jax.Array  # [num_rows+pad, D] sharded P(axis)
    V: jax.Array  # [num_cols+pad, D] sharded P(axis)
    num_rows: int
    num_cols: int


def _padded_len(n: int, shards: int) -> int:
    return n + 1 + ((-(n + 1)) % shards)  # +1 dummy row, then round up


def init_sharded_factors(
    data: als_ops.RatingsData, params: als_ops.ALSParams, mesh: Mesh, axis: str = "data"
) -> ShardedALSState:
    shards = mesh.shape[axis]
    key_u, key_v = jax.random.split(jax.random.PRNGKey(params.seed))
    u_len = _padded_len(data.num_rows, shards)
    v_len = _padded_len(data.num_cols, shards)
    U = als_ops.init_factors(u_len, params.rank, key_u)
    V = als_ops.init_factors(v_len, params.rank, key_v)
    # zero the dummy/pad rows: they are never solved but WOULD otherwise
    # pollute the psum'd implicit Gramian with their random init
    U = U.at[data.num_rows:].set(0.0)
    V = V.at[data.num_cols:].set(0.0)
    sharding = NamedSharding(mesh, P(axis))
    return ShardedALSState(
        mesh=mesh,
        axis=axis,
        U=jax.device_put(U, sharding),
        V=jax.device_put(V, sharding),
        num_rows=data.num_rows,
        num_cols=data.num_cols,
    )


def sharded_half_step(
    state: ShardedALSState,
    factors_self,
    factors_other,
    device_buckets: Sequence[DeviceBucket],
    params: als_ops.ALSParams,
):
    """Update factors_self (sharded) from factors_other (sharded), over
    pre-uploaded buckets."""
    for db in device_buckets:
        x = sharded_solve_bucket(
            factors_other,
            db.col_ids,
            db.ratings,
            db.mask,
            params.reg,
            mesh=state.mesh,
            axis=state.axis,
            implicit=params.implicit,
            alpha=params.alpha,
            weighted_reg=params.weighted_reg,
            implicit_weighted_reg=params.implicit_weighted_reg,
            compute_dtype=params.compute_dtype,
            use_pallas=params.use_pallas,
        )
        # scatter updated rows; padding rows hit the dummy row harmlessly
        factors_self = factors_self.at[db.row_ids].set(x)
    return factors_self


def sharded_als_train(
    data: als_ops.RatingsData,
    params: als_ops.ALSParams,
    mesh: Mesh,
    axis: str = "data",
) -> tuple[jax.Array, jax.Array]:
    """Full ALS with mesh-resident factors. Returns (U, V) trimmed to the
    true row counts (still device arrays; shard layout preserved until the
    caller re-shards or fetches)."""
    if any(
        b.seg_row is not None for b in (*data.row_buckets, *data.col_buckets)
    ):
        # segments of one row cannot span devices; rebuild this trainer's
        # layout with truncation from the retained COO triples
        data = als_ops.build_ratings_data(
            data.rows,
            data.cols,
            data.vals,
            data.num_rows,
            data.num_cols,
            bucket_widths=tuple(
                sorted({b.width for b in (*data.row_buckets, *data.col_buckets)})
            ),
            segment=False,
        )
    state = init_sharded_factors(data, params, mesh, axis)
    row_dbs = upload_buckets(data.row_buckets, mesh, axis, state.U.shape[0] - 1)
    col_dbs = upload_buckets(data.col_buckets, mesh, axis, state.V.shape[0] - 1)
    for _ in range(params.iterations):
        state.U = sharded_half_step(state, state.U, state.V, row_dbs, params)
        state.V = sharded_half_step(state, state.V, state.U, col_dbs, params)
    return state.U[: data.num_rows], state.V[: data.num_cols]
