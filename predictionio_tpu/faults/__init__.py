"""Deterministic, seeded fault injection (see inject.py).

``fault_point(name)`` is a no-op unless a :class:`FaultPlan` is active
(via the ``PIO_FAULTS`` env var or the test API) — the hot paths pay one
module-global None check.
"""

from predictionio_tpu.faults.inject import (  # noqa: F401
    KNOWN_POINTS,
    FaultError,
    FaultPlan,
    FaultRule,
    active_plan,
    clear,
    fault_point,
    injected,
    install,
    parse_plan,
    parse_rule,
    plan_from_env,
)
