"""Deterministic, seeded fault injection for crash/chaos testing.

The framework is a registry of NAMED fault points threaded through the
storage backends, the HTTP servers, the speed layer, and the training
loop (:data:`KNOWN_POINTS` is the catalogue; docs/robustness.md the
operator view). Each call site does::

    from predictionio_tpu import faults
    ...
    faults.fault_point("storage.fsync")

which is a no-op (one module-global ``is None`` check) unless a
:class:`FaultPlan` is active. A plan is a list of :class:`FaultRule`\\ s;
each rule names a point (exact, or a ``prefix.*`` wildcard), a trigger,
and an action:

- triggers: ``nth=N`` (the Nth matching call, 1-based), ``p=0.25``
  (seeded Bernoulli per call — deterministic for a given ``seed``), or
  always; ``times=K`` bounds total firings.
- actions: ``raise[=ExcName[,msg]]`` (default: :class:`FaultError`, an
  OSError subclass so the injected failure flows through the same
  error-handling the real fault would), ``sleep=ms`` (latency
  injection), ``kill`` (SIGKILL the process at the point — the
  in-protocol stand-in for kill-9/power loss, used by the crash-recovery
  and chaos tests).

Activation: the ``PIO_FAULTS`` env var (parsed once at import —
subprocess chaos children inherit it), or in-process via
:func:`install` / :func:`injected`. Env grammar, semicolon-separated::

    point[:trigger[,trigger...]][:action]

e.g. ``PIO_FAULTS="storage.fsync:nth=3:raise=OSError"`` or
``"storage.write:p=0.01,seed=7,times=2:sleep=50;http.read:nth=5:kill"``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import random
import signal
import threading
import time

from predictionio_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)


class FaultError(OSError):
    """Default injected exception. An OSError subclass on purpose: the
    I/O-shaped fault points (write/fsync/rename/socket) are guarded by
    OSError handling in production code, and the injection must prove
    THOSE paths out, not invent a novel error type they'd never see."""


# The catalogue of fault points threaded through the codebase. Injection
# works at any name (call sites are authoritative), but docs and tests
# key off this registry.
KNOWN_POINTS: dict[str, str] = {
    "storage.write": "event-log append write+flush (jsonl/partitioned)",
    "storage.fsync": "durability fsyncs: group-commit coalescer, compact, "
                     "partitioned seal",
    "storage.rename": "atomic tmp->final publishes: compact, partitioned "
                      "seal, tailer cursor, checkpoint",
    "storage.sqlite.commit": "sqlite event-insert transaction commit",
    "colcache.store": "columnar-cache block write+fsync+rename publish",
    "http.accept": "server socket accept (all HTTP servers)",
    "http.read": "request read/parse on an accepted connection",
    "http.frame": "binary ingest frame read off the request body "
                  "(/batch/events.bin, data/storage/frame.py)",
    "serve.query": "engine-server per-query scoring entry",
    "serve.batch_dispatch": "micro-batcher batch_predict device dispatch",
    "device.dispatch": "fused ALS training-program dispatch "
                       "(single-chip and sharded)",
    "train.checkpoint": "ALS checkpoint snapshot write",
    "foldin.fold": "speed-layer incremental fold-in solve",
    "tail.decode": "columnar tail span->array decode of one polled "
                   "chunk (realtime/tailer.py; a raise falls the chunk "
                   "back to the object parser, counted in "
                   "pio_tailer_columnar_fallback_lines_total)",
    "http.drain": "graceful-drain entry on an HTTP server "
                  "(HTTPApp.begin_drain)",
    "supervisor.spawn": "fleet-supervisor child (re)spawn "
                        "(server/supervisor.py)",
    "serve.model_mmap": "model-file mmap attempt at deploy/reload "
                        "(models/modelfile.py; a raise falls the load "
                        "back to a plain byte read, counted in "
                        "pio_model_mmap_fallback_total)",
    "router.forward": "router-tier forward of one query attempt to one "
                      "replica (server/router.py; a raise ejects the "
                      "replica and retries on another, counted in "
                      "pio_router_retries_total)",
    "router.probe": "router-tier /readyz probe of one replica "
                    "(server/router.py; a raise ejects the replica "
                    "until a later probe round re-admits it)",
    "train.prep_cache": "packed-prep cache publish (core/prep_cache.py "
                        "store; a raise skips the publish — training is "
                        "unaffected and the next train falls back to a "
                        "clean rebuild)",
}

_EXCEPTIONS: dict[str, type[BaseException]] = {
    "FaultError": FaultError,
    "OSError": OSError,
    "IOError": OSError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
    "TimeoutError": TimeoutError,
    "ConnectionError": ConnectionError,
    "ConnectionResetError": ConnectionResetError,
}


@dataclasses.dataclass
class FaultRule:
    """One point's trigger + action. Mutable call/fire counters live on
    the rule; the owning plan's lock serializes them."""

    point: str
    nth: int | None = None          # fire on the Nth matching call (1-based)
    probability: float | None = None
    seed: int = 0
    times: int | None = None        # max total firings (None = unlimited)
    action: str = "raise"           # "raise" | "sleep" | "kill"
    exc: type[BaseException] = FaultError
    message: str = ""
    sleep_ms: float = 0.0

    def __post_init__(self) -> None:
        if self.action not in ("raise", "sleep", "kill"):
            raise ValueError(f"unknown fault action {self.action!r}")
        self._rng = random.Random(self.seed)
        self.calls = 0
        self.fired = 0

    def matches(self, point: str) -> bool:
        if self.point.endswith(".*"):
            return point.startswith(self.point[:-1]) or point == self.point[:-2]
        return point == self.point

    def should_fire(self) -> bool:
        """Advance this rule's call counter; True when the trigger trips
        (caller holds the plan lock)."""
        self.calls += 1
        if self.times is not None and self.fired >= self.times:
            return False
        if self.nth is not None and self.calls != self.nth:
            return False
        if self.probability is not None and self._rng.random() >= self.probability:
            return False
        self.fired += 1
        return True


class FaultPlan:
    """An ordered rule list; the first matching rule that trips wins."""

    def __init__(self, rules: list[FaultRule] | tuple[FaultRule, ...]):
        self.rules = list(rules)
        self._lock = threading.Lock()
        self.fired: dict[str, int] = {}

    def on_call(self, point: str) -> FaultRule | None:
        with self._lock:
            for rule in self.rules:
                if rule.matches(point) and rule.should_fire():
                    self.fired[point] = self.fired.get(point, 0) + 1
                    return rule
        return None

    def fire_count(self, point: str | None = None) -> int:
        with self._lock:
            if point is not None:
                return self.fired.get(point, 0)
            return sum(self.fired.values())


def parse_rule(spec: str) -> FaultRule:
    """``point[:trigger[,trigger...]][:action]`` -> FaultRule."""
    parts = [p.strip() for p in spec.strip().split(":")]
    if not parts or not parts[0]:
        raise ValueError(f"fault rule needs a point name: {spec!r}")
    kwargs: dict = {"point": parts[0]}
    action_part = None
    for part in parts[1:]:
        if not part or part == "always":
            continue
        head = part.split("=", 1)[0].split(",", 1)[0]
        if head in ("raise", "sleep", "kill"):
            action_part = part
            continue
        for term in part.split(","):
            k, _, v = term.partition("=")
            k = k.strip()
            if k == "nth":
                kwargs["nth"] = int(v)
            elif k == "p":
                kwargs["probability"] = float(v)
            elif k == "seed":
                kwargs["seed"] = int(v)
            elif k == "times":
                kwargs["times"] = int(v)
            else:
                raise ValueError(f"unknown fault trigger {term!r} in {spec!r}")
    if action_part is not None:
        name, _, arg = action_part.partition("=")
        kwargs["action"] = name
        if name == "sleep":
            kwargs["sleep_ms"] = float(arg)
        elif name == "raise" and arg:
            exc_name, _, msg = arg.partition(",")
            try:
                kwargs["exc"] = _EXCEPTIONS[exc_name]
            except KeyError:
                raise ValueError(
                    f"unknown fault exception {exc_name!r}; one of "
                    f"{sorted(_EXCEPTIONS)}"
                ) from None
            kwargs["message"] = msg
    return FaultRule(**kwargs)


def parse_plan(spec: str) -> FaultPlan:
    rules = [parse_rule(s) for s in spec.split(";") if s.strip()]
    return FaultPlan(rules)


def plan_from_env() -> FaultPlan | None:
    spec = os.environ.get("PIO_FAULTS", "").strip()
    if not spec:
        return None
    plan = parse_plan(spec)
    logger.warning(
        "PIO_FAULTS active: %d fault rule(s) — %s",
        len(plan.rules), [r.point for r in plan.rules],
    )
    return plan


_active: FaultPlan | None = plan_from_env()


def active_plan() -> FaultPlan | None:
    return _active


def install(plan: FaultPlan) -> FaultPlan:
    """Activate a plan process-wide (test API). Returns it."""
    global _active
    _active = plan
    return plan


def clear() -> None:
    global _active
    _active = None


@contextlib.contextmanager
def injected(*rules: FaultRule | str):
    """Context-managed plan: ``with faults.injected("storage.fsync:nth=2")``.
    Accepts rule specs or FaultRule instances."""
    global _active
    plan = FaultPlan(
        [r if isinstance(r, FaultRule) else parse_rule(r) for r in rules]
    )
    prev = _active
    install(plan)
    try:
        yield plan
    finally:
        _active = prev


def fault_point(name: str) -> None:
    """The injection hook. Compiled down to one global load + None check
    when no plan is active — the <1% disabled-overhead gate in
    ``bench.py robustness`` measures exactly this path."""
    plan = _active
    if plan is None:
        return
    rule = plan.on_call(name)
    if rule is None:
        return
    obs_metrics.counter(
        "pio_faults_injected_total", "Faults fired by the active FaultPlan",
        point=name, action=rule.action,
    ).inc()
    if rule.action == "sleep":
        logger.warning("fault %s: injected %gms latency", name, rule.sleep_ms)
        time.sleep(rule.sleep_ms / 1e3)
        return
    if rule.action == "kill":
        logger.warning("fault %s: SIGKILL (injected crash)", name)
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(60)  # pragma: no cover - never survives the signal
        return
    logger.warning("fault %s: raising %s", name, rule.exc.__name__)
    raise rule.exc(rule.message or f"injected fault at {name}")
