"""Circuit breaker: trip on repeated failures, half-open with backoff.

Used by the speed layer around fold-in (realtime/speed_layer.py): after
``failure_threshold`` consecutive failures the breaker OPENS and the
caller stops attempting the guarded operation — the engine keeps serving
the last good epoch-fenced model instead of burning a failing path on
every poll tick. After an exponential-backoff-with-jitter delay the
breaker HALF-OPENS: exactly one trial call is allowed through; success
closes the breaker, failure re-opens it with a doubled backoff (capped).

State and transitions are exported through obs (``pio_breaker_state``,
``pio_breaker_transitions_total``, ``pio_breaker_failures_total``) so
``/metrics`` and ``pio status --json`` show a tripped breaker directly.

Deterministic for tests: the jitter RNG is seeded and the clock is
injectable.
"""

from __future__ import annotations

import logging
import random
import threading
import time

from predictionio_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


def backoff_interval(
    attempt: int,
    *,
    base_s: float,
    max_s: float,
    jitter: float,
    rng: random.Random,
) -> float:
    """The shared retry-backoff policy: ``base * 2^(attempt-1)`` capped
    at ``max_s``, then jittered by ``±jitter``. ``attempt`` is 1-based
    (attempt 1 waits ~``base_s``). Used by the breaker's open interval
    and the fleet supervisor's crash-restart schedule — one policy, one
    set of semantics to reason about."""
    raw = base_s * (2 ** max(0, attempt - 1))
    raw = min(raw, max_s)
    return raw * (1.0 + jitter * rng.uniform(-1.0, 1.0))


class CircuitBreaker:
    def __init__(
        self,
        name: str,
        failure_threshold: int = 3,
        base_backoff_s: float = 1.0,
        max_backoff_s: float = 60.0,
        jitter: float = 0.2,
        seed: int = 0,
        clock=time.monotonic,
    ) -> None:
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.base_backoff_s = float(base_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opens = 0  # consecutive opens since last success (backoff exp)
        self._retry_at = 0.0
        self.failures_total = 0
        self.trips_total = 0
        self._gauge().set(0)

    def _gauge(self):
        return obs_metrics.gauge(
            "pio_breaker_state",
            "Circuit breaker state (0=closed, 1=open, 2=half_open)",
            breaker=self.name,
        )

    def _transition(self, to: str) -> None:
        """Caller holds the lock."""
        if to == self._state:
            return
        logger.warning("breaker %s: %s -> %s", self.name, self._state, to)
        self._state = to
        self._gauge().set(_STATE_CODE[to])
        obs_metrics.counter(
            "pio_breaker_transitions_total",
            "Circuit breaker state transitions",
            breaker=self.name, to=to,
        ).inc()

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def backoff_s(self) -> float:
        """Current open-interval length: base * 2^(opens-1), jittered."""
        return backoff_interval(
            self._opens,
            base_s=self.base_backoff_s,
            max_s=self.max_backoff_s,
            jitter=self.jitter,
            rng=self._rng,
        )

    def allow(self) -> bool:
        """May the guarded operation run now? OPEN: no until the backoff
        deadline passes, then the breaker half-opens. HALF_OPEN admits
        trials until a verdict is recorded — with a single-threaded
        caller (the speed-layer loop) that is exactly one in-flight
        trial; the first ``record_failure`` re-opens, ``record_success``
        closes."""
        with self._lock:
            if self._state in (CLOSED, HALF_OPEN):
                return True
            if self._clock() >= self._retry_at:
                self._transition(HALF_OPEN)
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._opens = 0
            self._transition(CLOSED)

    def record_failure(self) -> None:
        self.failures_total += 1
        obs_metrics.counter(
            "pio_breaker_failures_total",
            "Failures observed by the circuit breaker",
            breaker=self.name,
        ).inc()
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN or (
                self._state == CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._open()

    def _open(self) -> None:
        """Caller holds the lock."""
        self._opens += 1
        self.trips_total += 1
        self._retry_at = self._clock() + self.backoff_s()
        self._transition(OPEN)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "failures_total": self.failures_total,
                "trips_total": self.trips_total,
                "retry_in_s": max(0.0, self._retry_at - self._clock())
                if self._state == OPEN
                else 0.0,
            }
