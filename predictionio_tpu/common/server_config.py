"""server.conf parsing, key authentication, and SSL context construction.

Capability parity with the reference's shared HTTP-server config:

- **Key auth** — the dashboard and the engine server's ``/stop`` /
  ``/reload`` endpoints are guarded by a server-wide access key passed as
  the ``accessKey`` query param, enforced only when
  ``key-auth-enforced`` is true (KeyAuthentication.scala:34-61).
- **SSL** — servers can terminate TLS themselves
  (SSLConfiguration.scala:32-74). The reference loads a JKS keystore;
  the Python-native equivalent is a PEM cert/key pair loaded into an
  ``ssl.SSLContext`` (``ssl-certfile`` / ``ssl-keyfile`` replace
  ``ssl-keystore-resource`` / ``ssl-key-alias``).

The config file mirrors ``conf/server.conf``: a
``org.apache.predictionio.server`` block of ``key = "value"`` entries.
Both the reference's HOCON block style and flat
``org.apache.predictionio.server.key=value`` lines parse.
"""

from __future__ import annotations

import re
import ssl
from dataclasses import dataclass, field

CONFIG_PREFIX = "org.apache.predictionio.server"


def _parse_conf(text: str) -> dict[str, str]:
    """Parse the HOCON-subset server.conf into flat dotted keys."""
    out: dict[str, str] = {}
    prefix_stack: list[str] = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#") or line.startswith("//"):
            continue
        m = re.match(r"^([\w.\-]+)\s*\{$", line)
        if m:
            prefix_stack.append(m.group(1))
            continue
        if line == "}":
            if prefix_stack:
                prefix_stack.pop()
            continue
        m = re.match(r"^([\w.\-]+)\s*=\s*(.*)$", line)
        if m:
            key = ".".join(prefix_stack + [m.group(1)])
            value = m.group(2).strip().strip('"')
            out[key] = value
    return out


def _get_bool(conf: dict[str, str], key: str, default: bool = False) -> bool:
    return conf.get(key, str(default)).strip().lower() in ("true", "1", "yes")


@dataclass
class ServerConfig:
    """Server-wide auth + SSL settings shared by all HTTP servers."""

    key_auth_enforced: bool = False
    access_key: str = ""
    ssl_enforced: bool = False
    ssl_certfile: str | None = None
    ssl_keyfile: str | None = None
    ssl_keyfile_password: str | None = None
    extras: dict[str, str] = field(default_factory=dict)

    def ssl_context(self) -> ssl.SSLContext | None:
        """Server-side TLS context from the PEM pair (the JKS-keystore
        analog, SSLConfiguration.scala:41-62). None when SSL is off."""
        if not self.ssl_enforced:
            return None
        if not self.ssl_certfile or not self.ssl_keyfile:
            raise ValueError(
                "ssl-enforced is true but ssl-certfile/ssl-keyfile are not set"
            )
        context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        context.minimum_version = ssl.TLSVersion.TLSv1_2
        context.load_cert_chain(
            certfile=self.ssl_certfile,
            keyfile=self.ssl_keyfile,
            password=self.ssl_keyfile_password,
        )
        return context


def load_server_config(path: str | None = None, text: str | None = None) -> ServerConfig:
    """Load a server.conf; missing file/keys fall back to defaults
    (auth and SSL both off — the reference template's defaults)."""
    if text is None:
        if path is None:
            return ServerConfig()
        try:
            with open(path) as f:
                text = f.read()
        except FileNotFoundError:
            return ServerConfig()
    conf = _parse_conf(text)
    p = CONFIG_PREFIX
    known = {
        f"{p}.key-auth-enforced",
        f"{p}.accessKey",
        f"{p}.ssl-enforced",
        f"{p}.ssl-certfile",
        f"{p}.ssl-keyfile",
        f"{p}.ssl-keyfile-pass",
    }
    return ServerConfig(
        key_auth_enforced=_get_bool(conf, f"{p}.key-auth-enforced"),
        access_key=conf.get(f"{p}.accessKey", ""),
        ssl_enforced=_get_bool(conf, f"{p}.ssl-enforced"),
        ssl_certfile=conf.get(f"{p}.ssl-certfile"),
        ssl_keyfile=conf.get(f"{p}.ssl-keyfile"),
        ssl_keyfile_password=conf.get(f"{p}.ssl-keyfile-pass"),
        extras={k: v for k, v in conf.items() if k not in known},
    )


class KeyAuthentication:
    """Query-param server-key check (KeyAuthentication.scala:44-61):
    authorized when auth is not enforced or ``accessKey`` matches."""

    def __init__(self, config: ServerConfig):
        self.config = config

    def authorized(self, query: dict[str, str]) -> bool:
        if not self.config.key_auth_enforced:
            return True
        return query.get("accessKey") == self.config.access_key
