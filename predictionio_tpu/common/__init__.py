"""Shared HTTP-server configuration: server-key auth and SSL.

Capability parity with the reference ``common/`` module
(common/.../configuration/SSLConfiguration.scala:32-74,
common/.../authentication/KeyAuthentication.scala:34-61).
"""

from predictionio_tpu.common.server_config import (
    KeyAuthentication,
    ServerConfig,
    load_server_config,
)

__all__ = ["KeyAuthentication", "ServerConfig", "load_server_config"]
