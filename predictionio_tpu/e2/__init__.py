"""e2: reusable engine-building helpers.

Capability parity with the reference ``e2/`` module
(e2/src/main/scala/org/apache/predictionio/e2/): CategoricalNaiveBayes,
MarkovChain, BinaryVectorizer, and the k-fold cross-validation splitter.
"""
