"""Markov chain over a sparse transition-count matrix.

Capability parity with the reference MarkovChain
(e2/.../engine/MarkovChain.scala:26-88): train keeps the top-N outgoing
transitions per state, row-normalized; predict multiplies a state
distribution by the transition matrix.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np


@dataclass
class MarkovChainModel:
    n_states: int
    top_n: int
    # per state: (next_state_ids, probabilities), both [<=top_n]
    transitions: list[tuple[np.ndarray, np.ndarray]]

    def predict(self, current: Sequence[float]) -> np.ndarray:
        """Next-state distribution: current(row vector) . P."""
        current = np.asarray(current, dtype=np.float64)
        if current.shape != (self.n_states,):
            raise ValueError(f"expected state vector of length {self.n_states}")
        out = np.zeros(self.n_states)
        for state, weight in enumerate(current):
            if weight == 0.0:
                continue
            ids, probs = self.transitions[state]
            out[ids] += weight * probs
        return out

    def transition_prob(self, i: int, j: int) -> float:
        ids, probs = self.transitions[i]
        hits = probs[ids == j]
        return float(hits[0]) if len(hits) else 0.0


def train(
    counts: Sequence[tuple[int, int, float]], n_states: int, top_n: int
) -> MarkovChainModel:
    """counts: (from_state, to_state, count) coordinate entries."""
    by_row: dict[int, dict[int, float]] = {}
    for i, j, c in counts:
        by_row.setdefault(int(i), {})[int(j)] = by_row.get(int(i), {}).get(int(j), 0.0) + float(c)
    transitions: list[tuple[np.ndarray, np.ndarray]] = []
    for state in range(n_states):
        row = by_row.get(state, {})
        if not row:
            transitions.append((np.array([], np.int64), np.array([], np.float64)))
            continue
        items = sorted(row.items(), key=lambda kv: -kv[1])[:top_n]
        ids = np.array([j for j, _ in items], dtype=np.int64)
        vals = np.array([c for _, c in items], dtype=np.float64)
        transitions.append((ids, vals / vals.sum()))
    return MarkovChainModel(n_states=n_states, top_n=top_n, transitions=transitions)
