"""Categorical Naive Bayes over string-valued features.

Capability parity with the reference CategoricalNaiveBayes
(e2/.../engine/CategoricalNaiveBayes.scala:24-173): labeled points whose
features are categorical strings per position; the model exposes
``predict`` (most likely label) and ``log_score`` with an optional
default for feature values unseen at training time.
"""

from __future__ import annotations

import math
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Callable, Iterable, Sequence


@dataclass(frozen=True)
class LabeledPoint:
    label: str
    features: tuple[str, ...]

    def __post_init__(self):
        object.__setattr__(self, "features", tuple(self.features))


@dataclass
class CategoricalNaiveBayesModel:
    priors: dict[str, float]  # label -> log prior
    likelihoods: dict[str, list[dict[str, float]]]  # label -> per-pos log P(v|l)

    def log_score(
        self,
        point: LabeledPoint,
        default_likelihood: Callable[[Iterable[float]], float] | None = None,
    ) -> float | None:
        """Log joint score of point under its label; None when the label is
        unknown or a feature value is unseen and no default is given
        (reference logScore, CategoricalNaiveBayes.scala:88-130)."""
        if point.label not in self.priors:
            return None
        like = self.likelihoods[point.label]
        if len(point.features) != len(like):
            raise ValueError(
                f"point has {len(point.features)} features; model expects {len(like)}"
            )
        total = self.priors[point.label]
        for pos, value in enumerate(point.features):
            if value in like[pos]:
                total += like[pos][value]
            elif default_likelihood is not None:
                total += default_likelihood(like[pos].values())
            else:
                return None
        return total

    def predict(self, features: Sequence[str]) -> str:
        """Most likely label; unseen feature values score -inf for that
        label (reference predict:140-149 with the default NegativeInfinity
        likelihood)."""
        best_label, best_score = None, -math.inf
        for label in sorted(self.priors):
            like = self.likelihoods[label]
            if len(features) != len(like):
                raise ValueError(
                    f"point has {len(features)} features; model expects {len(like)}"
                )
            score = self.priors[label]
            for pos, value in enumerate(features):
                score += like[pos].get(value, -math.inf)
            if best_label is None or score > best_score:
                best_label, best_score = label, score
        if best_label is None:
            raise ValueError("model has no labels")
        return best_label


def train(points: Iterable[LabeledPoint]) -> CategoricalNaiveBayesModel:
    """Count-based fit (reference object CategoricalNaiveBayes.train:24-86,
    combineByKey label/feature counting -> log-likelihoods)."""
    points = list(points)
    if not points:
        raise ValueError("cannot train on zero points")
    n_features = len(points[0].features)
    label_counts: dict[str, int] = defaultdict(int)
    # label -> position -> value -> count
    value_counts: dict[str, list[dict[str, int]]] = {}
    for p in points:
        if len(p.features) != n_features:
            raise ValueError("inconsistent feature arity")
        label_counts[p.label] += 1
        per_pos = value_counts.setdefault(
            p.label, [defaultdict(int) for _ in range(n_features)]
        )
        for pos, v in enumerate(p.features):
            per_pos[pos][v] += 1

    total = len(points)
    priors = {
        label: math.log(count / total) for label, count in label_counts.items()
    }
    likelihoods: dict[str, list[dict[str, float]]] = {}
    for label, per_pos in value_counts.items():
        denom = label_counts[label]
        likelihoods[label] = [
            {v: math.log(c / denom) for v, c in pos_map.items()}
            for pos_map in per_pos
        ]
    return CategoricalNaiveBayesModel(priors=priors, likelihoods=likelihoods)
