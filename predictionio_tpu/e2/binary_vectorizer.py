"""One-hot encoder over (property, value) pairs.

Capability parity with the reference BinaryVectorizer
(e2/.../engine/BinaryVectorizer.scala:47-90): fit collects the distinct
(property, value) pairs of the selected properties into a stable index;
transform produces a dense 0/1 vector.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Mapping, Sequence

import numpy as np


@dataclass
class BinaryVectorizer:
    index: dict[tuple[str, str], int]

    @property
    def num_features(self) -> int:
        return len(self.index)

    @staticmethod
    def fit(
        maps: Iterable[Mapping[str, str]], properties: Sequence[str]
    ) -> "BinaryVectorizer":
        pairs: dict[tuple[str, str], int] = {}
        wanted = set(properties)
        for m in maps:
            for k, v in m.items():
                if k in wanted and (k, str(v)) not in pairs:
                    pairs[(k, str(v))] = len(pairs)
        return BinaryVectorizer(index=pairs)

    def to_vector(self, m: Mapping[str, str]) -> np.ndarray:
        vec = np.zeros(len(self.index), dtype=np.float32)
        for k, v in m.items():
            ix = self.index.get((k, str(v)))
            if ix is not None:
                vec[ix] = 1.0
        return vec
