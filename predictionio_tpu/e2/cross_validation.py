"""k-fold cross-validation splitter.

Capability parity with the reference CommonHelperFunctions.splitData
(e2/.../evaluation/CrossValidation.scala:36-66): element i belongs to
eval fold ``i % k``; each fold yields (training subset, fold info,
eval subset).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, TypeVar

T = TypeVar("T")


def split_data(
    k: int,
    data: Sequence[T],
    make_training: Callable[[list[T]], Any] = list,
    make_qa: Callable[[T], Any] = lambda x: x,
) -> list[tuple[Any, dict, list[Any]]]:
    """Returns k folds of (training_data, {"fold": i}, eval_points)."""
    if k < 2:
        raise ValueError("k must be >= 2")
    folds = []
    for fold in range(k):
        train = [x for i, x in enumerate(data) if i % k != fold]
        evals = [make_qa(x) for i, x in enumerate(data) if i % k == fold]
        folds.append((make_training(train), {"fold": fold}, evals))
    return folds
