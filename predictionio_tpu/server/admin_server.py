"""Admin server: REST management API (default port 7071).

Capability parity with the reference admin server
(tools/.../admin/AdminAPI.scala:39-160, admin/CommandClient.scala):
``GET /`` status, ``GET /cmd/app`` list, ``POST /cmd/app`` create,
``DELETE /cmd/app/<name>`` delete, ``DELETE /cmd/app/<name>/data``
wipe event data.
"""

from __future__ import annotations

import logging

from predictionio_tpu.cli import commands
from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.server.http import (
    HTTPApp,
    Request,
    Response,
    Router,
    add_obs_routes,
)

logger = logging.getLogger(__name__)


class AdminServer:
    def __init__(self, storage: Storage | None = None, host: str = "0.0.0.0", port: int = 7071):
        self.storage = storage or get_storage()
        self.app = HTTPApp(
            self._router(), host=host, port=port, name="adminserver"
        )
        self.host = host

    def _router(self) -> Router:
        router = Router()
        server = self

        @router.route("GET", "/")
        def status(request: Request) -> Response:
            return Response.json({"status": "alive"})

        @router.route("GET", "/cmd/app")
        def list_apps(request: Request) -> Response:
            apps = commands.app_list(storage=server.storage)
            return Response.json(
                {
                    "status": 1,
                    "apps": [
                        {
                            "name": a["name"],
                            "id": a["id"],
                            "accessKey": a["access_key"],
                        }
                        for a in apps
                    ],
                }
            )

        @router.route("POST", "/cmd/app")
        def new_app(request: Request) -> Response:
            body = request.json() or {}
            name = body.get("name")
            if not name:
                return Response.error("app name is required", 400)
            try:
                info = commands.app_new(
                    name,
                    app_id=int(body.get("id") or 0),
                    description=body.get("description"),
                    storage=server.storage,
                )
            except commands.CommandError as e:
                return Response.json({"status": 0, "message": str(e)}, status=400)
            return Response.json(
                {
                    "status": 1,
                    "id": info["id"],
                    "name": info["name"],
                    "accessKey": info["access_key"],
                }
            )

        @router.route("DELETE", "/cmd/app/<name>")
        def delete_app(request: Request) -> Response:
            try:
                commands.app_delete(
                    request.path_params["name"], storage=server.storage
                )
            except commands.CommandError as e:
                return Response.json({"status": 0, "message": str(e)}, status=404)
            return Response.json({"status": 1})

        @router.route("DELETE", "/cmd/app/<name>/data")
        def delete_app_data(request: Request) -> Response:
            try:
                commands.app_data_delete(
                    request.path_params["name"], storage=server.storage
                )
            except commands.CommandError as e:
                return Response.json({"status": 0, "message": str(e)}, status=404)
            return Response.json({"status": 1})

        add_obs_routes(router)
        return router

    def start(self, background: bool = True) -> int:
        port = self.app.start(background=background)
        logger.info("Admin Server listening on %s:%d", self.host, port)
        return port

    def stop(self) -> None:
        self.app.stop()
