"""Self-healing fleet supervisor: crash-restart with seeded backoff.

``pio start-all --supervise`` (and ``pio supervise``) runs this parent
instead of the fire-and-forget detached bring-up: it spawns the fleet,
keeps the ``Popen`` handles (so crashes are reaped with a real exit
status instead of lingering as zombies), and monitors each child two
ways — pid liveness via ``poll()`` and HTTP ``/healthz`` probes whose
per-boot instance id + pid prove WHICH process answered.

A dead or persistently-unhealthy child is restarted on the shared
exponential-backoff-with-jitter policy (``common/breaker.py``'s
``backoff_interval`` — seeded per service, so restart timing is
deterministic under test). A service that crashes ``flap_max`` times
within ``flap_window_s`` is declared ``broken``: the supervisor stops
respawning it and fires a flight-recorder incident bundle
(``obs/incident.py``) for the operator.

State is exported three ways:

- ``pio_supervisor_restarts_total`` / ``pio_supervisor_state`` metrics
  in this process's obs registry (scrapeable when ``--supervise-port``
  mounts the obs routes);
- an atomically-written ``supervisor.json`` under the run dir, which
  ``pio status`` (plain and ``--json``) renders per service;
- the structured ``services()`` snapshot for in-process callers.

Clock, sleep, spawn, and probe are injectable — the crash/backoff/flap
state machine is unit-testable without processes.
"""

from __future__ import annotations

import collections
import logging
import os
import random
import signal
import subprocess
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable

from predictionio_tpu import faults
from predictionio_tpu.cli import daemon
from predictionio_tpu.common.breaker import backoff_interval
from predictionio_tpu.obs import incident as obs_incident
from predictionio_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)

# child states
STARTING = "starting"      # spawned, waiting for first healthy probe
UP = "up"                  # healthy
RESTARTING = "restarting"  # crashed; waiting out the backoff interval
BROKEN = "broken"          # flapped past the budget; operator required
STOPPED = "stopped"        # shut down by the supervisor

_STATE_CODE = {UP: 0, STARTING: 1, RESTARTING: 2, BROKEN: 3, STOPPED: 4}


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _describe_exit(rc: int | None) -> str:
    if rc is None:
        return "unknown"
    if rc < 0:
        try:
            return f"signal {-rc} ({signal.Signals(-rc).name})"
        except ValueError:
            return f"signal {-rc}"
    return f"exit code {rc}"


@dataclass
class ServiceSpec:
    """One supervised service: how to spawn it and where to probe it.

    ``spawn`` (tests) overrides the default ``pio``-verb spawn; it must
    return a Popen-like handle (``pid``, ``poll()``, ``terminate()``,
    ``kill()``, ``wait()``).
    """

    name: str
    argv: list[str] = field(default_factory=list)
    host: str = "127.0.0.1"
    port: int = 0
    spawn: Callable[[], Any] | None = None
    boot_timeout_s: float = 90.0


class _Child:
    """Mutable per-service supervision state."""

    def __init__(self, spec: ServiceSpec, seed: int):
        self.spec = spec
        self.state = STOPPED
        self.proc: Any | None = None
        self.pid: int | None = None
        self.instance: str | None = None
        self.restarts = 0                # respawns after the first start
        self.attempt = 0                 # consecutive failures -> backoff exp
        self.last_exit: str | None = None
        self.next_retry_at: float | None = None
        self.last_backoff_s: float | None = None
        self.boot_deadline = 0.0
        self.stable_at = 0.0
        self.health_fails = 0
        self.crash_times: collections.deque[float] = collections.deque()
        # per-service seeded jitter stream: restart timing is a pure
        # function of (seed, service name, crash sequence)
        self.rng = random.Random(seed ^ zlib.crc32(spec.name.encode()))


class Supervisor:
    """Spawns, probes, restarts, and reports on a service fleet."""

    def __init__(
        self,
        specs: list[ServiceSpec],
        *,
        poll_interval: float | None = None,
        base_backoff_s: float | None = None,
        max_backoff_s: float | None = None,
        jitter: float = 0.2,
        flap_max: int | None = None,
        flap_window_s: float | None = None,
        stable_s: float | None = None,
        health_fail_threshold: int | None = None,
        seed: int | None = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        probe: Callable[[ServiceSpec], dict | None] | None = None,
        retrain: "RetrainScheduler | None" = None,
    ):
        self.poll_interval = (
            poll_interval
            if poll_interval is not None
            else _env_float("PIO_SUPERVISE_POLL_S", 0.5)
        )
        self.base_backoff_s = (
            base_backoff_s
            if base_backoff_s is not None
            else _env_float("PIO_SUPERVISE_BACKOFF_S", 0.5)
        )
        self.max_backoff_s = (
            max_backoff_s
            if max_backoff_s is not None
            else _env_float("PIO_SUPERVISE_MAX_BACKOFF_S", 30.0)
        )
        self.jitter = float(jitter)
        self.flap_max = int(
            flap_max
            if flap_max is not None
            else _env_float("PIO_SUPERVISE_FLAP_N", 5)
        )
        self.flap_window_s = (
            flap_window_s
            if flap_window_s is not None
            else _env_float("PIO_SUPERVISE_FLAP_WINDOW_S", 60.0)
        )
        self.stable_s = (
            stable_s
            if stable_s is not None
            else _env_float("PIO_SUPERVISE_STABLE_S", 30.0)
        )
        self.health_fail_threshold = int(
            health_fail_threshold
            if health_fail_threshold is not None
            else _env_float("PIO_SUPERVISE_HEALTH_FAILS", 3)
        )
        if seed is None:
            seed = int(_env_float("PIO_SUPERVISE_SEED", 0))
        self.seed = seed
        self._clock = clock
        self._sleep = sleep
        self._probe_fn = probe
        self._children = [_Child(spec, seed) for spec in specs]
        self.retrain = retrain
        self._stop_event = threading.Event()
        self._dirty = True
        self._lock = threading.RLock()

    # -- metrics -----------------------------------------------------------

    @staticmethod
    def _m_restarts(name: str):
        return obs_metrics.counter(
            "pio_supervisor_restarts_total",
            "Child restarts performed by the fleet supervisor",
            service=name,
        )

    @staticmethod
    def _g_state(name: str):
        return obs_metrics.gauge(
            "pio_supervisor_state",
            "Supervised service state "
            "(0=up, 1=starting, 2=restarting, 3=broken, 4=stopped)",
            service=name,
        )

    def _set_state(self, child: _Child, state: str) -> None:
        if state != child.state:
            logger.info(
                "supervisor: %s %s -> %s", child.spec.name, child.state, state
            )
        child.state = state
        self._g_state(child.spec.name).set(float(_STATE_CODE[state]))
        self._dirty = True

    # -- spawn / probe -----------------------------------------------------

    def _spawn(self, child: _Child) -> Any:
        faults.fault_point("supervisor.spawn")
        if child.spec.spawn is not None:
            return child.spec.spawn()
        return daemon.spawn_service(child.spec.name, child.spec.argv)

    def _probe(self, child: _Child) -> dict | None:
        if self._probe_fn is not None:
            return self._probe_fn(child.spec)
        if not child.spec.port:
            return None
        return daemon.probe_health(
            child.spec.host, child.spec.port, timeout=1.0
        )

    def _launch(self, child: _Child, now: float) -> None:
        try:
            proc = self._spawn(child)
        except Exception as exc:
            child.last_exit = f"spawn failed: {exc}"
            logger.warning(
                "supervisor: spawn of %s failed: %s", child.spec.name, exc
            )
            self._on_down(child, now)
            return
        child.proc = proc
        child.pid = getattr(proc, "pid", None)
        child.instance = None
        child.health_fails = 0
        child.boot_deadline = now + child.spec.boot_timeout_s
        if child.spec.spawn is None and child.pid is not None:
            daemon._pid_file(child.spec.name).write_text(str(child.pid))
            daemon.write_service_record(
                child.spec.name, child.spec.argv,
                child.spec.host, child.spec.port,
            )
        self._set_state(child, STARTING)

    def _reap(self, child: _Child) -> None:
        proc = child.proc
        child.proc = None
        if proc is not None:
            try:
                proc.wait(timeout=0)
            except Exception:
                pass

    def _on_down(self, child: _Child, now: float) -> None:
        """A child died (or its spawn failed): schedule the restart, or
        declare it broken when it is flapping."""
        self._reap(child)
        child.instance = None
        child.crash_times.append(now)
        while child.crash_times and (
            now - child.crash_times[0] > self.flap_window_s
        ):
            child.crash_times.popleft()
        if len(child.crash_times) >= self.flap_max:
            self._set_state(child, BROKEN)
            child.next_retry_at = None
            logger.error(
                "supervisor: %s flapping (%d crashes in %.0fs) -> broken",
                child.spec.name, len(child.crash_times), self.flap_window_s,
            )
            try:
                obs_incident.record(
                    f"supervisor-flap-{child.spec.name}",
                    note=(
                        f"{child.spec.name} crashed "
                        f"{len(child.crash_times)} times within "
                        f"{self.flap_window_s:.0f}s; last exit: "
                        f"{child.last_exit}"
                    ),
                    context=self._service_doc(child),
                    force=True,
                )
            except Exception:
                logger.exception("supervisor: incident dump failed")
            return
        child.attempt += 1
        delay = backoff_interval(
            child.attempt,
            base_s=self.base_backoff_s,
            max_s=self.max_backoff_s,
            jitter=self.jitter,
            rng=child.rng,
        )
        child.last_backoff_s = delay
        child.next_retry_at = now + delay
        self._set_state(child, RESTARTING)
        logger.warning(
            "supervisor: %s down (%s); restart #%d in %.2fs",
            child.spec.name, child.last_exit, child.restarts + 1, delay,
        )

    # -- the state machine -------------------------------------------------

    def start_all(self, wait_healthy_s: float | None = None) -> None:
        """Bring the fleet up in order, waiting (bounded) for each child
        to turn healthy before the next — same sequencing as the
        detached ``pio start-all``. A child that fails to boot is left
        to the run loop's backoff/flap machinery."""
        with self._lock:
            for child in self._children:
                now = self._clock()
                self._launch(child, now)
                if child.state != STARTING:
                    continue
                deadline = self._clock() + (
                    wait_healthy_s
                    if wait_healthy_s is not None
                    else child.spec.boot_timeout_s
                )
                while self._clock() < deadline:
                    self.step()
                    if child.state != STARTING:
                        break
                    if self._stop_event.is_set():
                        return
                    self._sleep(min(0.1, self.poll_interval))
            self._write_state()

    def step(self, now: float | None = None) -> None:
        """One supervision pass over every child. Separated from
        :meth:`run` so tests drive the machine with a fake clock."""
        with self._lock:
            if now is None:
                now = self._clock()
            for child in self._children:
                self._step_child(child, now)
            # the retrain child is deliberately NOT a supervised service:
            # its exits are expected and must not feed the flap detector
            if self.retrain is not None:
                try:
                    self.retrain.tick(now)
                except Exception:
                    logger.exception("supervisor: retrain tick failed")
                if self.retrain.dirty:
                    self.retrain.dirty = False
                    self._dirty = True
            if self._dirty:
                self._write_state()

    def _step_child(self, child: _Child, now: float) -> None:
        if child.state in (BROKEN, STOPPED):
            return
        if child.state == RESTARTING:
            if child.next_retry_at is not None and now >= child.next_retry_at:
                child.restarts += 1
                self._m_restarts(child.spec.name).inc()
                child.next_retry_at = None
                self._launch(child, now)
            return
        # STARTING or UP: pid liveness first — poll() both detects and
        # reaps the exit, giving a real status for last_exit
        rc = child.proc.poll() if child.proc is not None else None
        if child.proc is not None and rc is not None:
            child.last_exit = _describe_exit(rc)
            self._on_down(child, now)
            return
        doc = self._probe(child)
        healthy = (
            doc is not None
            and (child.pid is None or doc.get("pid") == child.pid)
        )
        if healthy:
            child.health_fails = 0
            if child.state == STARTING:
                child.instance = doc.get("instance")
                child.stable_at = now + self.stable_s
                self._set_state(child, UP)
            elif child.attempt and now >= child.stable_at:
                # stayed healthy past the stability window: the backoff
                # schedule resets (next crash waits ~base again)
                child.attempt = 0
                self._dirty = True
            return
        if child.state == STARTING:
            if now >= child.boot_deadline:
                child.last_exit = (
                    f"boot timeout ({child.spec.boot_timeout_s:.0f}s "
                    "without a healthy probe)"
                )
                self._terminate_child(child)
                self._on_down(child, now)
            return
        # UP but probe failed: tolerate transient blips, restart a hung
        # child past the threshold
        child.health_fails += 1
        if child.health_fails >= self.health_fail_threshold:
            child.last_exit = (
                f"unhealthy ({child.health_fails} consecutive failed "
                "/healthz probes with the process alive)"
            )
            self._terminate_child(child)
            self._on_down(child, now)

    def _terminate_child(self, child: _Child, grace: float | None = None
                         ) -> None:
        proc = child.proc
        if proc is None:
            return
        if grace is None:
            grace = daemon.drain_grace()
        try:
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=grace)
                except Exception:
                    proc.kill()
                    proc.wait()
        except Exception:
            pass

    def run(self) -> None:
        """Supervise until :meth:`request_stop` (or a signal handler the
        CLI wires to it) fires, then shut the fleet down."""
        try:
            while not self._stop_event.is_set():
                self.step()
                self._stop_event.wait(self.poll_interval)
        finally:
            self.stop()

    def request_stop(self) -> None:
        self._stop_event.set()

    def stop(self) -> None:
        """Graceful fleet shutdown in REVERSE bring-up order (engine
        before event server, so speed-layer/fold-in dependencies drain
        cleanly): SIGTERM (-> child drain), escalate after the drain
        grace."""
        self._stop_event.set()
        with self._lock:
            if self.retrain is not None:
                self.retrain.stop()
            for child in reversed(self._children):
                if child.state in (STOPPED,):
                    continue
                self._terminate_child(child)
                self._reap(child)
                if child.spec.spawn is None:
                    daemon._pid_file(child.spec.name).unlink(missing_ok=True)
                    daemon._record_file(child.spec.name).unlink(
                        missing_ok=True
                    )
                self._set_state(child, STOPPED)
            self._write_state()

    # -- reporting ---------------------------------------------------------

    def _service_doc(self, child: _Child) -> dict:
        now = self._clock()
        return {
            "state": child.state,
            "pid": child.pid if child.proc is not None else None,
            "port": child.spec.port or None,
            "instance": child.instance,
            "restarts": child.restarts,
            "last_exit": child.last_exit,
            "last_backoff_s": (
                round(child.last_backoff_s, 3)
                if child.last_backoff_s is not None
                else None
            ),
            "next_retry_in_s": (
                round(max(0.0, child.next_retry_at - now), 3)
                if child.next_retry_at is not None
                else None
            ),
        }

    def services(self) -> dict[str, dict]:
        with self._lock:
            return {
                child.spec.name: self._service_doc(child)
                for child in self._children
            }

    def state_doc(self) -> dict:
        doc = {
            "pid": os.getpid(),
            "updated": time.time(),
            "services": self.services(),
        }
        if self.retrain is not None:
            doc["retrain"] = self.retrain.doc()
        return doc

    def _write_state(self) -> None:
        """Atomic supervisor.json under the run dir — what ``pio
        status`` renders without talking to this process."""
        self._dirty = False
        try:
            import json

            path = state_file()
            tmp = path.with_suffix(".json.tmp")
            tmp.write_text(json.dumps(self.state_doc(), indent=2))
            tmp.replace(path)
        except OSError:
            logger.exception("supervisor: state write failed")


class RetrainScheduler:
    """Cadenced warm retrain driven by the supervisor loop.

    Each due tick spawns ``pio train`` (warm-start + prep-cache hot
    path) as a NON-supervised child — its exits are expected, so it
    must never feed the flap detector — then, on success, POSTs
    ``/reload`` to every engine replica (epoch-fenced: the engine swaps
    to the newest COMPLETED instance). Ticks are serialized: while a
    retrain is running nothing else is spawned, and a crashed retrain
    just counts a failure and waits for the next cadence tick (the
    prep cache + checkpoint make the retry cheap).

    With ``slo_driven`` the interval adapts to the ``serving.freshness``
    SLO: while it burns, the interval halves (down to ``floor_s``); once
    it is ok again the interval decays back toward the configured base.
    A tick is skipped (counted) when the speed-layer watermark
    (``events_folded + events_behind`` from the engine's ``/stats.json``)
    hasn't moved — no new events means retraining buys nothing.

    Clock, spawn, stats/SLO fetch, and reload are injectable for tests
    and for in-process drills (bench.py production_stack).
    """

    def __init__(
        self,
        interval_s: float,
        *,
        train_argv: list[str],
        engine_ports: tuple[int, ...] | list[int] = (),
        host: str = "127.0.0.1",
        slo_driven: bool = False,
        floor_s: float | None = None,
        slo_name: str = "serving.freshness",
        spawn: Callable[[], Any] | None = None,
        clock: Callable[[], float] = time.monotonic,
        fetch_stats: Callable[[], dict | None] | None = None,
        fetch_slo: Callable[[], dict | None] | None = None,
        post_reload: Callable[[], int] | None = None,
    ):
        self.base_interval_s = float(interval_s)
        self.interval_s = float(interval_s)
        self.floor_s = (
            float(floor_s) if floor_s is not None
            else max(1.0, self.base_interval_s / 8.0)
        )
        self.train_argv = list(train_argv)
        self.engine_ports = tuple(int(p) for p in engine_ports)
        self.host = host
        self.slo_driven = bool(slo_driven)
        self.slo_name = slo_name
        self._spawn = spawn
        self._clock = clock
        self._fetch_stats = fetch_stats
        self._fetch_slo = fetch_slo
        self._post_reload = post_reload
        self._proc: Any | None = None
        self._started_at = 0.0
        self._pending_watermark: float | None = None
        self._last_watermark: float | None = None
        self._next_slo_check = 0.0
        self.next_at = self._clock() + self.interval_s
        self.runs = 0
        self.skips = 0
        self.failures = 0
        self.last_run: dict | None = None
        self.dirty = True
        self._g_interval().set(self.interval_s)

    # -- metrics -----------------------------------------------------------

    @staticmethod
    def _m(kind: str):
        return obs_metrics.counter(
            f"pio_retrain_{kind}_total",
            {
                "runs": "Scheduled retrains that completed successfully",
                "skips": "Scheduled retrains skipped (watermark unmoved)",
                "failures": "Scheduled retrains that exited non-zero",
            }[kind],
        )

    @staticmethod
    def _g_interval():
        return obs_metrics.gauge(
            "pio_retrain_interval_s",
            "Current retrain cadence (adapts under --retrain-slo)",
        )

    # -- default I/O (real fleet) ------------------------------------------

    def _http_json(self, port: int, path: str, post: bool = False):
        import json as _json
        import urllib.request

        req = urllib.request.Request(
            f"http://{self.host}:{port}{path}",
            method="POST" if post else "GET",
            data=b"{}" if post else None,
            headers={"Content-Type": "application/json"} if post else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=5.0) as resp:
                return _json.loads(resp.read().decode())
        except Exception:
            return None

    def _watermark(self) -> float | None:
        """Speed-layer progress marker; None -> unknown (never skip)."""
        doc = (
            self._fetch_stats() if self._fetch_stats is not None
            else (
                self._http_json(self.engine_ports[0], "/stats.json")
                if self.engine_ports else None
            )
        )
        if not isinstance(doc, dict):
            return None
        rt = doc.get("realtime")
        if not isinstance(rt, dict):
            return None
        if "events_folded" not in rt and "events_behind" not in rt:
            # batch-only serving (speed layer off) reports no counters:
            # a constant 0.0 here would skip every tick after the first
            # successful run — unknown progress must retrain on cadence
            return None
        try:
            return float(rt.get("events_folded", 0)) + float(
                rt.get("events_behind", 0)
            )
        except (TypeError, ValueError):
            return None

    def _slo_state(self) -> str | None:
        doc = (
            self._fetch_slo() if self._fetch_slo is not None
            else (
                self._http_json(self.engine_ports[0], "/slo.json")
                if self.engine_ports else None
            )
        )
        if not isinstance(doc, dict):
            return None
        for s in doc.get("slos", []):
            if s.get("name") == self.slo_name:
                return s.get("state")
        return None

    def _reload_all(self) -> int:
        if self._post_reload is not None:
            return int(self._post_reload())
        n = 0
        for port in self.engine_ports:
            if self._http_json(port, "/reload", post=True) is not None:
                n += 1
        return n

    # -- the cadence machine -----------------------------------------------

    def _set_interval(self, value: float) -> None:
        value = min(self.base_interval_s, max(self.floor_s, value))
        if value != self.interval_s:
            logger.info(
                "retrain: interval %.1fs -> %.1fs (slo %s)",
                self.interval_s, value, self.slo_name,
            )
            self.interval_s = value
            self._g_interval().set(value)
            self.dirty = True

    def _adapt(self, now: float) -> None:
        if now < self._next_slo_check:
            return
        self._next_slo_check = now + max(
            1.0, min(5.0, self.interval_s / 4.0)
        )
        state = self._slo_state()
        if state in ("burning", "violated"):
            self._set_interval(self.interval_s / 2.0)
            # pull the next run forward: a burn shouldn't wait out the
            # remainder of a long idle interval
            self.next_at = min(self.next_at, now + self.interval_s)
        elif state == "ok":
            self._set_interval(self.interval_s * 1.5)

    def tick(self, now: float | None = None) -> None:
        if now is None:
            now = self._clock()
        if self._proc is not None:
            rc = self._proc.poll()
            if rc is None:
                return  # serialized: one retrain at a time
            self._finish(rc, now)
            return
        if self.slo_driven:
            self._adapt(now)
        if now < self.next_at:
            return
        wm = self._watermark()
        if wm is not None and self._last_watermark is not None and (
            wm <= self._last_watermark
        ):
            self.skips += 1
            self._m("skips").inc()
            self.last_run = {
                "t": time.time(), "ok": True, "skipped": True,
                "watermark": wm,
            }
            self.next_at = now + self.interval_s
            self.dirty = True
            return
        self._pending_watermark = wm
        try:
            if self._spawn is not None:
                self._proc = self._spawn()
            else:
                self._proc = daemon.spawn_service("retrain", self.train_argv)
        except Exception as exc:
            self.failures += 1
            self._m("failures").inc()
            self.last_run = {
                "t": time.time(), "ok": False,
                "exit": f"spawn failed: {exc}",
            }
            self.next_at = now + self.interval_s
            self.dirty = True
            return
        self._started_at = now
        self.dirty = True

    def _finish(self, rc: int, now: float) -> None:
        self._proc = None
        ok = rc == 0
        reloaded = 0
        if ok:
            self.runs += 1
            self._m("runs").inc()
            self._last_watermark = self._pending_watermark
            reloaded = self._reload_all()
        else:
            self.failures += 1
            self._m("failures").inc()
        self.last_run = {
            "t": time.time(),
            "ok": ok,
            "exit": _describe_exit(rc),
            "wall_s": round(now - self._started_at, 3),
            "reloaded": reloaded,
        }
        self.next_at = now + self.interval_s
        self.dirty = True

    def doc(self) -> dict:
        now = self._clock()
        return {
            "state": "running" if self._proc is not None else "idle",
            "interval_s": round(self.interval_s, 3),
            "base_interval_s": round(self.base_interval_s, 3),
            "slo_driven": self.slo_driven,
            "next_in_s": (
                None if self._proc is not None
                else round(max(0.0, self.next_at - now), 3)
            ),
            "runs": self.runs,
            "skips": self.skips,
            "failures": self.failures,
            "last_run": self.last_run,
        }

    def stop(self) -> None:
        proc = self._proc
        self._proc = None
        if proc is not None and proc.poll() is None:
            try:
                proc.terminate()
                try:
                    proc.wait(timeout=daemon.drain_grace())
                except Exception:
                    proc.kill()
                    proc.wait()
            except Exception:
                pass


def state_file():
    return daemon.run_dir() / "supervisor.json"


def read_state() -> dict | None:
    """The last supervisor.json, or None. Reports ``live`` by checking
    the recorded supervisor pid."""
    path = state_file()
    if not path.exists():
        return None
    try:
        import json

        doc = json.loads(path.read_text())
    except (ValueError, OSError):
        return None
    if not isinstance(doc, dict):
        return None
    doc["live"] = bool(doc.get("pid")) and daemon._alive(int(doc["pid"]))
    return doc


def stats_app(supervisor: Supervisor, host: str, port: int):
    """Optional obs endpoint for the supervisor process itself:
    ``/stats.json`` (the state doc), plus the standard obs routes
    (``/metrics`` carries ``pio_supervisor_*``) and health routes."""
    from predictionio_tpu.server import http

    router = http.Router()
    router.add(
        "GET", "/stats.json",
        lambda _req: http.Response.json(supervisor.state_doc()),
    )
    http.add_obs_routes(router)
    return http.HTTPApp(router, host=host, port=port, name="supervisor")
