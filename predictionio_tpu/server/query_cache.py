"""Epoch-fenced query-result cache: preserialized response bytes, LRU.

The serving read path pays gather -> score -> top-k -> JSON encode on
every request even when the model has not changed since the identical
query last ran. This cache stores the FINISHED response bytes keyed by
``(engine_variant, canonical_query_bytes, epoch)`` so a hit skips the
device dispatch, the serving join, and the encode entirely — the
cached-scoring-tier-in-front-of-the-model pattern of Google's ads
serving stack (PAPERS.md), with the invalidation problem solved exactly
rather than by TTL: the engine server bumps one epoch counter on EVERY
model swap (``/reload`` and speed-layer ``apply_patch`` alike), so a
cached entry is valid iff its epoch equals the served epoch. Stale
epochs become unreachable the instant the counter moves; ``sweep()``
reclaims their bytes.

Concurrency: the key space is split over N shards, each an OrderedDict
under its own lock, so concurrent handler threads rarely contend — the
hit path is one dict lookup + move_to_end under a shard lock. Capacity
is BYTES, not entries (responses vary from ~100 B to tens of KB);
eviction is per-shard LRU. Hit/miss/eviction counters are per-shard and
summed on read, keeping the hot path free of any global atomic.
"""

from __future__ import annotations

import json
import threading
from collections import OrderedDict

__all__ = ["QueryCache", "canonical_query_bytes"]

# fixed per-entry bookkeeping estimate (key tuple, OrderedDict node,
# bytes object headers) added to the payload size when charging a shard
_ENTRY_OVERHEAD = 128


def canonical_query_bytes(body: dict) -> bytes:
    """Canonical bytes of a query body: key order and whitespace cannot
    fork cache entries for the same logical query. Raises TypeError for
    non-JSON-serializable bodies (the caller treats that as uncacheable).
    """
    return json.dumps(
        body, sort_keys=True, separators=(",", ":"), ensure_ascii=False
    ).encode("utf-8")


class _Shard:
    __slots__ = ("lock", "entries", "bytes", "hits", "misses", "evictions")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.entries: OrderedDict[tuple, bytes] = OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0


class QueryCache:
    """Sharded-lock, byte-capped LRU of preserialized response bytes."""

    def __init__(self, capacity_bytes: int, shards: int = 8):
        if capacity_bytes <= 0:
            raise ValueError("capacity_bytes must be positive")
        self.capacity_bytes = int(capacity_bytes)
        self._shards = [_Shard() for _ in range(max(1, int(shards)))]
        self._per_shard = max(
            _ENTRY_OVERHEAD + 1, self.capacity_bytes // len(self._shards)
        )

    def _shard(self, key: tuple) -> _Shard:
        return self._shards[hash(key) % len(self._shards)]

    def get(self, key: tuple) -> bytes | None:
        """Cached response bytes for the key, or None (LRU-touching)."""
        s = self._shard(key)
        with s.lock:
            payload = s.entries.get(key)
            if payload is None:
                s.misses += 1
                return None
            s.entries.move_to_end(key)
            s.hits += 1
            return payload

    def put(self, key: tuple, payload: bytes) -> None:
        """Insert (or refresh) an entry, evicting LRU past the shard's
        byte budget. Payloads too large for one shard are not cached."""
        size = len(payload) + len(key[1]) + _ENTRY_OVERHEAD
        s = self._shard(key)
        if size > self._per_shard:
            return
        with s.lock:
            old = s.entries.pop(key, None)
            if old is not None:
                s.bytes -= len(old) + len(key[1]) + _ENTRY_OVERHEAD
            s.entries[key] = payload
            s.bytes += size
            while s.bytes > self._per_shard and s.entries:
                k, v = s.entries.popitem(last=False)
                s.bytes -= len(v) + len(k[1]) + _ENTRY_OVERHEAD
                s.evictions += 1

    def sweep(self, current_epoch: int, variant: str | None = None) -> int:
        """Drop every entry whose epoch != ``current_epoch``.

        Correctness never needs this — a bumped epoch makes old entries
        unreachable by key — but the bytes they hold would otherwise only
        leave via LRU pressure. Called on every model swap (reload or
        fold-in patch). Returns how many entries were dropped.

        With ``variant`` set, only that tenant's partition is swept —
        a multi-tenant server reloading tenant A must leave tenant B's
        cached results (under B's own epoch) untouched."""
        dropped = 0
        for s in self._shards:
            with s.lock:
                stale = [
                    k for k in s.entries
                    if k[2] != current_epoch
                    and (variant is None or k[0] == variant)
                ]
                for k in stale:
                    v = s.entries.pop(k)
                    s.bytes -= len(v) + len(k[1]) + _ENTRY_OVERHEAD
                dropped += len(stale)
        return dropped

    def clear(self) -> None:
        for s in self._shards:
            with s.lock:
                s.entries.clear()
                s.bytes = 0

    def gauges(self) -> dict:
        """Aggregated operator gauges (engine server /stats.json)."""
        hits = misses = evictions = entries = nbytes = 0
        for s in self._shards:
            with s.lock:
                hits += s.hits
                misses += s.misses
                evictions += s.evictions
                entries += len(s.entries)
                nbytes += s.bytes
        lookups = hits + misses
        return {
            "cache_hits": hits,
            "cache_misses": misses,
            "cache_hit_rate": round(hits / lookups, 4) if lookups else 0.0,
            "cache_entries": entries,
            "cache_bytes": nbytes,
            "cache_capacity_bytes": self.capacity_bytes,
            "cache_evictions": evictions,
        }
