"""Event-loop HTTP server + routing shared by all framework servers.

Plays the role of spray-can/akka-http in the reference (request routing,
JSON marshalling, access-key auth), with no third-party dependencies.

Concurrency model (the thread-per-connection ``ThreadingHTTPServer`` it
replaced capped keep-alive concurrency at the thread count):

- ONE selector thread owns the listen socket, every idle keep-alive
  connection, and a timer wheel (``call_later``). 1k+ idle connections
  cost file descriptors, not stacks.
- A readable connection is unregistered and handed to a small worker
  pool, which runs the ``recv_into`` parser + router dispatch with
  blocking reads bounded by ``read_timeout`` (the slowloris bound),
  then hands the connection back to the selector.
- Low-concurrency latency: when few connections are open, the worker
  LINGERS briefly on the socket after responding, so a busy keep-alive
  client keeps its thread-per-connection round-trip time and only pays
  the selector hop when the server is actually fan-out loaded.
- The timer wheel doubles as the engine server's query-deadline clock
  (``HTTPApp.call_later``) — deadline expiry is a heap entry, not a
  standing watcher pool.
"""

from __future__ import annotations

import base64
import collections
import heapq
import itertools
import json
import logging
import os
import re
import select as select_mod
import selectors
import signal as signal_mod
import socket
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.client import responses as _RESPONSES
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse

from predictionio_tpu import faults
from predictionio_tpu.obs import device as obs_device
from predictionio_tpu.obs import history as obs_history
from predictionio_tpu.obs import incident as obs_incident
from predictionio_tpu.obs import metrics as obs_metrics
from predictionio_tpu.obs import slo as obs_slo
from predictionio_tpu.obs import trace as obs_trace
from predictionio_tpu.server import jsonx

logger = logging.getLogger(__name__)


@dataclass
class Request:
    method: str
    path: str
    query: dict[str, str]
    headers: dict[str, str]
    body: bytes
    path_params: dict[str, str] = field(default_factory=dict)
    # set only for stream routes (Router.add_stream): an incremental
    # body reader (_BodyStream) handed to the handler BEFORE the body is
    # read off the socket; ``body`` stays b"" on those requests
    body_stream: "Any | None" = None

    def json(self) -> Any:
        if not self.body:
            return None
        # orjson when available (event-server ingest parses one body per
        # request on the hot path), stdlib fallback — server/jsonx.py
        return jsonx.loads(self.body)

    def form(self) -> dict[str, str]:
        parsed = parse_qs(self.body.decode("utf-8"), keep_blank_values=True)
        return {k: v[0] for k, v in parsed.items()}

    @property
    def access_key(self) -> str | None:
        """accessKey from query param or HTTP basic auth username
        (reference EventServer withAccessKeyFromQueryOrBasicAuth,
        api/EventServer.scala:92-120)."""
        if "accessKey" in self.query:
            return self.query["accessKey"]
        auth = self.headers.get("authorization", "")
        if auth.lower().startswith("basic "):
            try:
                decoded = base64.b64decode(auth[6:]).decode("utf-8")
                return decoded.split(":", 1)[0] or None
            except Exception:
                return None
        return None


@dataclass
class Response:
    status: int = 200
    # JSON-serializable object; or (content_type, bytes); or raw bytes
    # already JSON-encoded (sent verbatim — the query-cache hit path and
    # any other preserialized producer skip the re-encode)
    body: Any = None
    headers: dict[str, str] = field(default_factory=dict)
    # invoked after the response bytes are written — lets a /stop route
    # shut the server down without racing its own response flush
    after_send: "Callable[[], None] | None" = None

    @staticmethod
    def json(obj: Any, status: int = 200) -> "Response":
        return Response(status=status, body=obj)

    @staticmethod
    def json_bytes(payload: bytes, status: int = 200) -> "Response":
        """Pre-encoded JSON sent as-is (no dumps on the send path)."""
        return Response(status=status, body=payload)

    @staticmethod
    def error(message: str, status: int) -> "Response":
        return Response(status=status, body={"message": message})

    @staticmethod
    def html(text: str, status: int = 200) -> "Response":
        return Response(status=status, body=("text/html; charset=utf-8", text.encode()))


Handler = Callable[[Request], Response]


_JSON_CT = "application/json; charset=utf-8"

# (status, phrase) -> full response bytes for header-only error replies,
# and (status, content_type) -> static head prefix up to "Content-Length: ".
# Built lazily ONCE per distinct shape instead of f-string-assembled per
# request — the measured per-request floor is dominated by exactly this
# kind of per-call byte construction.
_SIMPLE_CACHE: dict[tuple[int, str], bytes] = {}
_HEAD_CACHE: dict[tuple[int, str], bytes] = {}


def _simple_bytes(status: int, phrase: str) -> bytes:
    key = (status, phrase)
    payload = _SIMPLE_CACHE.get(key)
    if payload is None:
        payload = (
            f"HTTP/1.1 {status} {phrase}\r\n"
            "Content-Length: 0\r\nConnection: close\r\n\r\n"
        ).encode("latin-1")
        _SIMPLE_CACHE[key] = payload
    return payload


def _static_head(status: int, content_type: str) -> bytes:
    """Everything before the Content-Length VALUE, precomputed."""
    key = (status, content_type)
    head = _HEAD_CACHE.get(key)
    if head is None:
        phrase = _RESPONSES.get(status, "")
        head = (
            f"HTTP/1.1 {status} {phrase}\r\n"
            f"Content-Type: {content_type}\r\nContent-Length: "
        ).encode("latin-1")
        _HEAD_CACHE[key] = head
    return head


_CORS_ALLOW_HEADERS = (
    "Origin, X-Requested-With, Content-Type, Accept, Accept-Encoding, "
    "Accept-Language, Host, Referer, User-Agent"
)


class Router:
    """Method+path-pattern routing. Patterns use ``<name>`` segments.

    ``cors=True`` answers OPTIONS preflights and stamps
    ``Access-Control-Allow-Origin: *`` on every response (reference
    tools/.../dashboard/CorsSupport.scala — AllOrigins)."""

    def __init__(self, cors: bool = False) -> None:
        self._routes: list[tuple[str, re.Pattern, Handler]] = []
        # stream routes dispatch BEFORE the body is read: the handler
        # gets request.body_stream and consumes the body incrementally
        # (the wire-speed binary ingest path commits frame by frame
        # instead of materializing the whole body)
        self._stream_routes: list[tuple[str, re.Pattern, Handler]] = []
        self.cors = cors

    def add(self, method: str, pattern: str, handler: Handler) -> None:
        # <name> matches one segment; <name:path> greedily matches the
        # rest of the path (plugin REST dispatch forwards sub-paths)
        regex = re.sub(r"<([a-zA-Z_]+):path>", r"(?P<\1>.+)", pattern)
        # the lookbehind keeps this from rewriting the <name> inside the
        # (?P<name>...) groups the first pass just emitted
        regex = re.sub(r"(?<!\(\?P)<([a-zA-Z_]+)>", r"(?P<\1>[^/]+)", regex)
        self._routes.append((method.upper(), re.compile(f"^{regex}$"), handler))

    def route(self, method: str, pattern: str):
        def deco(fn: Handler) -> Handler:
            self.add(method, pattern, fn)
            return fn

        return deco

    def add_stream(self, method: str, pattern: str, handler: Handler) -> None:
        """Register a streaming-body route (same pattern syntax as
        :meth:`add`). Matched requests dispatch with the body still on
        the socket: ``request.body_stream.read(n)`` pulls it
        incrementally, ``request.body`` is empty."""
        regex = re.sub(r"<([a-zA-Z_]+):path>", r"(?P<\1>.+)", pattern)
        regex = re.sub(r"(?<!\(\?P)<([a-zA-Z_]+)>", r"(?P<\1>[^/]+)", regex)
        self._stream_routes.append(
            (method.upper(), re.compile(f"^{regex}$"), handler)
        )

    def match_stream(
        self, method: str, path: str
    ) -> tuple[Handler, dict[str, str]] | None:
        for m, regex, handler in self._stream_routes:
            if m != method:
                continue
            match = regex.match(path)
            if match:
                return handler, match.groupdict()
        return None

    def dispatch(self, request: Request) -> Response:
        response = self._dispatch(request)
        if self.cors:
            response.headers.setdefault("Access-Control-Allow-Origin", "*")
        return response

    def _dispatch(self, request: Request) -> Response:
        path_matched = False
        allowed: list[str] = []
        for method, regex, handler in self._routes:
            m = regex.match(request.path)
            if not m:
                continue
            path_matched = True
            allowed.append(method)
            if method != request.method:
                continue
            request.path_params = m.groupdict()
            return handler(request)
        if path_matched:
            if self.cors and request.method == "OPTIONS":
                # preflight for a resource that responds to other methods
                return Response(
                    200,
                    body=("text/plain", b""),
                    headers={
                        "Access-Control-Allow-Methods": ", ".join(
                            ["OPTIONS", *dict.fromkeys(allowed)]
                        ),
                        "Access-Control-Allow-Headers": _CORS_ALLOW_HEADERS,
                        "Access-Control-Max-Age": "1728000",
                    },
                )
            return Response.error("method not allowed", 405)
        return Response.error("not found", 404)


_PROM_CT = "text/plain; version=0.0.4; charset=utf-8"


def add_obs_routes(router: Router) -> None:
    """Mount ``GET /metrics`` (Prometheus text format),
    ``GET /traces.json`` (slowest recent traces; ``?limit=N`` caps the
    list, ``?since_ms=`` drops traces that started before the given
    epoch-milliseconds, ``?slo=violated`` keeps only traces tagged as
    SLO evidence), ``GET /slo.json`` (objective states, burn rates, and
    the alert ring), ``GET /history.json`` (bounded metrics history
    rings; ``?metric=`` substring filter, ``?since_ms=`` cutoff,
    ``?step=`` re-grids onto a coarser step), ``POST /incident``
    (on-demand flight-recorder bundle, ``?reason=``/``?note=``), and
    ``POST /profile`` (bounded on-demand ``jax.profiler`` capture,
    ``?seconds=``/``?out=``). The GET endpoints are unauthenticated on
    every server — standard scraper behavior; none exposes event data.

    Mounting also arms the passive obs machinery the routes read from:
    the history sampler's ticker and the flight recorder's crash/SLO
    hooks — both no-ops under ``PIO_OBS=0``."""
    obs_history.ensure_ticker()
    obs_incident.install_crash_hooks()

    def _metrics_route(_req: Request) -> Response:
        # Registers the per-device memory gauges on first scrape after
        # jax came up; a no-op (and jax-import-free) before that.
        obs_device.ensure_device_gauges()
        return Response(200, body=(_PROM_CT, obs_metrics.render_prometheus()))

    def _traces_route(req: Request) -> Response:
        traces = obs_trace.TRACES.snapshot()
        slo_filter = req.query.get("slo")
        if slo_filter is not None:
            if slo_filter != "violated":
                return Response.error("slo filter must be 'violated'", 400)
            traces = [t for t in traces if t.get("sloViolated")]
        since_ms = req.query.get("since_ms")
        if since_ms is not None:
            try:
                cutoff = float(since_ms)
            except ValueError:
                return Response.error("since_ms must be a number", 400)
            traces = [t for t in traces if t["start"] * 1000.0 >= cutoff]
        limit = req.query.get("limit")
        if limit is not None:
            try:
                n = int(limit)
            except ValueError:
                return Response.error("limit must be an integer", 400)
            if n < 0:
                return Response.error("limit must be >= 0", 400)
            traces = traces[:n]
        return Response.json({"traces": traces})

    def _profile_route(req: Request) -> Response:
        try:
            seconds = float(req.query.get("seconds", "2"))
        except ValueError:
            return Response.error("seconds must be a number", 400)
        try:
            result = obs_device.profile_capture(
                seconds, out_dir=req.query.get("out") or None
            )
        except RuntimeError as exc:
            return Response.error(str(exc), 409)
        except Exception as exc:  # jax missing / profiler failure
            return Response.error(f"profile capture failed: {exc}", 500)
        return Response.json(result)

    def _slo_route(_req: Request) -> Response:
        return Response.json(obs_slo.document())

    def _history_route(req: Request) -> Response:
        since_ms = req.query.get("since_ms")
        cutoff = None
        if since_ms is not None:
            try:
                cutoff = float(since_ms)
            except ValueError:
                return Response.error("since_ms must be a number", 400)
        step = req.query.get("step")
        step_s = None
        if step is not None:
            try:
                step_s = float(step)
            except ValueError:
                return Response.error("step must be a number", 400)
            if step_s <= 0:
                return Response.error("step must be > 0", 400)
        return Response.json(
            obs_history.snapshot(
                metric=req.query.get("metric") or None,
                since_ms=cutoff,
                step_s=step_s,
            )
        )

    def _incident_route(req: Request) -> Response:
        if not obs_metrics.enabled():
            return Response.error("observability disabled (PIO_OBS=0)", 503)
        try:
            path = obs_incident.record(
                req.query.get("reason") or "manual",
                note=req.query.get("note") or None,
                force=True,
            )
        except Exception as exc:
            return Response.error(f"incident dump failed: {exc}", 500)
        return Response.json(
            {
                "ok": path is not None,
                "incident": str(path) if path else None,
                "files": list(obs_incident.BUNDLE_FILES),
            }
        )

    router.add("GET", "/metrics", _metrics_route)
    router.add("GET", "/traces.json", _traces_route)
    router.add("GET", "/slo.json", _slo_route)
    router.add("GET", "/history.json", _history_route)
    router.add("POST", "/incident", _incident_route)
    router.add("POST", "/profile", _profile_route)


class _ConnReader:
    """Per-connection request reader over ONE reusable ``recv_into``
    buffer.

    The stdlib path (``socket.makefile`` -> BufferedReader) allocates a
    fresh 64 KiB buffer per connection and crosses the C/Python boundary
    once per ``readline`` — ~8 crossings per request (request line + 5-7
    headers). A keep-alive request usually lands in ONE TCP segment, so
    one ``recv_into`` into a reused bytearray followed by C-speed
    ``find(b"\\n")`` scans serves the whole request with a single
    syscall and zero per-request buffer allocations (only the returned
    line/body bytes are materialized). Interface matches what
    ``handle_one_request`` used from ``rfile``: ``readline(limit)``
    (up to ``limit`` bytes, newline-terminated unless truncated/EOF) and
    ``read(n)`` (short only at EOF). Works unchanged over TLS —
    ``SSLSocket.recv_into`` drives the lazy server-side handshake the
    accept path deferred."""

    __slots__ = ("_sock", "_buf", "_start", "_end")

    def __init__(self, sock, bufsize: int = 65536):
        self._sock = sock
        self._buf = bytearray(bufsize)
        self._start = 0
        self._end = 0

    def buffered(self) -> int:
        """Bytes already consumed from the kernel but not yet parsed —
        the event loop must NOT park a connection with a pipelined
        request sitting here (the selector can't see user-space bytes)."""
        return self._end - self._start

    def _fill(self) -> bool:
        """recv more bytes; False on EOF. Compacts before recv when the
        tail of the buffer is exhausted."""
        buf = self._buf
        if self._start == self._end:
            self._start = self._end = 0
        elif self._end == len(buf):
            n = self._end - self._start
            buf[:n] = buf[self._start:self._end]
            self._start, self._end = 0, n
        with memoryview(buf) as mv:
            got = self._sock.recv_into(mv[self._end:])
        if got == 0:
            return False
        self._end += got
        return True

    def readline(self, limit: int) -> bytes:
        """Up to ``limit`` bytes ending at the first ``\\n``; exactly
        ``limit`` bytes when no newline fits (caller rejects oversized
        lines); whatever remains at EOF (b"" when nothing)."""
        while True:
            i = self._buf.find(b"\n", self._start, self._end)
            if i >= 0 and i - self._start < limit:
                line = bytes(self._buf[self._start:i + 1])
                self._start = i + 1
                return line
            if self._end - self._start >= limit:
                line = bytes(self._buf[self._start:self._start + limit])
                self._start += limit
                return line
            if not self._fill():
                line = bytes(self._buf[self._start:self._end])
                self._start = self._end
                return line

    def read(self, n: int) -> bytes:
        """Exactly ``n`` body bytes (fewer only at EOF). Whatever the
        header recv over-read is consumed from the buffer; any remainder
        recv_into's DIRECTLY into the result — no double buffering."""
        have = min(n, self._end - self._start)
        if have == n:
            body = bytes(self._buf[self._start:self._start + n])
            self._start += n
            return body
        out = bytearray(n)
        out[:have] = self._buf[self._start:self._start + have]
        self._start += have
        filled = have
        with memoryview(out) as mv:
            while filled < n:
                got = self._sock.recv_into(mv[filled:])
                if got == 0:
                    return bytes(out[:filled])
                filled += got
        return bytes(out)


class _BodyStream:
    """Incremental request-body reader handed to stream routes
    (``Router.add_stream``): bounded by Content-Length, so it can never
    read into the next pipelined request. An ``Expect: 100-continue`` is
    answered lazily on the FIRST read — a handler that sheds the request
    (backpressure 429) before touching the body never invites the client
    to send it."""

    __slots__ = ("_reader", "_sock", "remaining", "_continue_pending")

    def __init__(self, reader, sock, length: int, continue_pending: bool):
        self._reader = reader
        self._sock = sock
        self.remaining = length
        self._continue_pending = continue_pending

    def read(self, n: int) -> bytes:
        if n <= 0 or self.remaining <= 0:
            return b""
        if self._continue_pending:
            self._continue_pending = False
            self._sock.sendall(b"HTTP/1.1 100 Continue\r\n\r\n")
        data = self._reader.read(min(n, self.remaining))
        self.remaining -= len(data)
        if not data:
            self.remaining = 0  # client EOF mid-body
        return data


class _TimerHandle:
    """One timer-wheel entry; ``cancel()`` is lazy (the loop skips
    cancelled entries when they surface at the top of the heap)."""

    __slots__ = ("when", "seq", "fn", "cancelled")

    def __init__(self, when: float, seq: int, fn: Callable[[], None]):
        self.when = when
        self.seq = seq
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def __lt__(self, other: "_TimerHandle") -> bool:
        return (self.when, self.seq) < (other.when, other.seq)


class _Connection:
    """One accepted socket's state: reader, keep-alive flag, idle timer.

    Ownership invariant: a connection is either REGISTERED with the
    selector (loop thread owns it) or ACTIVE in exactly one worker —
    never both. The loop unregisters before handing it to the pool and
    only the owning worker re-registers it, so reads, writes, and
    ``close`` never race."""

    __slots__ = (
        "app", "sock", "addr", "reader", "close_connection",
        "_rfile", "idle_timer",
    )

    def __init__(self, app: "HTTPApp", sock, addr):
        self.app = app
        self.sock = sock
        self.addr = addr
        self.reader = None
        self._rfile = None
        self.close_connection = False
        self.idle_timer: _TimerHandle | None = None

    def _ensure_reader(self):
        r = self.reader
        if r is None:
            if self.app.recv_buffer:
                r = _ConnReader(self.sock)
            else:
                # the stdlib rfile exposes the same readline/read shape —
                # it IS the fallback reader
                r = self._rfile = self.sock.makefile("rb")
            self.reader = r
        return r

    def buffered(self) -> bool:
        """True when a pipelined request (or part of one) is already in
        user space — in the reader's buffer or, over TLS, decrypted
        inside the SSL layer (``pending``). The selector only sees
        kernel-buffered bytes, so parking a connection with either
        non-empty would strand the request."""
        r = self.reader
        if isinstance(r, _ConnReader) and r.buffered():
            return True
        pending = getattr(self.sock, "pending", None)
        if pending is not None:
            try:
                return pending() > 0
            except (OSError, ValueError):
                return False
        return False

    def close(self) -> None:
        self.app._untrack(self)
        if self._rfile is not None:
            try:
                self._rfile.close()
            except OSError:
                pass
        try:
            self.sock.close()
        except OSError:
            pass

    # -- request cycle (runs in a worker thread) ---------------------------

    def handle_one_request(self) -> None:
        """Minimal HTTP/1.1 parse+dispatch+respond.

        BaseHTTPRequestHandler routes headers through the email parser
        and emits each response header as its own write — ~60% of a
        keep-alive round trip's server cost on the ingest/serving hot
        paths (measured: ~160 us/request floor). This parses the request
        line + headers directly and sends each response as ONE buffer.
        Scope matches what the framework's clients speak: method line,
        case-insensitive headers, Content-Length bodies,
        keep-alive/close, Expect: 100-continue; no chunked request
        bodies (the reference's spray server also buffers full
        entities)."""
        app = self.app
        self.close_connection = True
        reader = self._ensure_reader()
        try:
            faults.fault_point("http.read")
            line = reader.readline(65537)
        except OSError:
            return
        if not line:
            return
        # request clock starts when the first line ARRIVES, so a
        # keep-alive connection's idle wait never pollutes the
        # read/parse span
        t_start = time.perf_counter()
        if len(line) > 65536:
            self._send_simple(414, "URI Too Long")
            return
        try:
            method, target, version = (
                line.decode("latin-1").rstrip("\r\n").split(" ")
            )
        except ValueError:
            self._send_simple(400, "Bad Request")
            return
        if not version.startswith("HTTP/"):
            self._send_simple(400, "Bad Request")
            return
        if method not in (
            "GET", "POST", "DELETE", "PUT", "OPTIONS"
        ):
            # a HEAD answered with a body would desync keep-alive
            self._send_simple(501, "Unsupported method")
            return
        headers: dict[str, str] = {}
        n_lines = 0
        while True:
            try:
                h = reader.readline(65537)
            except OSError:  # read timeout / client reset
                return
            if h in (b"\r\n", b"\n", b""):
                break
            n_lines += 1  # count LINES, not dict entries: a
            # stream of repeated/colon-less lines must still
            # trip the cap (stdlib _MAXHEADERS analog)
            if len(h) > 65536 or n_lines > 256:
                self._send_simple(431, "Header Fields Too Large")
                return
            k, sep, v = h.decode("latin-1").partition(":")
            if sep:
                key, val = k.strip().lower(), v.strip()
                if key == "content-length" and headers.get(key, val) != val:
                    # conflicting duplicate framing headers are
                    # the classic smuggling vector (RFC 9112
                    # §6.3): never silently pick one
                    self._send_simple(400, "Bad Request")
                    return
                headers[key] = val
        conn = headers.get("connection", "").lower()
        self.close_connection = conn == "close" or (
            version == "HTTP/1.0" and conn != "keep-alive"
        )
        te = headers.get("transfer-encoding", "").lower()
        if te and te != "identity":
            # chunked bodies are out of scope; treating them as
            # body-less would desync the keep-alive stream
            # (framing bytes parsed as the next request)
            self._send_simple(501, "Transfer-Encoding unsupported")
            return
        expect_continue = (
            headers.get("expect", "").lower() == "100-continue"
        )
        try:
            length = int(headers.get("content-length") or 0)
        except ValueError:
            self._send_simple(400, "Bad Request")
            return
        if length < 0:
            self._send_simple(400, "Bad Request")
            return
        parsed = urlparse(target)
        stream_match = self.app.router.match_stream(method, parsed.path)
        body_stream: _BodyStream | None = None
        if stream_match is not None:
            # stream route: dispatch BEFORE the body read — the handler
            # pulls bytes incrementally (100-continue deferred to its
            # first read, see _BodyStream)
            body = b""
            body_stream = _BodyStream(
                reader, self.sock, length, expect_continue
            )
        else:
            if expect_continue:
                self.sock.sendall(b"HTTP/1.1 100 Continue\r\n\r\n")
            try:
                body = reader.read(length) if length > 0 else b""
            except OSError:  # read timeout mid-body
                return
            if length > 0 and len(body) < length:
                self.close_connection = True
                return  # client died mid-body
        q = {
            k: v[0]
            for k, v in parse_qs(
                parsed.query, keep_blank_values=True
            ).items()
        }
        request = Request(
            method=method,
            path=parsed.path,
            query=q,
            headers=headers,
            body=body,
            body_stream=body_stream,
        )
        draining = app._draining.is_set()
        if draining and time.monotonic() >= app._drain_deadline:
            # past the drain deadline: shed without dispatching (a
            # stream-route body may still be on the socket — the close
            # below resynchronizes the stream)
            self.close_connection = True
            shed = Response.error("draining", 503)
            shed.headers["Connection"] = "close"
            self._send(shed)
            return
        tr = None
        t_parsed = 0.0
        if obs_metrics.enabled():
            # trace anchored at first-line arrival; an incoming
            # X-PIO-Trace id stitches this hop into the caller's
            # timeline (read/parse happened before the header was
            # known, so its span is added retroactively)
            t_parsed = time.perf_counter()
            tr = obs_trace.Trace(
                f"{method} {parsed.path}",
                trace_id=headers.get("x-pio-trace"),
                t0=t_start,
            )
            tr.add_span("http.read_parse", t_start, t_parsed)
            obs_trace.set_current_trace(tr)
        try:
            if stream_match is not None:
                handler, request.path_params = stream_match
                response = handler(request)
            else:
                response = app.router.dispatch(request)
        except json.JSONDecodeError:
            response = Response.error("invalid JSON body", 400)
        except OSError:
            if stream_match is not None:
                # read timeout / client reset while the handler was
                # consuming the body stream: no usable response
                self.close_connection = True
                return
            logger.exception(
                "unhandled error on %s %s", method, parsed.path
            )
            response = Response.error("internal error", 500)
        except Exception:
            logger.exception(
                "unhandled error on %s %s", method, parsed.path
            )
            response = Response.error("internal error", 500)
        finally:
            if tr is not None:
                obs_trace.set_current_trace(None)
        if body_stream is not None and body_stream.remaining > 0:
            # the handler left body bytes on the socket (reject/shed):
            # drain small remainders to preserve keep-alive, give up on
            # large ones (the response still goes out; the close tells
            # the client to stop sending)
            if body_stream.remaining <= 262144 and not body_stream._continue_pending:
                try:
                    while body_stream.remaining > 0:
                        if not body_stream.read(65536):
                            break
                except OSError:
                    self.close_connection = True
            else:
                self.close_connection = True
        if draining or app._draining.is_set():
            # within the drain window (re-checked at send time: drain
            # may have begun while this request was in flight): the
            # request is served normally, but the connection is handed
            # back to the client closed so its NEXT request reconnects
            # (and lands on whichever listener still accepts — the
            # rolling-restart handoff)
            response.headers.setdefault("Connection", "close")
            self.close_connection = True
        if tr is not None:
            # bookkeeping runs BEFORE the response bytes leave:
            # once the client unblocks it starts contending for
            # the GIL, and post-send bookkeeping then costs two
            # forced thread switches per request — far more than
            # the few µs of work itself. The measured duration
            # excludes only the final buffered socket write.
            t_end = time.perf_counter()
            tr.add_span("dispatch", t_parsed, t_end)
            tr.status = response.status
            tr.duration_s = t_end - t_start
            app._m_request.observe(t_end - t_start)
            app._m_read_parse.observe(t_parsed - t_start)
            app._m_requests.inc()
            if response.status >= 500:
                app._m_errors.inc()
            obs_trace.TRACES.offer(tr)
        self._send(response)

    def _send_simple(self, status: int, phrase: str) -> None:
        # cached constant bytes — parse-reject paths pay one
        # dict lookup, not per-request string assembly
        self.sock.sendall(_simple_bytes(status, phrase))
        self.close_connection = True

    def _head(self, response: Response, content_type: str,
              extra: str) -> bytes:
        phrase = _RESPONSES.get(response.status, "")
        head = (
            f"HTTP/1.1 {response.status} {phrase}\r\n"
            f"Content-Type: {content_type}\r\n{extra}"
        )
        for k, v in response.headers.items():
            head += f"{k}: {v}\r\n"
        return (head + "\r\n").encode("latin-1")

    def _send(self, response: Response) -> None:
        if (
            isinstance(response.body, tuple)
            and not isinstance(response.body[1], (bytes, bytearray))
        ):
            # streaming body: (content_type, iterator-of-bytes).
            # No Content-Length; Connection: close delimits the
            # stream (bulk export of multi-GB logs must not
            # materialize in server RSS)
            content_type, chunks = response.body
            self.sock.sendall(
                self._head(response, content_type,
                           "Connection: close\r\n")
            )
            for chunk in chunks:
                if chunk:
                    self.sock.sendall(chunk)
            self.close_connection = True
            if response.after_send is not None:
                threading.Thread(
                    target=response.after_send, daemon=True
                ).start()
            return
        if isinstance(response.body, (bytes, bytearray)):
            # pre-encoded JSON (query-cache hits and any other
            # preserialized producer): sent verbatim, no dumps
            content_type, payload = _JSON_CT, response.body
        elif isinstance(response.body, tuple):
            content_type, payload = response.body
        else:
            content_type = _JSON_CT
            payload = jsonx.dumps_bytes(
                response.body if response.body is not None else {}
            )
        if response.headers:
            head = self._head(
                response, content_type,
                f"Content-Length: {len(payload)}\r\n",
            )
        else:
            # common case: no custom headers — static prefix +
            # the length digits, zero per-request f-strings
            head = (
                _static_head(response.status, content_type)
                + b"%d\r\n\r\n" % len(payload)
            )
        self.sock.sendall(head + payload)
        if response.after_send is not None:
            threading.Thread(
                target=response.after_send, daemon=True
            ).start()


class _EventLoop:
    """Selector + timer wheel. Runs in one thread (or inline for
    ``start(background=False)``); all selector/heap mutation happens on
    that thread — cross-thread requests arrive via ``_pending`` and a
    wake pipe."""

    # select timeout floor when no timer is due: bounds stop() latency
    # even if the wake-pipe write is lost
    _IDLE_TICK = 5.0

    def __init__(self, app: "HTTPApp", lsock: socket.socket):
        self.app = app
        self.lsock = lsock
        self.selector = selectors.DefaultSelector()
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        os.set_blocking(self._wake_w, False)
        self.selector.register(self._wake_r, selectors.EVENT_READ, "wake")
        self.selector.register(lsock, selectors.EVENT_READ, "accept")
        self._timers: list[_TimerHandle] = []
        self._tlock = threading.Lock()
        self._seq = itertools.count()
        self._pending: collections.deque[Callable[[], None]] = collections.deque()
        self._stopping = False

    # -- cross-thread API --------------------------------------------------

    def call_later(self, delay: float, fn: Callable[[], None]) -> _TimerHandle:
        h = _TimerHandle(time.monotonic() + max(0.0, delay), next(self._seq), fn)
        with self._tlock:
            heapq.heappush(self._timers, h)
        self._wakeup()
        return h

    def call_soon(self, fn: Callable[[], None]) -> None:
        self._pending.append(fn)
        self._wakeup()

    def stop(self) -> None:
        self._stopping = True
        self._wakeup()

    def close_listener(self) -> None:
        """Stop accepting (loop thread only — reach it via
        ``call_soon``). Parked keep-alive connections stay registered;
        with ``SO_REUSEPORT`` the kernel routes new connections to the
        remaining same-port listeners."""
        try:
            self.selector.unregister(self.lsock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            self.lsock.close()
        except OSError:
            pass

    def _wakeup(self) -> None:
        try:
            os.write(self._wake_w, b"\0")
        except OSError:
            pass

    # -- loop body (owning thread only) ------------------------------------

    def run(self) -> None:
        try:
            while not self._stopping:
                try:
                    events = self.selector.select(self._next_timeout())
                except OSError:
                    continue
                while self._pending:
                    try:
                        self._pending.popleft()()
                    except Exception:
                        logger.exception("event-loop callback failed")
                for key, _ in events:
                    data = key.data
                    if data == "wake":
                        try:
                            while os.read(self._wake_r, 4096):
                                pass
                        except OSError:
                            pass
                    elif data == "accept":
                        self._accept()
                    else:
                        self._on_readable(data)
                self._fire_timers()
        finally:
            self._teardown()

    def _next_timeout(self) -> float:
        with self._tlock:
            while self._timers and self._timers[0].cancelled:
                heapq.heappop(self._timers)
            if not self._timers:
                return self._IDLE_TICK
            return min(
                self._IDLE_TICK,
                max(0.0, self._timers[0].when - time.monotonic()),
            )

    def _fire_timers(self) -> None:
        now = time.monotonic()
        while True:
            with self._tlock:
                if not self._timers:
                    return
                top = self._timers[0]
                if top.cancelled:
                    heapq.heappop(self._timers)
                    continue
                if top.when > now:
                    return
                heapq.heappop(self._timers)
            try:
                top.fn()
            except Exception:
                logger.exception("timer callback failed")

    def _accept(self) -> None:
        # accept in a loop until the backlog drains (edge amortization);
        # FaultError subclasses OSError, so an injected accept failure
        # takes the same swallow-and-retry path a real transient accept
        # error does (the pending connection stays in the backlog)
        while True:
            try:
                faults.fault_point("http.accept")
                sock, addr = self.lsock.accept()
            except BlockingIOError:
                return
            except OSError:
                return
            self.app._setup_conn(sock, addr, self)

    def register_conn(self, conn: _Connection) -> None:
        """Park a connection with the selector until it turns readable;
        arm its idle timer. Loop thread only — workers go through
        ``call_soon``."""
        try:
            self.selector.register(conn.sock, selectors.EVENT_READ, conn)
        except (KeyError, ValueError, OSError):
            conn.close()
            return
        conn.idle_timer = self.call_later(
            self.app.read_timeout, lambda: self._idle_close(conn)
        )

    def _idle_close(self, conn: _Connection) -> None:
        # fires only while the conn is parked: if a worker claimed it the
        # unregister below raises KeyError and we leave it alone
        try:
            self.selector.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            return
        conn.close()

    def _on_readable(self, conn: _Connection) -> None:
        try:
            self.selector.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            return
        if conn.idle_timer is not None:
            conn.idle_timer.cancel()
            conn.idle_timer = None
        self.app._submit_conn(conn)

    def _teardown(self) -> None:
        for key in list(self.selector.get_map().values()):
            if isinstance(key.data, _Connection):
                key.data.close()
        try:
            self.selector.close()
        except OSError:
            pass
        try:
            self.lsock.close()
        except OSError:
            pass
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass


class HTTPApp:
    """A router bound to an event-loop front end with start/stop
    lifecycle. Idle keep-alive connections are selector entries (fds);
    only in-flight requests occupy worker threads."""

    def __init__(
        self,
        router: Router,
        host: str = "0.0.0.0",
        port: int = 0,
        ssl_context=None,
        reuse_port: bool = False,
        read_timeout: float = 120.0,
        recv_buffer: bool = True,
        name: str = "server",
        handler_threads: int | None = None,
        ready_check: "Callable[[], str | None] | None" = None,
    ):
        self.router = router
        self.host = host
        self.port = port
        # server role label on this app's request metrics ("eventserver",
        # "engine", ...) — one process can host several HTTPApps (tests)
        self.name = name
        self._m_request = obs_metrics.histogram(
            "pio_http_request_seconds",
            "End-to-end request handling time (read+parse+dispatch+send)",
            server=name,
        )
        self._m_read_parse = obs_metrics.histogram(
            "pio_http_read_parse_seconds",
            "Request read+parse time, excluding keep-alive idle wait",
            server=name,
        )
        self._m_requests = obs_metrics.counter(
            "pio_http_requests_total", "Requests handled", server=name
        )
        self._m_errors = obs_metrics.counter(
            "pio_http_errors_total", "Requests answered with 5xx", server=name
        )
        self._g_conns = obs_metrics.gauge(
            "pio_http_open_connections",
            "Accepted connections currently open (idle + in-flight)",
            server=name,
        )
        # server-side TLS (reference SSLConfiguration sslContext wiring
        # into spray; here an ssl.SSLContext wrapping the accepted socket)
        self.ssl_context = ssl_context
        # per-connection socket timeout: a client that stops sending
        # mid-request (slowloris) releases its worker thread instead of
        # pinning it forever; applies to plain TCP and TLS alike
        self.read_timeout = read_timeout
        # SO_REUSEPORT: N worker PROCESSES bind the same port and the
        # kernel load-balances accepts — the multi-process scale-out
        # path (`--workers`) past the single-interpreter GIL
        self.reuse_port = reuse_port
        # False falls back to the stdlib rfile (BufferedReader) request
        # parse — kept for the bench's before/after http_floor_us
        # comparison and as an escape hatch. Fallback connections stay
        # worker-pinned for their whole life: the BufferedReader may
        # hold pipelined bytes the selector cannot see.
        self.recv_buffer = recv_buffer
        # default 16, overridable per-process via PIO_HTTP_HANDLER_THREADS:
        # the per-replica concurrency cap a scale-out fleet tunes so one
        # replica's slot count — not the host's core count — bounds how
        # many dispatch-bound queries it serves at once
        if handler_threads is None:
            try:
                handler_threads = int(
                    os.environ.get("PIO_HTTP_HANDLER_THREADS", "") or 16
                )
            except ValueError:
                handler_threads = 16
        self.handler_threads = max(1, int(handler_threads))
        self._loop: _EventLoop | None = None
        self._pool = None
        self._thread: threading.Thread | None = None
        self._conns: set[_Connection] = set()
        self._conns_lock = threading.Lock()
        # -- graceful lifecycle (liveness/readiness + drain) --------------
        # per-boot identity: lets a health probe tell THIS instance from
        # a foreign or stale listener on the same port
        self.instance_id = uuid.uuid4().hex[:12]
        # returns None when ready, else a human-readable reason — the
        # server-specific half of /readyz (warmup, model, storage)
        self.ready_check = ready_check
        self._draining = threading.Event()
        self._drain_deadline = float("inf")
        self._shutdown_hooks: list[Callable[[], None]] = []
        self._hooks_ran = False
        self._active = 0  # connections currently inside a worker
        router.add("GET", "/healthz", self._healthz_route)
        router.add("GET", "/readyz", self._readyz_route)

    # -- liveness / readiness ----------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def _healthz_route(self, _req: Request) -> Response:
        """Liveness: the process is up and the loop answers. Returns the
        per-boot instance id so callers can verify WHICH listener
        answered (fixes the raw-TCP foreign-listener TOCTOU)."""
        return Response.json({
            "status": "ok",
            "server": self.name,
            "instance": self.instance_id,
            "pid": os.getpid(),
            "draining": self._draining.is_set(),
        })

    def _readyz_route(self, _req: Request) -> Response:
        """Readiness: warmed up, dependencies reachable, not draining.
        503 with a reason while not ready — load balancers and the
        rolling-restart handoff key off this, not /healthz."""
        reason: str | None = None
        if self._draining.is_set():
            reason = "draining"
        elif self.ready_check is not None:
            try:
                reason = self.ready_check()
            except Exception as exc:
                reason = f"ready_check failed: {exc}"
        doc = {
            "ready": reason is None,
            "server": self.name,
            "instance": self.instance_id,
        }
        if reason is None:
            return Response.json(doc)
        doc["reason"] = reason
        return Response.json(doc, status=503)

    # -- graceful drain ----------------------------------------------------

    def add_shutdown_hook(self, fn: Callable[[], None]) -> None:
        """Register a flush hook (group-commit coalescers, tailer
        cursors, ...) run exactly once after in-flight requests quiesce
        during :meth:`drain`, before the loop stops."""
        self._shutdown_hooks.append(fn)

    def _run_shutdown_hooks(self) -> None:
        with self._conns_lock:
            if self._hooks_ran:
                return
            self._hooks_ran = True
        for fn in self._shutdown_hooks:
            try:
                fn()
            except Exception:
                logger.exception("shutdown hook failed")

    def begin_drain(self, timeout: float | None = None) -> float:
        """Flip into draining (idempotent): stop accepting, fail
        readiness, answer served requests with ``Connection: close``,
        and shed everything past the deadline with 503. Returns the
        monotonic drain deadline; does not block."""
        if self._draining.is_set():
            return self._drain_deadline
        faults.fault_point("http.drain")
        if timeout is None:
            try:
                timeout = float(
                    os.environ.get("PIO_DRAIN_TIMEOUT_S", "") or 10.0
                )
            except ValueError:
                timeout = 10.0
        self._drain_deadline = time.monotonic() + max(0.0, timeout)
        self._draining.set()
        loop = self._loop
        if loop is not None:
            loop.call_soon(loop.close_listener)
        return self._drain_deadline

    def drain(self, timeout: float | None = None) -> None:
        """Graceful shutdown: :meth:`begin_drain`, wait (bounded by the
        deadline) for in-flight and parked keep-alive requests to
        finish, run the shutdown hooks, then :meth:`stop`."""
        deadline = self.begin_drain(timeout)
        while time.monotonic() < deadline:
            with self._conns_lock:
                quiesced = self._active == 0 and not self._conns
            if quiesced:
                break
            time.sleep(0.02)
        self._run_shutdown_hooks()
        self.stop()

    def _install_signal_drain(self) -> None:
        """SIGTERM -> drain -> clean loop exit (exit 0). Foreground
        (main-thread) servers only: signal handlers cannot be installed
        elsewhere."""
        if threading.current_thread() is not threading.main_thread():
            return

        def _on_term(signum, frame):
            threading.Thread(
                target=self._drain_for_signal,
                daemon=True,
                name=f"pio-drain-{self.name}",
            ).start()

        try:
            signal_mod.signal(signal_mod.SIGTERM, _on_term)
        except (ValueError, OSError):  # pragma: no cover
            pass

    def _drain_for_signal(self) -> None:
        try:
            self.drain()
        except Exception:
            logger.exception("graceful drain failed; stopping hard")
            self.stop()

    # -- timer wheel (shared clock for query deadlines etc.) ---------------

    def call_later(self, delay: float, fn) -> _TimerHandle | None:
        """Schedule ``fn`` on the event loop's timer wheel. Returns a
        cancellable handle, or None when the loop isn't running (caller
        falls back to its own clock)."""
        loop = self._loop
        if loop is None or loop._stopping:
            return None
        return loop.call_later(delay, fn)

    # -- connection plumbing ----------------------------------------------

    def _track(self, conn: _Connection) -> None:
        with self._conns_lock:
            self._conns.add(conn)
            self._g_conns.set(float(len(self._conns)))

    def _untrack(self, conn: _Connection) -> None:
        with self._conns_lock:
            self._conns.discard(conn)
            self._g_conns.set(float(len(self._conns)))

    def _conn_count(self) -> int:
        with self._conns_lock:
            return len(self._conns)

    def _setup_conn(self, sock, addr, loop: _EventLoop) -> None:
        """Accept-path setup (loop thread): timeouts, TCP_NODELAY, and —
        with TLS — wrap WITHOUT handshaking: the handshake happens lazily
        on first read in the worker thread, so a silent client (TCP
        health probe) can't stall the accept loop."""
        try:
            sock.settimeout(self.read_timeout)
            # TCP_NODELAY: Nagle held small JSON responses back ~5ms a
            # request (measured 171 -> 1287 rps on keep-alive ingest)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            if self.ssl_context is not None:
                sock = self.ssl_context.wrap_socket(
                    sock, server_side=True, do_handshake_on_connect=False
                )
        except OSError:
            try:
                sock.close()
            except OSError:
                pass
            return
        conn = _Connection(self, sock, addr)
        self._track(conn)
        loop.register_conn(conn)

    def _submit_conn(self, conn: _Connection) -> None:
        pool = self._pool
        if pool is None:
            conn.close()
            return
        try:
            pool.submit(self._serve_conn, conn)
        except RuntimeError:  # pool shut down
            conn.close()

    def _serve_conn(self, conn: _Connection) -> None:
        """Worker entry: serve requests until the connection closes, a
        read would block (hand back to the selector), or — for rfile
        fallback connections — forever (worker-pinned, the old
        thread-per-connection behavior)."""
        loop = self._loop
        with self._conns_lock:
            self._active += 1
        try:
            while True:
                conn.handle_one_request()
                if conn.close_connection:
                    conn.close()
                    return
                if not self.recv_buffer:
                    continue  # worker-pinned fallback
                if conn.buffered():
                    continue  # pipelined request already in hand
                if self._linger(conn):
                    continue  # next request arrived within the linger
                if loop is None or loop._stopping:
                    conn.close()
                    return
                loop.call_soon(lambda: loop.register_conn(conn))
                return
        except OSError:
            conn.close()  # client reset / write timeout: routine
        except Exception:
            logger.exception("connection worker failed")
            conn.close()
        finally:
            with self._conns_lock:
                self._active -= 1

    # linger: when the server isn't fan-out loaded, blocking briefly on
    # the just-served socket keeps a busy keep-alive client at
    # thread-per-connection latency (no selector hop between requests).
    # Bounded so at most half the pool can be pinned lingering; past
    # that connection count the server is in event-driven mode.
    _LINGER_S = 0.02

    def _linger(self, conn: _Connection) -> bool:
        if self._conn_count() > max(2, self.handler_threads // 2):
            return False
        try:
            r, _, _ = select_mod.select([conn.sock], [], [], self._LINGER_S)
        except (OSError, ValueError):
            return False
        return bool(r)

    # -- lifecycle ---------------------------------------------------------

    def _bind(self) -> socket.socket:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if self.reuse_port:
                try:
                    # set SO_REUSEPORT explicitly rather than relying on
                    # socketserver.allow_reuse_port (3.11+ only)
                    sock.setsockopt(
                        socket.SOL_SOCKET, socket.SO_REUSEPORT, 1
                    )
                except (AttributeError, OSError):  # pragma: no cover
                    pass  # platform without SO_REUSEPORT
            sock.bind((self.host, self.port))
            sock.listen(1024)
            sock.setblocking(False)
        except BaseException:
            sock.close()
            raise
        return sock

    def start(self, background: bool = True) -> int:
        """Bind and serve. Returns the bound port."""
        from concurrent.futures import ThreadPoolExecutor

        if self.reuse_port and self.port == 0:
            raise ValueError(
                "reuse_port workers need an explicit --port (the "
                "kernel balances accepts across same-port listeners)"
            )
        lsock = self._bind()
        self.port = lsock.getsockname()[1]
        self._pool = ThreadPoolExecutor(
            max_workers=self.handler_threads,
            thread_name_prefix=f"http-{self.name}",
        )
        self._loop = _EventLoop(self, lsock)
        if background:
            self._thread = threading.Thread(
                target=self._loop.run, daemon=True, name=f"httploop-{self.name}"
            )
            self._thread.start()
        else:
            # foreground servers own the process: SIGTERM drains before
            # the loop exits, so the command returns 0 after a clean
            # shutdown instead of dying mid-response
            self._install_signal_drain()
            try:
                self._loop.run()
            except KeyboardInterrupt:
                pass
            finally:
                self.stop()
        return self.port

    def stop(self) -> None:
        loop, self._loop = self._loop, None
        if loop is None:
            return
        loop.stop()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5)
        self._thread = None
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
