"""Server plugin interfaces and discovery.

Parity with the reference's ServiceLoader-based plugin systems:
- EventServerPlugin (inputblocker/inputsniffer): intercepts events at
  ingestion (data/.../api/EventServerPlugin.scala),
- EngineServerPlugin (outputblocker/outputsniffer): transforms or
  observes query responses (core/.../workflow/EngineServerPlugin.scala).

Java ServiceLoader discovery becomes dotted-name loading from the
``PIO_PLUGINS`` env var (comma-separated ``module:Class`` or
``module.Class``) plus programmatic registration.
"""

from __future__ import annotations

import importlib
import logging
import os
from typing import Any

logger = logging.getLogger(__name__)

INPUT_BLOCKER = "inputblocker"
INPUT_SNIFFER = "inputsniffer"
OUTPUT_BLOCKER = "outputblocker"
OUTPUT_SNIFFER = "outputsniffer"


class EventServerPlugin:
    """Override ``process`` (blockers may raise to reject, return a
    modified event dict to rewrite) and/or ``handle_rest``."""

    plugin_name = "plugin"
    plugin_description = ""
    plugin_type = INPUT_SNIFFER

    def start(self, context: dict[str, Any]) -> None: ...

    def process(self, event_json: dict[str, Any], context: dict[str, Any]):
        return event_json

    def handle_rest(self, path: str, params: dict[str, str]) -> Any:
        return {}


class EngineServerPlugin:
    plugin_name = "plugin"
    plugin_description = ""
    plugin_type = OUTPUT_SNIFFER

    def start(self, context: dict[str, Any]) -> None: ...

    def process(
        self,
        engine_variant: str,
        query: dict[str, Any],
        result: Any,
        context: dict[str, Any],
    ):
        return result

    def handle_rest(self, arguments: dict[str, Any]) -> Any:
        return {}


def load_plugins(base_class: type, env_var: str = "PIO_PLUGINS") -> list[Any]:
    """Instantiate plugins of the given kind named in ``env_var``."""
    plugins: list[Any] = []
    spec = os.environ.get(env_var, "")
    for entry in filter(None, (s.strip() for s in spec.split(","))):
        module_name, _, attr = entry.replace(":", ".").rpartition(".")
        try:
            cls = getattr(importlib.import_module(module_name), attr)
        except Exception:
            logger.exception("cannot load plugin %s", entry)
            continue
        if isinstance(cls, type) and issubclass(cls, base_class):
            plugins.append(cls())
        else:
            logger.warning("%s is not a %s; skipped", entry, base_class.__name__)
    return plugins
