"""HTTP storage service: the client-server storage backend's server side.

The reference's production deployment serves all three repositories from
a client-server SQL database (JDBC Postgres/MySQL —
storage/jdbc/.../JDBCLEvents.scala:37, Storage.scala:218-233) so the
event server, trainer, and engine server on DIFFERENT hosts share state.
This service provides the same property for the TPU framework: it wraps
a local Storage (sqlite DAOs by default) and exposes every DAO method
over HTTP; remote processes configure the ``http`` backend
(data/storage/httpstorage.py) with this service's URL and get the full
registry capability set — no shared filesystem required.

Protocol: ``POST /rpc/<repo>/<method>`` with a JSON body
``{"args": [...], "kwargs": {...}}`` encoded by data/storage/wire.py;
responds ``{"result": ...}`` or ``{"error": <ExceptionName>,
"message": ...}``. Method names are allowlisted against the public
surface of the DAO base classes — nothing else is callable. Optional
shared-key auth via the ``x-pio-storage-key`` header (the credential
analog of the reference's JDBC username/password).
"""

from __future__ import annotations

import logging
from typing import Any, Callable

from predictionio_tpu.data.event import EventValidationError
from predictionio_tpu.data.storage import Storage, base, get_storage, wire
from predictionio_tpu.server.http import HTTPApp, Request, Response, Router

logger = logging.getLogger(__name__)

# repo name on the wire -> (Storage accessor, DAO base class)
_REPOS: dict[str, tuple[str, type]] = {
    "apps": ("get_metadata_apps", base.Apps),
    "access_keys": ("get_metadata_access_keys", base.AccessKeys),
    "channels": ("get_metadata_channels", base.Channels),
    "engine_instances": ("get_metadata_engine_instances", base.EngineInstances),
    "evaluation_instances": (
        "get_metadata_evaluation_instances",
        base.EvaluationInstances,
    ),
    "events": ("get_events", base.Events),
    "models": ("get_model_data_models", base.Models),
}


def _public_methods(cls: type) -> frozenset[str]:
    return frozenset(
        name
        for name in dir(cls)
        if not name.startswith("_") and callable(getattr(cls, name, None))
    )


_ALLOWED: dict[str, frozenset[str]] = {
    repo: _public_methods(cls) | frozenset(wire.EXTENSION_METHODS.get(repo, ()))
    for repo, (_, cls) in _REPOS.items()
}


class StorageServer:
    """Serves a Storage's DAOs over HTTP (see module docstring)."""

    def __init__(
        self,
        storage: Storage | None = None,
        host: str = "0.0.0.0",
        port: int = 7072,
        auth_key: str | None = None,
        server_config=None,
    ):
        self.storage = storage or get_storage()
        self.auth_key = auth_key
        self.host = host
        self.app = HTTPApp(
            self._router(),
            host=host,
            port=port,
            ssl_context=(
                server_config.ssl_context() if server_config is not None else None
            ),
        )

    def _router(self) -> Router:
        router = Router()

        @router.route("GET", "/")
        def status(request: Request) -> Response:
            return Response.json(
                {
                    "status": "alive",
                    "service": "pio storage server",
                    "repos": sorted(_REPOS),
                }
            )

        @router.route("POST", "/rpc/<repo>/<method>")
        def rpc(request: Request) -> Response:
            denied = self._check_auth(request)
            if denied is not None:
                return denied
            repo = request.path_params["repo"]
            method = request.path_params["method"]
            if repo not in _REPOS:
                return Response.error(f"unknown repository {repo}", 404)
            if method not in _ALLOWED[repo]:
                return Response.error(
                    f"method {method} not allowed on {repo}", 403
                )
            accessor, _ = _REPOS[repo]
            dao = getattr(self.storage, accessor)()
            if not callable(getattr(dao, method, None)):
                return Response.error(
                    f"backend does not implement {repo}.{method}", 403
                )
            payload = request.json() or {}
            args = [wire.decode(a) for a in payload.get("args", [])]
            kwargs = {k: wire.decode(v) for k, v in payload.get("kwargs", {}).items()}
            try:
                result = getattr(dao, method)(*args, **kwargs)
            except (EventValidationError, ValueError, KeyError, TypeError) as e:
                return Response.json(
                    {"error": type(e).__name__, "message": str(e)}, status=400
                )
            except Exception as e:  # backend failure: 500 with the class
                logger.exception("storage rpc %s.%s failed", repo, method)
                return Response.json(
                    {"error": type(e).__name__, "message": str(e)}, status=500
                )
            return Response.json({"result": wire.encode(result)})

        @router.route("POST", "/bulk/import")
        def bulk_import(request: Request) -> Response:
            """Raw JSONL splice import over the wire: the request body IS
            the line blob (no per-event wire encoding, no base64
            inflation), appended through the backing store's
            append_jsonl. The clients' HTTPEvents.append_jsonl — `pio
            import` against an http storage source lands here."""
            denied = self._check_auth(request)
            if denied is not None:
                return denied
            dao = self.storage.get_events()
            splice = getattr(dao, "append_jsonl", None)
            if splice is None:
                return Response.error(
                    "backend does not implement append_jsonl", 403
                )
            try:
                app_id = int(request.query["app_id"])
            except (KeyError, ValueError):
                return Response.error(
                    "app_id query param required (integer)", 400
                )
            try:
                channel_id = (
                    int(request.query["channel_id"])
                    if request.query.get("channel_id")
                    else None
                )
            except ValueError:
                return Response.error(
                    "channel_id query param must be an integer", 400
                )
            blob = request.body
            declared = request.headers.get("content-length")
            if declared is not None and int(declared) != len(blob):
                # a dropped client connection yields a short read; an
                # appended truncated line would corrupt the log for
                # every later replay
                return Response.error("truncated request body", 400)
            if not blob:
                return Response.error("empty body", 400)
            # the server is the trust boundary for append_jsonl's
            # contract (scanner-clean lines, eventId on every line):
            # clients validate, but a corrupt blob committed verbatim
            # would poison the app's whole log
            from predictionio_tpu import native

            if not native.native_available():
                # degraded mode can't validate spans; per-event RPC
                # (which fully parses) is the safe path
                return Response.error(
                    "splice import unavailable without the native codec",
                    403,
                )
            probe = blob if blob.endswith(b"\n") else blob + b"\n"
            sc = native.scan_events(probe)
            nonempty = (sc.flags & native.FLAG_EMPTY) == 0
            if (
                (((sc.flags & native.FLAG_FALLBACK) != 0) & nonempty).any()
                or ((sc.offs[:, native.F_EVENT_ID] < 0) & nonempty).any()
            ):
                return Response.error(
                    "body must be scanner-clean JSONL with an eventId "
                    "on every line",
                    400,
                )
            # replay-safety: committing a line that later fails
            # Event.from_dict (missing event/entityType/entityId, or an
            # unparseable eventTime/creationTime) would brick every
            # find()/export of this (app, channel) with
            # EventValidationError. The CLI client checks this before
            # sending (_splice_import_chunk), but the server is the
            # trust boundary — mirror the predicate here.
            if b'"$delete"' in blob:
                # a top-level {"$delete": id} key acts as a jsonl delete
                # MARKER on replay — deleting an attacker-chosen
                # existing event. Inside a JSON string the quote would
                # be escaped (\"), so any raw occurrence of these bytes
                # is a key; the client routes such lines to the
                # per-event RPC path (cli/commands.py), never a splice
                # blob — reject the blob outright.
                return Response.error(
                    'splice blobs may not carry "$delete" markers; use '
                    "the delete RPC or per-event import",
                    400,
                )
            import numpy as np

            offs, lens = sc.offs, sc.lens
            ok = np.ones(len(sc), dtype=bool)
            for f in (native.F_EVENT, native.F_ENTITY_TYPE, native.F_ENTITY_ID):
                ok &= (offs[:, f] >= 0) & (lens[:, f] > 0)
            ok &= offs[:, native.F_EVENT_TIME] >= 0
            ok &= ~np.isnan(
                native.parse_times(
                    probe,
                    offs[:, native.F_EVENT_TIME],
                    lens[:, native.F_EVENT_TIME],
                )
            )
            ct = offs[:, native.F_CREATION_TIME] >= 0
            ok &= ~ct | ~np.isnan(
                native.parse_times(
                    probe,
                    offs[:, native.F_CREATION_TIME],
                    lens[:, native.F_CREATION_TIME],
                )
            )
            if (~ok & nonempty).any():
                bad = int(np.flatnonzero(~ok & nonempty)[0])
                return Response.error(
                    "line %d would poison the log on replay: every line "
                    "needs non-empty event/entityType/entityId and a "
                    "parseable eventTime (and creationTime when present)"
                    % (bad + 1),
                    400,
                )
            try:
                splice(blob, app_id, channel_id)
            except (EventValidationError, ValueError, KeyError, TypeError) as e:
                return Response.json(
                    {"error": type(e).__name__, "message": str(e)}, status=400
                )
            except Exception as e:
                logger.exception("bulk import failed")
                return Response.json(
                    {"error": type(e).__name__, "message": str(e)}, status=500
                )
            return Response.json({"ok": True})

        @router.route("POST", "/bulk/export")
        def bulk_export(request: Request) -> Response:
            """Stream an app's events as raw JSONL — the splice export
            over the wire (clients' HTTPEvents.export_jsonl). The
            backing store exports into a spooled temp file (bounded
            server RSS; spills to disk past 64MB) which streams out in
            chunks with the record count in a header."""
            denied = self._check_auth(request)
            if denied is not None:
                return denied
            payload = request.json() or {}
            dao = self.storage.get_events()
            fast = getattr(dao, "export_jsonl", None)
            if fast is None:
                return Response.error(
                    "backend does not implement export_jsonl", 403
                )
            import tempfile

            spool = tempfile.SpooledTemporaryFile(max_size=64 << 20)
            try:
                n = fast(
                    int(payload["app_id"]),
                    (
                        int(payload["channel_id"])
                        if payload.get("channel_id") is not None
                        else None
                    ),
                    spool,
                )
            except Exception as e:
                spool.close()
                logger.exception("bulk export failed")
                return Response.json(
                    {"error": type(e).__name__, "message": str(e)}, status=500
                )
            if n is None:
                # chained-remote case: the backing store is itself an
                # http backend whose service lacks the capability
                spool.close()
                return Response.error(
                    "backend does not implement export_jsonl", 403
                )
            spool.seek(0)

            def chunks():
                try:
                    while True:
                        b = spool.read(8 << 20)
                        if not b:
                            return
                        yield b
                finally:
                    spool.close()

            return Response(
                200,
                ("application/x-ndjson", chunks()),
                headers={"X-Pio-Record-Count": str(n)},
            )

        return router

    def _check_auth(self, request: Request) -> Response | None:
        """Shared x-pio-storage-key gate for every route; None = allowed."""
        if self.auth_key is not None:
            if request.headers.get("x-pio-storage-key") != self.auth_key:
                return Response.error("invalid storage key", 401)
        return None

    def start(self, background: bool = True) -> int:
        port = self.app.start(background=background)
        logger.info("Storage Server listening on %s:%d", self.host, port)
        return port

    def stop(self) -> None:
        self.app.stop()
