"""Event-server ingestion statistics.

Parity with the reference Stats/StatsActor
(data/.../api/Stats.scala:43-82, api/StatsActor.scala:36): per-minute
buckets counting (appId, event name, entityType, status) served at
``/stats.json`` when stats are enabled.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from dataclasses import dataclass


@dataclass(frozen=True)
class _Key:
    app_id: int
    status: int
    event: str
    entity_type: str


class Stats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        # minute bucket -> key -> count
        self._buckets: dict[int, dict[_Key, int]] = defaultdict(
            lambda: defaultdict(int)
        )
        # per-app accepted-write sequence + last ingest wall time: the
        # authoritative upstream numbers a realtime tailer's
        # events_behind / seconds_behind gauges compare against
        self._seq: dict[int, int] = defaultdict(int)
        self._last_ingest: dict[int, float] = {}
        self.start_time = time.time()

    def update(self, app_id: int, status: int, event: str, entity_type: str) -> None:
        minute = int(time.time() // 60)
        with self._lock:
            self._buckets[minute][_Key(app_id, status, event, entity_type)] += 1
            if status == 201:  # accepted write
                self._seq[app_id] += 1
                self._last_ingest[app_id] = time.time()

    def get(self, app_id: int) -> dict:
        """Aggregate counts for one app across all buckets
        (the reference reports previous-minute and cumulative views;
        cumulative is what its tests assert on)."""
        with self._lock:
            agg: dict[tuple, int] = defaultdict(int)
            for bucket in self._buckets.values():
                for key, count in bucket.items():
                    if key.app_id == app_id:
                        agg[(key.status, key.event, key.entity_type)] += count
            seq = self._seq.get(app_id, 0)
            last_ingest = self._last_ingest.get(app_id)
        return {
            "startTime": self.start_time,
            "statusCount": _group(agg, 0),
            "eventCount": _group(agg, 1),
            "entityTypeCount": _group(agg, 2),
            "lastEventSeq": seq,
            "lastIngestTime": last_ingest,
        }


def _group(agg: dict[tuple, int], ix: int) -> dict:
    out: dict = defaultdict(int)
    for key, count in agg.items():
        out[str(key[ix])] += count
    return dict(out)
