"""Event-server ingestion statistics.

Parity with the reference Stats/StatsActor
(data/.../api/Stats.scala:43-82, api/StatsActor.scala:36): per-minute
buckets counting (appId, event name, entityType, status) served at
``/stats.json`` when stats are enabled.

Unlike the reference (which grew its minute map forever), the live
window is BOUNDED: only the newest ``retention_minutes`` buckets are
kept and anything older is folded into a cumulative per-key total on
the way out, so a long-running event server holds ~24 h of minute
resolution at a fixed memory ceiling while ``get()`` still reports
exact all-time counts.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from dataclasses import dataclass

# 24 h of minute buckets — the window an operator actually inspects;
# everything older collapses to one cumulative dict
DEFAULT_RETENTION_MINUTES = 1440


@dataclass(frozen=True)
class _Key:
    app_id: int
    status: int
    event: str
    entity_type: str


class Stats:
    def __init__(self, retention_minutes: int = DEFAULT_RETENTION_MINUTES) -> None:
        self.retention_minutes = int(retention_minutes)
        self._lock = threading.Lock()
        # minute bucket -> key -> count (live window)
        self._buckets: dict[int, dict[_Key, int]] = defaultdict(
            lambda: defaultdict(int)
        )
        # counts folded out of expired buckets; totals stay exact
        self._cumulative: dict[_Key, int] = defaultdict(int)
        # per-app accepted-write sequence + last ingest wall time: the
        # authoritative upstream numbers a realtime tailer's
        # events_behind / seconds_behind gauges compare against
        self._seq: dict[int, int] = defaultdict(int)
        self._last_ingest: dict[int, float] = {}
        self.start_time = time.time()

    def update(self, app_id: int, status: int, event: str, entity_type: str) -> None:
        now = time.time()
        minute = int(now // 60)
        with self._lock:
            self._buckets[minute][_Key(app_id, status, event, entity_type)] += 1
            if status == 201:  # accepted write
                self._seq[app_id] += 1
                self._last_ingest[app_id] = now
            self._fold_expired_locked(minute)

    def _fold_expired_locked(self, current_minute: int) -> None:
        """Fold buckets older than the retention window into the
        cumulative totals. Amortized O(1): traffic creates at most one
        new bucket per minute, so at most one usually expires per call —
        the loop only runs long after an idle gap, and then once."""
        horizon = current_minute - self.retention_minutes
        while self._buckets:
            oldest = min(self._buckets)
            if oldest > horizon:
                break
            for key, count in self._buckets.pop(oldest).items():
                self._cumulative[key] += count

    def bucket_count(self) -> int:
        with self._lock:
            return len(self._buckets)

    def history_series(self) -> dict:
        """The live minute buckets in the :mod:`obs.history` read shape:
        ``{series_key: {"kind": "delta", "points": [[t_ms, count], ...]}}``.
        Each point is one minute's accepted/rejected count per (app,
        status) — the event server registers this as a history provider,
        so ``/history.json`` (and the dashboard sparklines, ``pio top``)
        show ingest alongside the sampled process metrics. A point's
        timestamp is the minute's END (the bucket is complete then),
        matching the delta convention of sampled counters."""
        per_series: dict[str, list] = defaultdict(list)
        with self._lock:
            for minute in sorted(self._buckets):
                agg: dict[tuple[int, int], int] = defaultdict(int)
                for key, count in self._buckets[minute].items():
                    agg[(key.app_id, key.status)] += count
                for (app_id, status), count in sorted(agg.items()):
                    series = (
                        f'pio_stats_events{{app="{app_id}",status="{status}"}}'
                    )
                    per_series[series].append([(minute + 1) * 60_000, count])
        return {
            k: {"kind": "delta", "points": pts} for k, pts in per_series.items()
        }

    def get(self, app_id: int) -> dict:
        """Aggregate counts for one app: cumulative folded totals plus
        every live bucket (the reference reports previous-minute and
        cumulative views; cumulative is what its tests assert on)."""
        with self._lock:
            agg: dict[tuple, int] = defaultdict(int)
            for key, count in self._cumulative.items():
                if key.app_id == app_id:
                    agg[(key.status, key.event, key.entity_type)] += count
            for bucket in self._buckets.values():
                for key, count in bucket.items():
                    if key.app_id == app_id:
                        agg[(key.status, key.event, key.entity_type)] += count
            seq = self._seq.get(app_id, 0)
            last_ingest = self._last_ingest.get(app_id)
        return {
            "startTime": self.start_time,
            "statusCount": _group(agg, 0),
            "eventCount": _group(agg, 1),
            "entityTypeCount": _group(agg, 2),
            "lastEventSeq": seq,
            "lastIngestTime": last_ingest,
        }


def _group(agg: dict[tuple, int], ix: int) -> dict:
    out: dict = defaultdict(int)
    for key, count in agg.items():
        out[str(key[ix])] += count
    return dict(out)
