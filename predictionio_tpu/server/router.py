"""Scale-out serving router: one front tier over N engine replicas.

``pio route`` binds this server in front of a replica set of engine
servers (spawned by ``pio start-all --replicas N`` on consecutive
ports) and spreads ``/queries.json`` — bare, ``/<variant>/queries.json``
path-prefix, and ``X-PIO-Variant`` header forms — across them. The
pieces, in the order a request meets them:

- **Affinity**: the routing key is the canonical query byte string
  (``server/query_cache.canonical_query_bytes`` — the same bytes that
  key the replica-side query cache), salted with the variant name, on a
  consistent-hash ring with virtual nodes. A given query always prefers
  the same replica, so each replica's query cache and pow2 jit buckets
  stay hot instead of every replica holding a cold copy of everything.
- **Spill**: when the preferred replica is ejected or saturated
  (inflight >= ``PIO_ROUTER_SATURATION``), the router spills to
  power-of-two-choices least-inflight among the available replicas —
  bounded herding without a global scan.
- **Health**: a probe thread polls each replica's ``/readyz`` every
  ``PIO_ROUTER_PROBE_INTERVAL_S``. The per-boot instance id in the
  probe doc makes membership explicit: a restarted replica (new
  instance) is admitted as a NEW member with fresh stats, immediately —
  it is not the flaky old one still serving its ejection backoff.
  Passively, any connect error / injected fault / 5xx ejects the
  replica breaker-style with seeded exponential backoff
  (``common/breaker.backoff_interval``); re-admission requires both the
  backoff to expire AND a ready probe.
- **Retry**: a failed attempt is retried on a different replica (up to
  ``PIO_ROUTER_RETRIES`` extra attempts), counted in
  ``pio_router_retries_total``. Replica 4xx responses (invalid query,
  unknown variant) are NOT failures — they pass through byte-identical,
  so multi-tenant clients cannot tell the router from a replica.
- **Hedging**: queries are read-only, so after an adaptive delay (the
  observed latency quantile ``PIO_ROUTER_HEDGE_QUANTILE``, clamped to
  [``PIO_ROUTER_HEDGE_MIN_MS``, ``PIO_ROUTER_HEDGE_MAX_MS``]) the
  router fires ONE duplicate attempt at a different replica and the
  first response wins — the Tail-at-Scale move that turns a straggling
  replica's p99 into roughly the healthy p95 + a healthy-replica
  round trip. ``PIO_ROUTER_HEDGE=0`` disables.

Observability: ``pio_router_{requests,retries,hedges,hedge_wins,
ejections}_total``, per-replica inflight/p99/ready gauges, a hedge
win-ratio gauge, availability + p99 SLOs
(``obs/slo.install_router_slos``), and a ``/stats.json`` replicas
block that ``pio status``/``pio top`` render as per-replica sub-rows.
"""

from __future__ import annotations

import bisect
import collections
import hashlib
import json
import logging
import os
import random
import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import wait as futures_wait
from http.client import HTTPConnection

from predictionio_tpu import faults
from predictionio_tpu.common.breaker import backoff_interval
from predictionio_tpu.obs import metrics as obs_metrics
from predictionio_tpu.obs import slo as obs_slo
from predictionio_tpu.server.http import (
    HTTPApp,
    Request,
    Response,
    Router,
    add_obs_routes,
)
from predictionio_tpu.server.query_cache import canonical_query_bytes

logger = logging.getLogger(__name__)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


# replica states
UNPROBED = "unprobed"  # boot: never answered a probe yet
READY = "ready"
EJECTED = "ejected"

_VNODES = 64  # virtual nodes per replica on the hash ring
_LATENCY_WINDOW = 512  # per-replica success-latency samples kept


def _hash64(data: bytes) -> int:
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "big")


class _AttemptError(Exception):
    """One forwarding attempt failed. Carries the replica 5xx response
    (if there was one) so an all-replicas-down request can still pass
    the last real answer — e.g. a 503 warming fence with its
    Retry-After — through to the client."""

    def __init__(self, reason: str, status: int = 0, body: bytes = b"",
                 headers: dict[str, str] | None = None):
        super().__init__(reason)
        self.status = status
        self.body = body
        self.headers = headers or {}


class Replica:
    """One engine-server backend: address, probed identity, breaker
    state, and a small keep-alive connection pool."""

    def __init__(self, name: str, host: str, port: int, *, seed: int = 0,
                 timeout_s: float = 30.0):
        self.name = name
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.instance: str | None = None
        self.state = UNPROBED
        self.inflight = 0
        self.requests = 0
        self.failures = 0
        self.ejections = 0
        self.eject_attempt = 0
        self.retry_at = 0.0  # monotonic; re-admission gate while ejected
        self.latencies: collections.deque[float] = collections.deque(
            maxlen=_LATENCY_WINDOW
        )
        # per-replica RNG: deterministic backoff jitter under a fixed
        # pool seed, decorrelated across replicas
        self.rng = random.Random(seed ^ _hash64(name.encode()))
        self._conns: list[HTTPConnection] = []
        self._conns_lock = threading.Lock()
        self._g_inflight = obs_metrics.gauge(
            "pio_router_replica_inflight",
            "Requests in flight at this replica via the router",
            replica=name,
        )
        self._g_p99 = obs_metrics.gauge(
            "pio_router_replica_p99_ms",
            "Observed p99 forward latency to this replica (ms)",
            replica=name,
        )
        self._g_ready = obs_metrics.gauge(
            "pio_router_replica_ready",
            "1 when the replica is admitted, 0 when ejected/unprobed",
            replica=name,
        )
        self._g_ready.set(0.0)

    # -- connection pool ---------------------------------------------------

    def acquire(self) -> HTTPConnection:
        with self._conns_lock:
            if self._conns:
                return self._conns.pop()
        return HTTPConnection(self.host, self.port, timeout=self.timeout_s)

    def release(self, conn: HTTPConnection) -> None:
        with self._conns_lock:
            if len(self._conns) < 64:
                self._conns.append(conn)
                return
        conn.close()

    def close_conns(self) -> None:
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except Exception:
                pass

    # -- stats -------------------------------------------------------------

    def p99_ms(self) -> float:
        if not self.latencies:
            return 0.0
        xs = sorted(self.latencies)
        return xs[min(len(xs) - 1, int(0.99 * len(xs)))] * 1e3

    def stats(self) -> dict:
        return {
            "host": self.host,
            "port": self.port,
            "instance": self.instance,
            "state": self.state,
            "inflight": self.inflight,
            "requests": self.requests,
            "failures": self.failures,
            "ejections": self.ejections,
            "p99Ms": round(self.p99_ms(), 3),
        }


class ReplicaPool:
    """The membership + balancing core: consistent-hash ring with
    pow2-choices spill, passive breaker ejection, active instance-aware
    re-admission. All state transitions run under one lock — the
    per-request work inside it is a ring lookup and counter bumps."""

    def __init__(self, replicas: list[Replica], *, seed: int = 0,
                 saturation: int | None = None,
                 eject_base_s: float | None = None,
                 eject_max_s: float | None = None,
                 clock=time.monotonic):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.replicas = list(replicas)
        self.by_name = {r.name: r for r in self.replicas}
        if len(self.by_name) != len(self.replicas):
            raise ValueError("duplicate replica names")
        self.saturation = (
            saturation if saturation is not None
            else _env_int("PIO_ROUTER_SATURATION", 32)
        )
        self.eject_base_s = (
            eject_base_s if eject_base_s is not None
            else _env_float("PIO_ROUTER_EJECT_BACKOFF_S", 0.5)
        )
        self.eject_max_s = (
            eject_max_s if eject_max_s is not None
            else _env_float("PIO_ROUTER_EJECT_MAX_S", 30.0)
        )
        self.clock = clock
        self.lock = threading.Lock()
        self.rng = random.Random(seed)
        self._m_ejections = {
            r.name: obs_metrics.counter(
                "pio_router_ejections_total",
                "Replica ejections (passive failure or failed probe)",
                replica=r.name,
            )
            for r in self.replicas
        }
        # the ring is keyed on replica NAME (stable across restarts):
        # a respawned replica takes back the same hash ranges, so the
        # keys it re-warms are the keys it will keep serving
        points: list[tuple[int, str]] = []
        for r in self.replicas:
            for v in range(_VNODES):
                points.append((_hash64(f"{r.name}#{v}".encode()), r.name))
        points.sort()
        self._ring_points = [p for p, _ in points]
        self._ring_names = [n for _, n in points]

    # -- selection ---------------------------------------------------------

    def _ring_order(self, key: bytes):
        """Distinct replicas in ring order starting at the key's point."""
        start = bisect.bisect_left(self._ring_points, _hash64(key))
        seen: set[str] = set()
        n = len(self._ring_names)
        for i in range(n):
            name = self._ring_names[(start + i) % n]
            if name not in seen:
                seen.add(name)
                yield self.by_name[name]

    def pick(self, key: bytes, exclude: frozenset | set = frozenset()
             ) -> Replica | None:
        """The routing decision: hash-preferred replica when it is
        admitted and unsaturated, else pow2-choices least-inflight among
        the admitted replicas. None when nothing is admitted."""
        with self.lock:
            avail = [
                r for r in self.replicas
                if r.state == READY and r.name not in exclude
            ]
            if not avail:
                return None
            avail_names = {r.name for r in avail}
            for r in self._ring_order(key):
                if r.name in avail_names:
                    if r.inflight < self.saturation:
                        return r
                    break  # preferred is saturated: spill
            if len(avail) == 1:
                return avail[0]
            a, b = self.rng.sample(avail, 2)
            return a if a.inflight <= b.inflight else b

    def pick_other(self, exclude: frozenset | set) -> Replica | None:
        """Least-inflight admitted replica outside ``exclude`` — the
        hedge/retry target (affinity is pointless on a duplicate)."""
        with self.lock:
            avail = [
                r for r in self.replicas
                if r.state == READY and r.name not in exclude
            ]
            if not avail:
                return None
            if len(avail) == 1:
                return avail[0]
            a, b = self.rng.sample(avail, 2)
            return a if a.inflight <= b.inflight else b

    def available_count(self, exclude: frozenset | set = frozenset()) -> int:
        with self.lock:
            return sum(
                1 for r in self.replicas
                if r.state == READY and r.name not in exclude
            )

    # -- accounting --------------------------------------------------------

    def begin(self, replica: Replica) -> None:
        with self.lock:
            replica.inflight += 1
            replica.requests += 1
            replica._g_inflight.set(float(replica.inflight))

    def record_success(self, replica: Replica, elapsed_s: float) -> None:
        with self.lock:
            replica.inflight -= 1
            replica._g_inflight.set(float(replica.inflight))
            replica.latencies.append(elapsed_s)
            replica._g_p99.set(replica.p99_ms())
            # real traffic succeeding resets the breaker escalation
            replica.eject_attempt = 0

    def record_failure(self, replica: Replica, reason: str) -> None:
        with self.lock:
            replica.inflight -= 1
            replica._g_inflight.set(float(replica.inflight))
            self._eject_locked(replica, reason)

    def _eject_locked(self, replica: Replica, reason: str) -> None:
        replica.failures += 1
        if replica.state == EJECTED:
            return  # already serving its backoff; don't escalate per-probe
        replica.state = EJECTED
        replica.ejections += 1
        replica.eject_attempt += 1
        backoff = backoff_interval(
            replica.eject_attempt,
            base_s=self.eject_base_s,
            max_s=self.eject_max_s,
            jitter=0.2,
            rng=replica.rng,
        )
        replica.retry_at = self.clock() + backoff
        replica._g_ready.set(0.0)
        self._m_ejections[replica.name].inc()
        replica.close_conns()
        logger.warning(
            "router ejected replica %s (%s); re-admission in >= %.2fs",
            replica.name, reason, backoff,
        )

    # -- active probing ----------------------------------------------------

    def probe_one(self, replica: Replica, probe=None) -> None:
        """One ``/readyz`` round for one replica. Ready probes admit;
        anything else ejects. An instance change on a ready probe is a
        NEW member — admitted immediately with fresh stats, bypassing
        the dead predecessor's backoff."""
        from predictionio_tpu.cli import daemon as _daemon

        probe = probe or _daemon.probe_ready
        doc = None
        try:
            faults.fault_point("router.probe")
            doc = probe(replica.host, replica.port, timeout=2.0)
        except Exception:
            doc = None
        with self.lock:
            now = self.clock()
            if doc is not None and doc.get("ready"):
                instance = doc.get("instance")
                if instance != replica.instance:
                    # restarted replica: new member, fresh breaker + stats
                    replica.instance = instance
                    replica.latencies.clear()
                    replica.eject_attempt = 0
                    replica.retry_at = 0.0
                elif replica.state == EJECTED and now < replica.retry_at:
                    return  # same instance, still serving its backoff
                if replica.state != READY:
                    logger.info(
                        "router admitted replica %s (instance %s)",
                        replica.name, instance,
                    )
                replica.state = READY
                replica._g_ready.set(1.0)
            else:
                self._eject_locked(replica, "probe failed")

    def probe_all(self, probe=None) -> None:
        for r in self.replicas:
            self.probe_one(r, probe=probe)

    def stats(self) -> dict:
        with self.lock:
            return {r.name: r.stats() for r in self.replicas}


def parse_replica_spec(spec: str, index: int) -> tuple[str, str, int]:
    """``[name=]host:port`` -> (name, host, port); default name
    ``engine-<index>`` matches what ``pio start-all --replicas`` spawns."""
    name = f"engine-{index}"
    if "=" in spec:
        name, spec = spec.split("=", 1)
    host, _, port_s = spec.rpartition(":")
    if not host or not port_s.isdigit():
        raise ValueError(f"replica spec {spec!r} is not [name=]host:port")
    return name, host, int(port_s)


class RouterServer:
    """The ``pio route`` daemon: a ReplicaPool behind an event-loop
    HTTPApp, with a probe thread and a hedging forwarder."""

    # headers copied from the winning replica response to the client
    # (everything the engine uses to steer client behavior)
    _PASS_HEADERS = ("retry-after",)

    def __init__(
        self,
        replicas: list[tuple[str, str, int]],
        host: str = "0.0.0.0",
        port: int = 8100,
        *,
        reuse_port: bool = False,
        probe_interval_s: float | None = None,
        hedge: bool | None = None,
        seed: int = 0,
        probe=None,
    ):
        timeout_s = _env_float("PIO_ROUTER_FORWARD_TIMEOUT_S", 30.0)
        self.pool = ReplicaPool([
            Replica(name, rhost, rport, seed=seed, timeout_s=timeout_s)
            for name, rhost, rport in replicas
        ])
        self.probe_interval_s = (
            probe_interval_s if probe_interval_s is not None
            else _env_float("PIO_ROUTER_PROBE_INTERVAL_S", 1.0)
        )
        self.hedge_enabled = (
            hedge if hedge is not None
            else os.environ.get("PIO_ROUTER_HEDGE", "1") != "0"
        )
        self.hedge_quantile = _env_float("PIO_ROUTER_HEDGE_QUANTILE", 0.95)
        self.hedge_min_s = _env_float("PIO_ROUTER_HEDGE_MIN_MS", 5.0) / 1e3
        self.hedge_max_s = _env_float("PIO_ROUTER_HEDGE_MAX_MS", 1000.0) / 1e3
        self.max_retries = _env_int("PIO_ROUTER_RETRIES", 2)
        self._probe = probe
        self._probe_stop = threading.Event()
        self._probe_thread: threading.Thread | None = None
        # attempts (primary + hedge + retry) run here, so a handler
        # thread blocked in futures_wait never starves the attempts it
        # is waiting on
        handler_threads = _env_int("PIO_HTTP_HANDLER_THREADS", 16)
        self._executor = ThreadPoolExecutor(
            max_workers=max(4, handler_threads * 2),
            thread_name_prefix="router-fwd",
        )
        # router-level latency window: the adaptive hedge delay source
        self._latencies: collections.deque[float] = collections.deque(
            maxlen=2048
        )
        self._lat_lock = threading.Lock()

        self._m_requests = obs_metrics.counter(
            "pio_router_requests_total", "Queries routed (all variants)"
        )
        self._m_retries = obs_metrics.counter(
            "pio_router_retries_total",
            "Attempts re-sent to another replica after a failure",
        )
        self._m_hedges = obs_metrics.counter(
            "pio_router_hedges_total",
            "Duplicate attempts fired after the adaptive hedge delay",
        )
        self._m_hedge_wins = obs_metrics.counter(
            "pio_router_hedge_wins_total",
            "Hedged attempts that answered before the primary",
        )
        self._g_hedge_ratio = obs_metrics.gauge(
            "pio_router_hedge_win_ratio",
            "hedge wins / hedges fired (0 when no hedges yet)",
        )
        self._g_hedge_ratio.set_function(self._hedge_win_ratio)

        router = Router()
        router.add("POST", "/queries.json", self._route_bare)
        router.add("GET", "/stats.json", self._stats_route)
        add_obs_routes(router)
        # registered LAST so /stats.json and the obs routes win first
        router.add("POST", "/<variant>/queries.json", self._route_variant)
        self.app = HTTPApp(
            router,
            host=host,
            port=port,
            reuse_port=reuse_port,
            name="router",
            handler_threads=handler_threads,
            ready_check=self._ready_reason,
        )
        self.start_time = time.time()
        self._slos = obs_slo.install_router_slos(self)

    # -- lifecycle ---------------------------------------------------------

    def start(self, background: bool = True) -> int:
        """Probe once synchronously (so /readyz is meaningful the moment
        the port answers), start the probe thread, then serve."""
        self.pool.probe_all(probe=self._probe)
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True, name="router-probe"
        )
        self._probe_thread.start()
        self.app.add_shutdown_hook(self._shutdown)
        return self.app.start(background=background)

    def stop(self) -> None:
        self.app.stop()
        self._shutdown()

    def _shutdown(self) -> None:
        self._probe_stop.set()
        self._executor.shutdown(wait=False)
        for r in self.pool.replicas:
            r.close_conns()

    def _probe_loop(self) -> None:
        while not self._probe_stop.wait(self.probe_interval_s):
            try:
                self.pool.probe_all(probe=self._probe)
            except Exception:  # pragma: no cover - probe must never die
                logger.exception("router probe round failed")

    def _ready_reason(self) -> str | None:
        n = self.pool.available_count()
        if n > 0:
            return None
        total = len(self.pool.replicas)
        return f"no ready replicas (0/{total} admitted)"

    # -- hedging -----------------------------------------------------------

    def _hedge_win_ratio(self) -> float:
        hedges = self._m_hedges.value()
        return (self._m_hedge_wins.value() / hedges) if hedges else 0.0

    def _observe_latency(self, elapsed_s: float) -> None:
        with self._lat_lock:
            self._latencies.append(elapsed_s)

    def hedge_delay_s(self) -> float:
        """Adaptive delay: the observed forward-latency quantile,
        clamped. Until enough samples exist, the max — hedging blind
        would double load exactly when every replica is cold."""
        with self._lat_lock:
            if len(self._latencies) < 16:
                return self.hedge_max_s
            xs = sorted(self._latencies)
        q = xs[min(len(xs) - 1, int(self.hedge_quantile * len(xs)))]
        return min(self.hedge_max_s, max(self.hedge_min_s, q))

    # -- forwarding --------------------------------------------------------

    def _attempt(self, replica: Replica, path: str, body: bytes,
                 headers: dict[str, str]) -> tuple[int, bytes, dict]:
        """One forward to one replica. 2xx-4xx pass through (the replica
        answered; a 400/404 is the CLIENT's problem). Connect errors,
        injected faults, and 5xx are attempt failures: the replica is
        ejected and _AttemptError carries any 5xx body for last-resort
        pass-through."""
        self.pool.begin(replica)
        start = time.perf_counter()
        conn = None
        try:
            faults.fault_point("router.forward")
            conn = replica.acquire()
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            status = resp.status
            rhdrs = {
                k: resp.getheader(k)
                for k in self._PASS_HEADERS
                if resp.getheader(k) is not None
            }
        except Exception as exc:
            if conn is not None:
                conn.close()
            self.pool.record_failure(replica, f"{type(exc).__name__}: {exc}")
            raise _AttemptError(f"{replica.name}: {exc}") from exc
        if status >= 500:
            conn.close()
            self.pool.record_failure(replica, f"HTTP {status}")
            raise _AttemptError(
                f"{replica.name}: HTTP {status}", status=status,
                body=data, headers=rhdrs,
            )
        elapsed = time.perf_counter() - start
        replica.release(conn)
        self.pool.record_success(replica, elapsed)
        self._observe_latency(elapsed)
        return status, data, rhdrs

    def forward(self, key: bytes, path: str, body: bytes,
                headers: dict[str, str]) -> Response:
        """Route one query: primary by affinity, hedge after the
        adaptive delay, retry failures on other replicas, first good
        response wins."""
        self._m_requests.inc()
        primary = self.pool.pick(key)
        if primary is None:
            return Response.error("no ready replicas", 503)
        futures = {
            self._executor.submit(self._attempt, primary, path, body,
                                  headers): (primary, False)
        }
        tried = {primary.name}
        hedged = False
        retries_left = self.max_retries
        last_err: _AttemptError | None = None
        while futures:
            timeout = None
            if (
                self.hedge_enabled
                and not hedged
                and self.pool.available_count(exclude=tried) > 0
            ):
                timeout = self.hedge_delay_s()
            done, _pending = futures_wait(
                set(futures), timeout=timeout, return_when=FIRST_COMPLETED
            )
            if not done:
                # hedge timer expired with the primary still in flight
                hedged = True
                rep = self.pool.pick_other(tried)
                if rep is not None:
                    tried.add(rep.name)
                    self._m_hedges.inc()
                    futures[self._executor.submit(
                        self._attempt, rep, path, body, headers
                    )] = (rep, True)
                continue
            for fut in done:
                _rep, is_hedge = futures.pop(fut)
                try:
                    status, data, rhdrs = fut.result()
                except _AttemptError as exc:
                    if exc.status:  # keep the most recent real 5xx
                        last_err = exc
                    elif last_err is None or not last_err.status:
                        last_err = exc
                    if not futures and retries_left > 0:
                        rep = self.pool.pick_other(tried)
                        if rep is not None:
                            retries_left -= 1
                            tried.add(rep.name)
                            self._m_retries.inc()
                            futures[self._executor.submit(
                                self._attempt, rep, path, body, headers
                            )] = (rep, False)
                    continue
                if is_hedge:
                    self._m_hedge_wins.inc()
                # an abandoned sibling attempt finishes in the executor
                # and records its own replica stats; its bytes are
                # dropped — queries are read-only, so that is safe
                return Response(status=status, body=data, headers=rhdrs)
        if last_err is not None and last_err.status:
            return Response(
                status=last_err.status, body=last_err.body,
                headers=last_err.headers,
            )
        return Response.error(
            f"all replicas failed: {last_err}", 502,
        )

    # -- routes ------------------------------------------------------------

    def _affinity_key(self, variant: str, body_bytes: bytes) -> bytes:
        """Variant-salted canonical query bytes — the same
        canonicalization that keys the replica-side query cache, so
        affinity and cache locality agree. Unparseable bodies hash raw
        (the replica will 400 them; they still route consistently)."""
        try:
            body = json.loads(body_bytes)
            canon = (
                canonical_query_bytes(body)
                if isinstance(body, dict) else body_bytes
            )
        except ValueError:
            canon = body_bytes
        return variant.encode() + b"\x00" + canon

    def _route_bare(self, request: Request) -> Response:
        variant = request.headers.get("x-pio-variant", "")
        headers = {"Content-Type": "application/json"}
        if variant:
            headers["X-PIO-Variant"] = variant
        return self.forward(
            self._affinity_key(variant, request.body),
            "/queries.json", request.body, headers,
        )

    def _route_variant(self, request: Request) -> Response:
        variant = request.path_params["variant"]
        return self.forward(
            self._affinity_key(variant, request.body),
            f"/{variant}/queries.json", request.body,
            {"Content-Type": "application/json"},
        )

    def _stats_route(self, _req: Request) -> Response:
        return Response.json(self.stats())

    def stats(self) -> dict:
        hedges = self._m_hedges.value()
        return {
            "server": "router",
            "instance": self.app.instance_id,
            "uptime_s": round(time.time() - self.start_time, 3),
            "replicas": self.pool.stats(),
            "routing": {
                "requests": self._m_requests.value(),
                "retries": self._m_retries.value(),
                "hedge_enabled": self.hedge_enabled,
                "hedge_delay_ms": round(self.hedge_delay_s() * 1e3, 3),
                "hedges": hedges,
                "hedge_wins": self._m_hedge_wins.value(),
                "hedge_win_ratio": round(self._hedge_win_ratio(), 4),
            },
            "obs": obs_metrics.stats_block(),
        }
