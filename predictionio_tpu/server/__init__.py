"""HTTP servers: event ingestion, engine query serving, admin, dashboard.

Capability parity with the reference's spray/akka servers
(data/.../api/EventServer.scala, core/.../workflow/CreateServer.scala,
tools/.../admin/AdminAPI.scala, tools/.../dashboard/Dashboard.scala) on a
threaded stdlib HTTP stack — the serving path's device work (top-k
scoring) stays a single fused jax call per request.
"""
