"""Engine Server: deployed-engine query serving (default port 8000).

Capability parity with the reference CreateServer
(core/.../workflow/CreateServer.scala:105-663):

- ``POST /queries.json`` — deserialize query via the algorithm's query
  class, ``serving.supplement``, score every algorithm, ``serving.serve``,
  JSON response (:470-500). Per-request bookkeeping: requestCount,
  avgServingSec, lastServingSec (:399-403).
- ``GET /`` — status page with engine info and serving stats; browsers
  (Accept: text/html) get the HTML render (:443-467), API clients JSON.
- Serving errors POST ``logPrefix + {engineInstance, message}`` to
  ``--log-url`` when configured (:422-433, :596-618).
- ``POST /reload`` — hot-swap to the newest COMPLETED engine instance
  (:316-342); key-authenticated.
- ``POST /stop`` — key-authenticated shutdown (:260-285).
- ``GET /plugins.json`` + output blocker/sniffer plugins (:578-581).
- Feedback loop (:514-577): when enabled, asynchronously POSTs a
  ``predict`` event (entityType ``pio_pr``) with query+prediction back to
  the Event Server, generating/propagating ``prId``.

The reference scores algorithms sequentially per request with a
"TODO: Parallelize" note (:494-496); here multi-algorithm scoring still
iterates host-side but each algorithm's scoring is one fused device call.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import urllib.request
import uuid
from concurrent.futures import InvalidStateError
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, NamedTuple

from predictionio_tpu import faults
from predictionio_tpu.core.engine import Engine
from predictionio_tpu.core.workflow import prepare_deploy
from predictionio_tpu.data.storage import EngineInstance, Storage, get_storage
from predictionio_tpu.obs import device as obs_device
from predictionio_tpu.obs import freshness as obs_freshness
from predictionio_tpu.obs import metrics as obs_metrics
from predictionio_tpu.obs import slo as obs_slo
from predictionio_tpu.obs import trace as obs_trace
from predictionio_tpu.server import jsonx
from predictionio_tpu.server import plugins as plugin_mod
from predictionio_tpu.server.http import (
    HTTPApp,
    Request,
    Response,
    Router,
    add_obs_routes,
)
from predictionio_tpu.server.query_cache import (
    QueryCache,
    canonical_query_bytes,
)

logger = logging.getLogger(__name__)


class QueryDeadlineExceeded(Exception):
    """A query overran the configured per-query deadline
    (PIO_QUERY_DEADLINE_MS); the route maps this to 503 + Retry-After
    instead of letting the client hang."""


def _to_jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return dataclasses.asdict(obj)
    return obj


def _model_bytes(obj: Any, _depth: int = 0) -> int:
    """Total ``nbytes`` reachable from a deployed model list — the byte
    size of a model put/patch for the device transfer accounting. Walks
    containers and object attributes a few levels deep (an ALS model is
    an object holding factor arrays or int8 (values, scales) pairs);
    anything unrecognized counts as 0 rather than guessing."""
    if _depth > 3:
        return 0
    nbytes = getattr(obj, "nbytes", None)
    if isinstance(nbytes, int):
        return nbytes
    if isinstance(obj, (list, tuple)):
        return sum(_model_bytes(o, _depth + 1) for o in obj)
    if isinstance(obj, dict):
        return sum(_model_bytes(o, _depth + 1) for o in obj.values())
    if hasattr(obj, "__dict__"):
        return sum(_model_bytes(v, _depth + 1) for v in vars(obj).values())
    return 0


def _query_from_json(query_class: type | None, data: dict[str, Any]) -> Any:
    """JSON -> query object (reference JsonExtractor.extract on
    algo.queryClass, CreateServer.scala:479-485)."""
    if query_class is None:
        return data
    if dataclasses.is_dataclass(query_class):
        names = {f.name for f in dataclasses.fields(query_class)}
        return query_class(**{k: v for k, v in data.items() if k in names})
    return query_class(**data)


class _MicroBatcher:
    """Collects concurrent ``/queries.json`` requests and scores them
    with ONE ``batch_predict`` call per algorithm — amortizing the fixed
    per-device-call dispatch cost across requests. On TPU attachments
    where dispatch dominates (remote tunnels measure ~130 ms/call), N
    concurrent requests cost ~1 dispatch instead of N; batch_predict's
    batched matmul also fills the MXU where single queries underuse it.

    LOAD-AWARE: the batcher is ALWAYS engaged — the engage decision
    moved from deploy time (the retired ``MIN_DISPATCH_S`` floor, which
    disengaged every local attachment and was exactly why BENCH_r04
    measured batching LOSING) to per-batch time, where queue depth is
    known:

    - queue depth 1 (idle server): the collected "batch" takes the
      single-item FAST PATH — straight to ``predict``, no padding, no
      coalescing — so a lone query pays only the queue hop (~0.1 ms),
      never the window.
    - queue depth > 1 (amortization wins by construction): ONE padded
      ``batch_predict`` per algorithm scores the whole batch. Depth is
      created by load itself: requests queue behind the in-flight
      device call and coalesce into the next one.
    - ``dispatch > window`` (remote tunnels, ~130 ms/call): the worker
      additionally waits up to the window to grow the batch — added
      latency bounded by the window, itself below one dispatch.

    Batches pad to power-of-two sizes (1,2,4,...,``max_batch``) so the
    jitted scoring programs specialize on at most log2(max_batch)+1
    shapes — ``pio_jit_compiles_total`` stays flat under load.

    Semantics are identical to per-request serving: every Algorithm has
    ``batch_predict`` (the default loops ``predict``), and
    serving/plugins/feedback still run per query. Queries are parsed on
    their REQUEST thread (a malformed body 400s without occupying a
    batch slot), and the serving/feedback/plugin tail also runs on the
    request thread — the worker only collects and dispatches, so the
    JSON/serving work of batchmates overlaps. A failing batch retries
    its items individually so one bad query can't poison its
    batchmates."""

    def __init__(self, server: "EngineServer", window_ms: float,
                 max_batch: int = 64, dispatch_cost_s: float | None = None):
        import queue

        self._server = server
        self._window = window_ms / 1e3
        self._max = max_batch
        self._q: "queue.Queue" = queue.Queue()
        self._stopped = False
        self._lock = threading.Lock()
        self.dispatch_cost_s = (
            self._measure_dispatch() if dispatch_cost_s is None
            else dispatch_cost_s
        )
        # kept for dashboards/tests: the batcher no longer disengages —
        # single-item batches bypass the machinery instead
        self.engaged = True
        self._window_wait = self.dispatch_cost_s > self._window
        if not self._window_wait:
            logger.info(
                "micro-batch: measured dispatch %.2f ms <= window %.1f ms "
                "on this attachment; window bypassed (batches form only "
                "from naturally queued requests)",
                self.dispatch_cost_s * 1e3,
                window_ms,
            )
        else:
            logger.info(
                "micro-batch: measured dispatch %.2f ms > window %.1f ms "
                "on this attachment; window-waiting to grow batches",
                self.dispatch_cost_s * 1e3,
                window_ms,
            )
        # the numbers the ROADMAP "make the batcher win" item needs:
        # where requests wait, how big batches actually get, and what a
        # dispatch costs
        self._m_batch_size = obs_metrics.histogram(
            "pio_batch_size", "Queries coalesced per device dispatch",
            bounds=(1, 2, 4, 8, 16, 32, 64, 128),
        )
        self._m_queue_wait = obs_metrics.histogram(
            "pio_batch_queue_wait_seconds",
            "Per-query wait from submit to batch collection",
        )
        self._m_dispatch = obs_metrics.histogram(
            "pio_batch_dispatch_seconds",
            "batch_predict device-dispatch time per micro-batch",
        )
        obs_metrics.gauge(
            "pio_batch_engaged",
            "1 when the micro-batcher serves queries, 0 when disengaged",
        ).set(1.0)
        obs_metrics.gauge(
            "pio_batch_dispatch_cost_seconds",
            "Measured per-device-call dispatch cost at deploy",
        ).set(self.dispatch_cost_s)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    @staticmethod
    def _measure_dispatch() -> float:
        """Per-device-call dispatch cost (seconds): a cached no-op jit
        round trip — the fixed cost micro-batching amortizes. ~0.1 ms on
        a local attachment, ~130 ms over a remote TPU tunnel."""
        try:
            import jax
            import jax.numpy as jnp

            f = jax.jit(lambda x: x + 1)
            x = jnp.zeros((), jnp.float32)
            f(x).block_until_ready()  # compile outside the timing
            t0 = time.perf_counter()
            for _ in range(3):
                f(x).block_until_ready()
            return (time.perf_counter() - t0) / 3
        except Exception:  # pragma: no cover - probe must never kill boot
            logger.exception("dispatch probe failed; assuming fast")
            return 0.0

    @property
    def active(self) -> bool:
        return not self._stopped

    def submit(self, body: dict, variant=None) -> "_Submitted":
        """Parse on the request thread, enqueue for the worker. Returns
        the pending future (resolving to the per-algorithm predictions)
        plus the parsed context the request thread needs to finish the
        query itself. Parse errors raise here — a malformed body 400s
        without ever occupying a batch slot."""
        from concurrent.futures import Future

        server = self._server
        v = variant if variant is not None else server._default_variant
        with server._lock:
            algorithms, serving = v.algorithms, v.serving
        query, sup = server._parse_query(body, algorithms, serving)
        f: Future = Future()
        t0 = time.perf_counter()
        # the stopped check and the put share stop()'s lock: stop() can
        # never drain between them and strand this future in a dead queue
        with self._lock:
            if self._stopped:
                raise RuntimeError("server stopping")
            # the request thread's trace rides the queue item — the
            # worker thread can't see this thread's thread-local
            self._q.put((f, t0, obs_trace.current_trace(), sup, v))
        return _Submitted(f, query, serving, t0)

    def stop(self) -> None:
        import queue

        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        # no submit can enqueue past this point (flag is set under the
        # lock); let the worker finish its in-flight batch, then fail
        # whatever is still queued rather than leaving clients blocked
        # on the future timeout
        if self._thread is not None:
            self._thread.join(timeout=5)
        while True:
            try:
                f, *_ = self._q.get_nowait()
            except queue.Empty:
                break
            if not f.done():
                f.set_exception(RuntimeError("server stopping"))

    def _loop(self) -> None:
        import queue

        while not self._stopped:
            try:
                first = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            batch = [first]
            deadline = time.perf_counter() + self._window
            while len(batch) < self._max:
                try:
                    batch.append(self._q.get_nowait())
                    continue
                except queue.Empty:
                    pass
                # queue is empty: idle-wait for more only when a saved
                # dispatch is worth more than the window
                if not self._window_wait:
                    break
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            self._m_batch_size.observe(float(len(batch)))
            try:
                self._server._handle_query_batch(batch)
            except Exception:  # pragma: no cover - worker must survive
                logger.exception("micro-batch worker failed")
                for f, *_ in batch:
                    if not f.done():
                        f.set_exception(RuntimeError("batch worker failed"))


class _Submitted(NamedTuple):
    """What ``_MicroBatcher.submit`` hands back to the request thread:
    the pending predictions future plus the context to finish the query
    (serving/feedback/plugins run on the request thread, not the batch
    worker)."""

    fut: Any
    query: Any
    serving: Any
    t0: float


class _Variant:
    """One mounted tenant of an EngineServer: its own engine, instance,
    models, epoch fence, serving bookkeeping, and (optionally) speed
    layer — while the HTTP front end, micro-batcher worker, jit cache,
    and query-cache byte budget stay shared process-wide.

    Duck-types the surface ``realtime.SpeedLayer`` expects from a
    "server" (engine_params / storage / instance / model_snapshot /
    apply_patch / query_cache / _lock / _foldin_epoch / speed_layer), so
    a layer constructed with a mount folds into exactly that tenant.

    ``labeled`` is True on multi-tenant servers: per-tenant metric
    series ride a ``variant=<name>`` label and ``variant_name`` suffixes
    the mount's SLO names. Solo deploys stay unlabeled so their metric
    and SLO names are byte-identical to the pre-multi-tenant server."""

    def __init__(
        self,
        server: "EngineServer",
        name: str,
        engine: Engine,
        instance: EngineInstance,
        labeled: bool,
    ):
        self.server = server
        self.name = name
        self.engine = engine
        self._epoch = 0
        self._foldin_epoch = 0
        self.speed_layer = None  # attached by realtime.SpeedLayer
        self.request_count = 0
        self.serving_seconds = 0.0
        self.last_serving_sec = 0.0
        self.last_reload_ts = 0.0
        self.variant_name = name if labeled else None
        self._m_serving_v = (
            obs_metrics.histogram(
                "pio_serving_seconds",
                "Per-query scoring+serve time (parse through plugins)",
                variant=name,
            )
            if labeled
            else None
        )
        self._m_requests_v = (
            obs_metrics.counter(
                "pio_serving_requests_total",
                "Queries served, per tenant",
                variant=name,
            )
            if labeled
            else None
        )
        self._slos: list = []
        self._load(instance)

    # -- shared infrastructure (also the SpeedLayer "server" surface) ------
    @property
    def _lock(self):
        return self.server._lock

    @property
    def storage(self):
        return self.server.storage

    @property
    def query_cache(self):
        return self.server.query_cache

    # -- load / reload ------------------------------------------------------
    def _load(self, instance: EngineInstance) -> None:
        engine_params, algorithms, models, serving = prepare_deploy(
            self.engine, instance, storage=self.server.storage
        )
        obs_device.count_transfer(
            "h2d", "serve.model_put", _model_bytes(models)
        )
        with self._lock:
            self.instance = instance
            self.engine_params = engine_params
            self.algorithms = algorithms
            self.models = models
            self.serving = serving
            # retrain wins: a reload supersedes any applied fold-in
            # patches (the new instance was trained on the full log)
            self._epoch += 1
            self._foldin_epoch = 0
            epoch = self._epoch
        # entries under older epochs are unreachable by key the moment
        # the counter moves; the sweep reclaims their bytes — scoped to
        # THIS tenant's partition, so reloading one mount never flushes
        # a co-tenant's cached results
        if self.query_cache is not None:
            self.query_cache.sweep(epoch, variant=self.name)
        # freshness lineage, batch side: events ingested before this
        # instance's training began are servable NOW — one sample of
        # (commit - train_start) records the batch-layer staleness floor
        try:
            train_start = instance.start_time.timestamp()
        except (AttributeError, OSError, ValueError):
            train_start = None
        obs_freshness.observe_commit(
            [train_start] if train_start is not None else [],
            kind="reload",
            epoch=epoch,
        )
        self.last_reload_ts = time.time()
        if self.variant_name is not None:
            obs_metrics.gauge(
                "pio_serving_epoch",
                "Model swap epoch, per tenant",
                variant=self.name,
            ).set(float(epoch))
        logger.info(
            "engine instance %s loaded for serving (variant %s)",
            instance.id,
            self.name,
        )

    def reload(self) -> bool:
        """Swap this mount to its latest completed instance."""
        latest = self.storage.get_metadata_engine_instances().get_latest_completed(
            self.instance.engine_id,
            self.instance.engine_version,
            self.instance.engine_variant,
        )
        if latest is None:
            return False
        # prepare_deploy runs OFF the server lock; the swap is atomic —
        # the old model keeps serving 200s through the whole reload
        self._load(latest)
        return True

    # -- speed-layer hot patching -------------------------------------------
    def model_snapshot(self):
        """(instance_id, models, epoch) under the lock — the fenced read
        a fold-in starts from."""
        with self._lock:
            return self.instance.id, self.models, self._epoch

    def apply_patch(self, models, expected_epoch: int) -> bool:
        """Epoch-fenced swap of this mount's model list."""
        with self._lock:
            if expected_epoch != self._epoch:
                return False
            self.models = models
            self._epoch += 1
            self._foldin_epoch += 1
            epoch = self._epoch
        obs_device.count_transfer(
            "h2d", "serve.model_patch", _model_bytes(models)
        )
        if self.query_cache is not None:
            self.query_cache.sweep(epoch, variant=self.name)
        if self.variant_name is not None:
            obs_metrics.gauge(
                "pio_serving_epoch",
                "Model swap epoch, per tenant",
                variant=self.name,
            ).set(float(epoch))
        return True

    # -- observability -------------------------------------------------------
    def stats(self) -> dict[str, Any]:
        """One row of the /stats.json ``variants`` block: qps inputs,
        p99, epoch, freshness, SLO states for this tenant."""
        with self._lock:
            avg = (
                self.serving_seconds / self.request_count
                if self.request_count
                else 0.0
            )
            d: dict[str, Any] = {
                "engineInstanceId": self.instance.id,
                "engineVariant": self.instance.engine_variant,
                "epoch": self._epoch,
                "foldinEpoch": self._foldin_epoch,
                "requestCount": self.request_count,
                "avgServingSec": round(avg, 6),
                "lastServingSec": round(self.last_serving_sec, 6),
            }
        hist = (
            self._m_serving_v
            if self._m_serving_v is not None
            else self.server._m_serving
        )
        try:
            d["p99Ms"] = round(hist.percentile(0.99) * 1e3, 3)
        except Exception:  # pragma: no cover - stats must never 500
            d["p99Ms"] = None
        d["modelAgeSec"] = (
            round(time.time() - self.last_reload_ts, 1)
            if self.last_reload_ts
            else None
        )
        layer = self.speed_layer
        d["secondsBehind"] = (
            layer.gauges().get("seconds_behind") if layer is not None else None
        )
        if self._slos:
            d["slo"] = {s.name: s.state for s in self._slos}
        return d


class EngineServer:
    def __init__(
        self,
        engine: Engine,
        instance: EngineInstance,
        storage: Storage | None = None,
        host: str = "0.0.0.0",
        port: int = 8000,
        server_key: str | None = None,
        feedback: bool = False,
        event_server_url: str | None = None,
        access_key: str | None = None,
        server_config=None,
        log_url: str | None = None,
        log_prefix: str | None = None,
        batch_window_ms: float = 0.0,
        dispatch_cost_s: float | None = None,
        reuse_port: bool = False,
        query_cache_mb: float = 0.0,
        query_deadline_ms: float | None = None,
        extra_variants: list[tuple[str, Engine, EngineInstance]] | None = None,
    ):
        self.storage = storage or get_storage()
        self.host = host
        # server.conf-style config supplies the control key and TLS
        # (reference common KeyAuthentication + SSLConfiguration)
        self.server_config = server_config
        self.server_key = server_key
        self.feedback = feedback
        self.event_server_url = event_server_url
        self.access_key = access_key
        # serving errors POST to this URL when set (reference
        # CreateServer.scala remoteLog, :422-433 + :596-618)
        self.log_url = log_url
        self.log_prefix = log_prefix or ""
        self._lock = threading.RLock()
        self.query_cache: QueryCache | None = None
        # set while deploy warmup overlaps live traffic (reuse_port
        # workers, late warmups): /queries.json answers 503 +
        # Retry-After instead of paying a compile it didn't order.
        # /reload does NOT set this — the old model serves through the
        # whole swap (prepare_deploy runs off-lock, the swap is atomic)
        self._swapping = threading.Event()
        # per-query deadline (PIO_QUERY_DEADLINE_MS or query_deadline_ms
        # arg): a query that overruns it gets 503 + Retry-After instead
        # of hanging its connection; None = unbounded (the default)
        if query_deadline_ms is None:
            try:
                query_deadline_ms = float(
                    os.environ.get("PIO_QUERY_DEADLINE_MS", "0").strip() or 0
                )
            except ValueError:
                logger.warning("ignoring non-numeric PIO_QUERY_DEADLINE_MS")
                query_deadline_ms = 0.0
        self.query_deadline_s = (
            query_deadline_ms / 1e3 if query_deadline_ms > 0 else None
        )
        # deadline expiry rides the HTTP front end's timer wheel
        # (HTTPApp.call_later) — a heap entry per in-flight deadline
        # query, not the 32-thread watcher pool this replaced. The
        # unbatched path still needs the scoring off the request thread
        # to answer 503 AT the deadline; a short-lived thread per query
        # does that, capped so an overload degrades to inline scoring
        # with post-hoc shedding instead of unbounded thread spawn.
        self._ddl_slots = threading.BoundedSemaphore(32)

        # tenant mounts: the primary (engine, instance) is the DEFAULT
        # variant — bare /queries.json serves it, and every legacy
        # attribute (engine/instance/models/_epoch/...) delegates to it,
        # so a solo deploy behaves byte-identically to the
        # single-tenant server. extra_variants adds co-tenants routed by
        # /<name>/queries.json or the X-PIO-Variant header; each keeps
        # its own epoch fence, /reload, speed layer, and query-cache
        # partition while sharing this process's HTTP front end,
        # micro-batcher, jit cache (pow2 buckets make compiled programs
        # tenant-independent), and query-cache byte budget.
        default_name = instance.engine_variant or "default"
        mounts = [(default_name, engine, instance)] + list(extra_variants or [])
        labeled = len(mounts) > 1
        self.variants: dict[str, _Variant] = {}
        for name, eng, inst in mounts:
            if not name or "/" in name:
                raise ValueError(f"invalid variant mount name {name!r}")
            if name in self.variants:
                raise ValueError(f"duplicate variant mount name {name!r}")
            self.variants[name] = _Variant(self, name, eng, inst, labeled)
        self.default_variant_name = default_name
        self._default_variant = self.variants[default_name]

        self.start_time = time.time()
        self._m_serving = obs_metrics.histogram(
            "pio_serving_seconds",
            "Per-query scoring+serve time (parse through plugins)",
        )
        self._m_cache_lookup = obs_metrics.histogram(
            "pio_cache_lookup_seconds",
            "Query-cache canonicalize+lookup time (hits and misses)",
        )
        # default objectives: p99 latency, 5xx availability, the
        # warmup/deadline 503 budget, ingest-to-servable freshness
        obs_slo.install_engine_slos(self)
        # multi-tenant servers additionally get one latency objective
        # per mount (names suffixed [mount]) so one noisy tenant pages
        # as itself, not as the process aggregate
        if labeled:
            for v in self.variants.values():
                v._slos = obs_slo.install_variant_slos(v)

        self.plugins = plugin_mod.load_plugins(plugin_mod.EngineServerPlugin)
        self.plugin_context: dict[str, Any] = {"storage": self.storage}
        for p in self.plugins:
            p.start(self.plugin_context)

        # query-result cache: preserialized response bytes keyed by
        # (engine_variant, canonical_query_bytes, epoch) — the epoch
        # fence gives EXACT invalidation (every /reload and every
        # speed-layer patch bumps it), so a hit can never be stale with
        # respect to the served model. Two per-request effects make
        # caching incorrect, so they disable it outright:
        if query_cache_mb and query_cache_mb > 0:
            blockers = [
                p for p in self.plugins
                if p.plugin_type == plugin_mod.OUTPUT_BLOCKER
            ]
            if feedback:
                logger.warning(
                    "query cache disabled: --feedback generates a fresh "
                    "prId and POSTs a predict event per request"
                )
            elif blockers:
                logger.warning(
                    "query cache disabled: output-blocker plugin(s) %s "
                    "rewrite responses per request",
                    [p.plugin_name for p in blockers],
                )
            else:
                self.query_cache = QueryCache(int(query_cache_mb * 2**20))

        # micro-batched serving: amortize device dispatch across
        # concurrent requests (0 = per-request, the reference behavior;
        # dispatch_cost_s overrides the startup probe — tests pin it to
        # force window/bypass mode deterministically)
        self.batcher = (
            _MicroBatcher(self, batch_window_ms, dispatch_cost_s=dispatch_cost_s)
            if batch_window_ms > 0
            else None
        )

        self.app = HTTPApp(
            self._router(),
            host=host,
            port=port,
            ssl_context=(
                server_config.ssl_context() if server_config is not None else None
            ),
            reuse_port=reuse_port,
            name="engine",
            ready_check=self._ready_reason,
        )
        # drain-time flush: the speed layer persists its tailer cursor
        # and the batcher stops dispatching before the loop exits
        self.app.add_shutdown_hook(self._drain_flush)

    # -- default-variant delegation -----------------------------------------
    # Legacy surface: every pre-multi-tenant attribute reads/writes the
    # default mount. SpeedLayer(server), the supervisor, the CLI, and
    # tests keep working unchanged; per-tenant state lives on _Variant.

    def _delegate(attr):  # noqa: N805 - descriptor factory, not a method
        def _get(self):
            return getattr(self._default_variant, attr)

        def _set(self, value):
            setattr(self._default_variant, attr, value)

        return property(_get, _set)

    engine = _delegate("engine")
    instance = _delegate("instance")
    engine_params = _delegate("engine_params")
    algorithms = _delegate("algorithms")
    models = _delegate("models")
    serving = _delegate("serving")
    _epoch = _delegate("_epoch")
    _foldin_epoch = _delegate("_foldin_epoch")
    speed_layer = _delegate("speed_layer")
    request_count = _delegate("request_count")
    serving_seconds = _delegate("serving_seconds")
    last_serving_sec = _delegate("last_serving_sec")
    del _delegate

    def _load(self, instance: EngineInstance) -> None:
        self._default_variant._load(instance)

    # -- query path --------------------------------------------------------
    def serve_query_bytes(
        self, body: dict[str, Any], variant: "_Variant | None" = None
    ) -> bytes:
        """THE /queries.json read path: preserialized response bytes.

        Cache hit: one canonical-bytes build + one sharded dict lookup —
        no device dispatch, no serving join, no JSON encode, and the
        request never enters the micro-batch queue. Miss: the normal
        scoring path, then the encoded bytes are stored iff every
        Algorithm and the Serving say the query is cacheable.

        Epoch fencing: the epoch is snapshotted BEFORE scoring, so a
        model swap landing mid-flight strands the computed result under
        the pre-swap epoch — it can never be served after the swap. (The
        reverse order would race: old-model results could be filed under
        the new epoch.)"""
        v = variant if variant is not None else self._default_variant
        cache = self.query_cache
        key = None
        if cache is not None:
            t_c0 = time.perf_counter()
            with self._lock:
                epoch = v._epoch
            try:
                # keyed by the MOUNT name (not instance.engine_variant):
                # unique even when several mounts share one instance, so
                # each tenant keeps its own cache partition
                key = (v.name, canonical_query_bytes(body), epoch)
            except (TypeError, ValueError):
                key = None  # non-canonicalizable body: uncacheable
            payload = cache.get(key) if key is not None else None
            t_c1 = time.perf_counter()
            self._m_cache_lookup.observe(t_c1 - t_c0)
            tr = obs_trace.current_trace()
            if tr is not None:
                tr.add_span(
                    "cache.hit" if payload is not None else "cache.miss",
                    t_c0, t_c1,
                )
            if payload is not None:
                # a hit is still a served request; it adds ~0 to
                # serving_seconds by construction
                with self._lock:
                    v.request_count += 1
                if v._m_requests_v is not None:
                    v._m_requests_v.inc()
                return payload
        if self.batcher is not None and self.batcher.active:
            try:
                response_obj = self._serve_batched(body, v)
            except RuntimeError as e:
                # batcher INFRASTRUCTURE failure (dead worker / stopping
                # server), not a query error: degrade to the unbatched
                # path so the request still serves
                if str(e) not in ("batch worker failed", "server stopping"):
                    raise
                obs_metrics.counter(
                    "pio_batcher_fallback_total",
                    "Queries served unbatched after a micro-batcher failure",
                ).inc()
                logger.warning(
                    "micro-batcher unavailable (%s); serving unbatched", e
                )
                response_obj = self._query_with_deadline(body, v)
        else:
            response_obj = self._query_with_deadline(body, v)
        payload = jsonx.dumps_bytes(response_obj)
        if key is not None and self._query_cacheable(body, v):
            cache.put(key, payload)
        return payload

    def _query_cacheable(
        self, body: dict[str, Any], variant: "_Variant | None" = None
    ) -> bool:
        """Every Algorithm AND the Serving must consent (core/base.py
        ``cacheable_query``). Runs on the miss path only."""
        v = variant if variant is not None else self._default_variant
        with self._lock:
            algorithms, serving = v.algorithms, v.serving
        try:
            query, supplemented = self._parse_query(body, algorithms, serving)
        except Exception:
            return False
        if not serving.cacheable_query(query):
            return False
        return all(a.cacheable_query(supplemented) for a in algorithms)

    def _serve_batched(
        self, body: dict[str, Any], variant: "_Variant | None" = None
    ) -> dict[str, Any]:
        """Score through the micro-batcher. The worker resolves the
        future with the per-algorithm predictions; serving/feedback/
        plugins (``_finish_query``) run HERE on the request thread, so
        batchmates' response tails overlap instead of serializing on
        the worker. Deadline expiry is a timer-wheel entry that fails
        the future — the client gets its 503 AT the deadline even while
        the device call is still in flight."""
        # legacy single-arg call for the default mount (submit defaults
        # to it): solo-deploy wrappers/stubs of submit keep working
        if variant is None or variant is self._default_variant:
            sub = self.batcher.submit(body)
        else:
            sub = self.batcher.submit(body, variant)
        fut = sub.fut
        handle = None
        if self.query_deadline_s is not None:
            handle = self.app.call_later(
                self.query_deadline_s,
                lambda: self._expire_future(fut, "batched"),
            )
        # with a timer armed, result() only needs a generous backstop;
        # without one (loop not running, or no deadline) the result
        # timeout itself enforces the bound
        if self.query_deadline_s is None:
            timeout = 60.0
        elif handle is None:
            timeout = self.query_deadline_s
        else:
            timeout = self.query_deadline_s + 60.0
        try:
            predictions = fut.result(timeout=timeout)
        except FuturesTimeout:
            self._count_deadline("batched")
            raise QueryDeadlineExceeded(
                "query exceeded the per-query deadline"
            ) from None
        finally:
            if handle is not None:
                handle.cancel()
        return self._finish_query(
            body, sub.query, predictions, sub.serving, sub.t0, variant=variant
        )

    @staticmethod
    def _count_deadline(path: str) -> None:
        obs_metrics.counter(
            "pio_query_deadline_exceeded_total",
            "Queries 503'd for overrunning PIO_QUERY_DEADLINE_MS",
            path=path,
        ).inc()

    def _expire_future(self, fut, path: str) -> None:
        """Timer-wheel callback: fail a still-pending query future at
        its deadline. Counts only when this call actually expired it
        (the scoring path winning the race resolves the future first)."""
        if fut.done():
            return
        try:
            fut.set_exception(
                QueryDeadlineExceeded("query exceeded the per-query deadline")
            )
        except InvalidStateError:
            return
        self._count_deadline(path)

    def _query_with_deadline(
        self, body: dict[str, Any], variant: "_Variant | None" = None
    ) -> dict[str, Any]:
        """Unbatched scoring under the per-query deadline (a plain
        ``handle_query`` call when no deadline is configured — the
        zero-cost default path).

        With a deadline: scoring runs on a short-lived thread while a
        timer-wheel entry arms the 503 — the client is answered AT the
        deadline and an overrunning call finishes discarded (Python
        can't preempt it). The thread count is capped; past the cap —
        or before the HTTP loop starts — scoring runs inline and
        overruns are shed after the fact (same 503 + Retry-After, the
        response-freshness guarantee holds, only the early answer is
        lost)."""
        if self.query_deadline_s is None:
            return self.handle_query(body, variant)
        from concurrent.futures import Future

        fut: Future = Future()
        handle = self.app.call_later(
            self.query_deadline_s, lambda: self._expire_future(fut, "unbatched")
        )
        if handle is None or not self._ddl_slots.acquire(blocking=False):
            if handle is not None:
                handle.cancel()
            t0 = time.monotonic()
            result = self.handle_query(body, variant)
            if time.monotonic() - t0 > self.query_deadline_s:
                self._count_deadline("unbatched")
                raise QueryDeadlineExceeded(
                    "query exceeded the per-query deadline"
                )
            return result

        def run() -> None:
            try:
                r = self.handle_query(body, variant)
            except BaseException as e:
                if not fut.done():
                    try:
                        fut.set_exception(e)
                    except InvalidStateError:
                        pass
            else:
                if not fut.done():
                    try:
                        fut.set_result(r)
                    except InvalidStateError:
                        pass
            finally:
                self._ddl_slots.release()

        threading.Thread(target=run, daemon=True, name="query-ddl").start()
        try:
            return fut.result(timeout=self.query_deadline_s + 60.0)
        except FuturesTimeout:
            self._count_deadline("unbatched")
            raise QueryDeadlineExceeded(
                "query exceeded the per-query deadline"
            ) from None
        finally:
            handle.cancel()

    def handle_query(
        self, body: dict[str, Any], variant: "_Variant | None" = None
    ) -> dict[str, Any]:
        faults.fault_point("serve.query")
        v = variant if variant is not None else self._default_variant
        t0 = time.perf_counter()
        with self._lock:
            algorithms, models, serving = v.algorithms, v.models, v.serving
        query, supplemented = self._parse_query(body, algorithms, serving)
        predictions = [
            a.predict(m, supplemented) for a, m in zip(algorithms, models)
        ]
        # drain the two-stage retrieval stage split unconditionally (the
        # thread-local must not leak into the next query on this thread);
        # attach sub-spans when this request is traced
        from predictionio_tpu.ops import retrieval as _retrieval

        split = _retrieval.take_stage_split()
        if split is not None:
            tr = obs_trace.current_trace()
            if tr is not None:
                ss = split.get("shortlist", 0.0)
                rs = split.get("rescore", 0.0)
                tr.add_span("dispatch.shortlist", t0, t0 + ss)
                tr.add_span("dispatch.rescore", t0 + ss, t0 + ss + rs)
        return self._finish_query(
            body, query, predictions, serving, t0, variant=v
        )

    @staticmethod
    def _parse_query(body, algorithms, serving):
        query_class = algorithms[0].query_class
        query = _query_from_json(query_class, body)
        return query, serving.supplement(query)

    def _finish_query(
        self, body, query, predictions, serving, t0, trace=None, variant=None
    ) -> dict[str, Any]:
        """Per-query tail shared by the per-request and micro-batched
        paths: serve, feedback, plugins, bookkeeping. ``trace`` is passed
        explicitly from the batch worker (whose thread-local is not the
        request thread's); the per-request path falls back to it."""
        v = variant if variant is not None else self._default_variant
        if trace is None:
            trace = obs_trace.current_trace()
        result = serving.serve(query, predictions)
        response = _to_jsonable(result)

        pr_id: str | None = None
        if self.feedback:
            pr_id = body.get("prId") or uuid.uuid4().hex[:16]
            self._send_feedback(body, response, pr_id, trace=trace)
            if isinstance(response, dict):
                response = {**response, "prId": pr_id}

        for p in self.plugins:
            if p.plugin_type == plugin_mod.OUTPUT_BLOCKER:
                response = p.process(
                    v.instance.engine_variant, body, response, self.plugin_context
                )
            else:
                p.process(
                    v.instance.engine_variant, body, response, self.plugin_context
                )

        t_end = time.perf_counter()
        dt = t_end - t0
        self._m_serving.observe(dt)
        if v._m_serving_v is not None:
            v._m_serving_v.observe(dt)
        if v._m_requests_v is not None:
            v._m_requests_v.inc()
        if trace is not None:
            trace.add_span("serve", t0, t_end)
        with self._lock:
            v.request_count += 1
            v.serving_seconds += dt
            v.last_serving_sec = dt
        return response

    @staticmethod
    def _resolve(fut, predictions=None, exc=None) -> None:
        # the deadline timer may have expired the future already —
        # losing that race is normal, never an error
        if fut.done():
            return
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(predictions)
        except InvalidStateError:
            pass

    def _handle_query_batch(self, items) -> None:
        """Score one micro-batch, grouped by tenant mount: queries for
        different variants never share a ``batch_predict`` call (their
        models differ), but co-tenant queries still coalesce — and
        because every group pads to the same pow2 buckets, the jitted
        programs stay shared across tenants (compiles bounded by bucket
        count, not tenant count)."""
        groups: dict[int, list] = {}
        by_id: dict[int, Any] = {}
        for it in items:
            v = it[4]
            groups.setdefault(id(v), []).append(it)
            by_id[id(v)] = v
        for vid, group in groups.items():
            self._score_batch_group(by_id[vid], group)

    def _score_batch_group(self, variant: "_Variant", items) -> None:
        """Score one tenant's micro-batch: every algorithm runs ONE
        batch_predict over the whole batch; serving/feedback/plugins run
        per query on the REQUEST threads (the futures resolve to
        predictions, not responses). A single-item batch — an idle
        server's lone query — skips the padding/coalesce machinery and
        goes straight to ``predict``. A failing batch retries its
        queries individually so one bad request can't fail its
        batchmates."""
        with self._lock:
            algorithms, models = variant.algorithms, variant.models
        batcher = self.batcher
        t_collect = time.perf_counter()
        for fut, t0, tr, _, _ in items:
            if batcher is not None:
                batcher._m_queue_wait.observe(t_collect - t0)
            if tr is not None:
                tr.add_span("batch.queue_wait", t0, t_collect)
        if len(items) == 1:
            # FAST PATH: no padding, no index plumbing — lone-query
            # latency matches per-request serving
            fut, _, _, sup, _ = items[0]
            try:
                predictions = [
                    a.predict(m, sup) for a, m in zip(algorithms, models)
                ]
            except Exception as e:
                self._resolve(fut, exc=e)
                return
            self._resolve(fut, predictions)
            return
        per_algo: list[dict] | None
        try:
            indexed = [
                (i, sup) for i, (_, _, _, sup, _) in enumerate(items)
            ]
            # pad to a power-of-two batch size with copies of the first
            # query (padding results are discarded): jitted batch
            # programs specialize on the batch shape, and
            # traffic-dependent sizes would recompile per distinct size
            # — the stall the window exists to avoid
            n_real = len(indexed)
            pad_to = 1 << max(0, n_real - 1).bit_length()
            indexed = indexed + [
                (n_real + j, indexed[0][1]) for j in range(pad_to - n_real)
            ]
            t_d0 = time.perf_counter()
            faults.fault_point("serve.batch_dispatch")
            per_algo = [
                dict(a.batch_predict(m, indexed))
                for a, m in zip(algorithms, models)
            ]
            t_d1 = time.perf_counter()
            if batcher is not None:
                batcher._m_dispatch.observe(t_d1 - t_d0)
            # two-stage retrieval stage split for this dispatch (if the
            # batch went coarse+rescore): sub-spans let /traces.json show
            # where dispatch time went without a device round-trip
            from predictionio_tpu.ops import retrieval as _retrieval

            split = _retrieval.take_stage_split()
            for _, _, tr, _, _ in items:
                if tr is not None:
                    tr.add_span(f"batch.dispatch[{n_real}]", t_d0, t_d1)
                    if split is not None:
                        ss = split.get("shortlist", 0.0)
                        rs = split.get("rescore", 0.0)
                        tr.add_span("dispatch.shortlist", t_d0, t_d0 + ss)
                        tr.add_span(
                            "dispatch.rescore", t_d0 + ss, t_d0 + ss + rs
                        )
        except Exception:
            logger.exception("batched scoring failed; retrying per query")
            per_algo = None
        for i, (fut, t0, tr, sup, _) in enumerate(items):
            if per_algo is None:
                try:
                    predictions = [
                        a.predict(m, sup) for a, m in zip(algorithms, models)
                    ]
                except Exception as e:
                    self._resolve(fut, exc=e)
                    continue
            else:
                predictions = [d[i] for d in per_algo]
            self._resolve(fut, predictions)

    @staticmethod
    def _post_async(
        url: str,
        payload: bytes,
        what: str,
        headers: dict[str, str] | None = None,
    ) -> None:
        """Fire-and-forget POST on a daemon thread — failures are logged,
        never raised (feedback + remote-log transport)."""

        def post():
            try:
                req = urllib.request.Request(
                    url, data=payload, headers=headers or {}
                )
                urllib.request.urlopen(req, timeout=10).read()
            except Exception:
                logger.exception("%s POST failed", what)

        threading.Thread(target=post, daemon=True).start()

    def _send_feedback(
        self, query: dict, prediction: Any, pr_id: str, trace=None
    ) -> None:
        """Async predict-event POST back to the event server
        (CreateServer.scala:514-577)."""
        if not (self.event_server_url and self.access_key):
            logger.warning("feedback enabled but event server/access key missing")
            return
        payload = json.dumps(
            {
                "event": "predict",
                "entityType": "pio_pr",
                "entityId": pr_id,
                "properties": {"query": query, "prediction": prediction},
                "prId": pr_id,
            }
        ).encode()
        url = (
            f"{self.event_server_url.rstrip('/')}/events.json"
            f"?accessKey={self.access_key}"
        )
        headers = {"Content-Type": "application/json"}
        if trace is not None:
            # the event server's ingest hop joins this query's timeline
            headers[obs_trace.TRACE_HEADER] = trace.trace_id
        self._post_async(url, payload, "feedback event", headers=headers)

    def _remote_log(self, message: str) -> None:
        """Best-effort POST of a serving error to ``log_url`` (reference
        CreateServer.scala:422-433 remoteLog; fired on query failures at
        :596-618). Body is ``log_prefix`` + JSON {engineInstance,
        message}, like the reference's logPrefix + write(...)."""
        if not self.log_url:
            return
        payload = (
            self.log_prefix
            + json.dumps(
                {
                    "engineInstance": {
                        "id": self.instance.id,
                        "engineFactory": self.instance.engine_factory,
                        "engineVariant": self.instance.engine_variant,
                    },
                    "message": message,
                }
            )
        ).encode()
        self._post_async(self.log_url, payload, "remote log")

    # -- control -----------------------------------------------------------
    def reload(self, variant: "_Variant | None" = None) -> bool:
        """Swap a mount to its latest completed instance (reference
        /reload). Defaults to the default mount; the per-variant routes
        pass their own. Other mounts' epochs and cache partitions are
        untouched."""
        v = variant if variant is not None else self._default_variant
        latest = self.storage.get_metadata_engine_instances().get_latest_completed(
            v.instance.engine_id,
            v.instance.engine_version,
            v.instance.engine_variant,
        )
        if latest is None:
            return False
        # prepare_deploy runs OFF the server lock; the swap is atomic —
        # the old model keeps serving 200s through the whole reload. The
        # default mount goes through the server-level _load hook (tests
        # and plugins may wrap it); co-tenants load directly.
        if v is self._default_variant:
            self._load(latest)
        else:
            v._load(latest)
        return True

    # -- speed-layer hot patching -------------------------------------------
    def model_snapshot(self):
        """(instance_id, models, epoch) of the DEFAULT mount under the
        lock — the fenced read a fold-in starts from (solo-deploy compat;
        multi-tenant speed layers hold their _Variant directly)."""
        return self._default_variant.model_snapshot()

    def apply_patch(self, models, expected_epoch: int) -> bool:
        """Epoch-fenced swap of the default mount's model list
        (speed-layer hot patch). Returns False without touching anything
        when the epoch moved since the snapshot — the caller re-reads
        and re-folds."""
        return self._default_variant.apply_patch(models, expected_epoch)

    def status(self) -> dict[str, Any]:
        with self._lock:
            avg = (
                self.serving_seconds / self.request_count
                if self.request_count
                else 0.0
            )
            return {
                "status": "alive",
                "engineInstanceId": self.instance.id,
                "engineFactory": self.instance.engine_factory,
                "engineVariant": self.instance.engine_variant,
                "startTime": self.start_time,
                "requestCount": self.request_count,
                "avgServingSec": round(avg, 6),
                "lastServingSec": round(self.last_serving_sec, 6),
                "plugins": [p.plugin_name for p in self.plugins],
            }

    def _status_html(self) -> str:
        """Minimal render of the reference's HTML status page
        (CreateServer.scala:443-467, templates html.index): engine info,
        component params, serving stats."""
        import html as html_mod

        s = self.status()
        with self._lock:
            algo_rows = "".join(
                f"<tr><td>{html_mod.escape(type(a).__name__)}</td>"
                f"<td><pre>{html_mod.escape(str(p))}</pre></td></tr>"
                for a, (_, p) in zip(
                    self.algorithms, self.engine_params.algorithms
                )
            )
            serving_name = type(self.serving).__name__
        rows = "".join(
            f"<tr><th>{html_mod.escape(str(k))}</th>"
            f"<td>{html_mod.escape(str(v))}</td></tr>"
            for k, v in s.items()
        )
        return (
            "<!DOCTYPE html><html><head>"
            "<title>Engine Server at "
            f"{html_mod.escape(self.host)}</title></head><body>"
            f"<h1>Engine: {html_mod.escape(s['engineFactory'])}</h1>"
            f"<table border='1'>{rows}</table>"
            f"<h2>Algorithms</h2><table border='1'>"
            f"<tr><th>Class</th><th>Params</th></tr>{algo_rows}</table>"
            f"<h2>Serving</h2><p>{html_mod.escape(serving_name)}</p>"
            "</body></html>"
        )

    # -- routes ------------------------------------------------------------
    def _router(self) -> Router:
        router = Router()
        server = self

        @router.route("GET", "/")
        def status(request: Request) -> Response:
            # browsers get the reference's HTML status page
            # (CreateServer.scala:443-467 renders html.index); API
            # clients keep the JSON body
            if "text/html" in request.headers.get("accept", ""):
                return Response.html(server._status_html())
            return Response.json(server.status())

        @router.route("GET", "/stats.json")
        def stats(request: Request) -> Response:
            body = server.status()
            layer = server.speed_layer
            body["realtime"] = (
                layer.gauges() if layer is not None else {"enabled": False}
            )
            cache = server.query_cache
            body["cache"] = (
                {"enabled": True, **cache.gauges()}
                if cache is not None
                else {"enabled": False}
            )
            # per-mount rows: solo deploys get a one-entry block keyed by
            # the default mount name, so dashboards render one code path
            body["variants"] = {
                name: v.stats() for name, v in server.variants.items()
            }
            # additive: existing consumers keep their fields untouched
            body["obs"] = obs_metrics.stats_block()
            body["device"] = obs_device.device_block()
            body["freshness"] = obs_freshness.block()
            try:
                from predictionio_tpu.ops import retrieval as _retrieval

                body["retrieval"] = _retrieval.stats_block()
            except Exception:  # pragma: no cover - stats must never 500
                pass
            return Response.json(body)

        def _resolve_header_variant(request: Request) -> "_Variant | None":
            """Mount for a BARE-path request: the ``X-PIO-Variant``
            header when present (None for an unknown name -> 404), else
            the default mount."""
            name = request.headers.get("x-pio-variant")
            if name is None:
                return server._default_variant
            return server.variants.get(name)

        def _handle_queries(request: Request, v: "_Variant") -> Response:
            if server._swapping.is_set():
                obs_metrics.counter(
                    "pio_query_unavailable_total",
                    "Queries 503'd while unavailable",
                    reason="swap",
                ).inc()
                # a 503 burst must be visible in /traces.json, not just
                # as a counter — mark the request's trace
                tr = obs_trace.current_trace()
                if tr is not None:
                    now = time.perf_counter()
                    tr.add_span("serve.unavailable", now, now)
                return Response(
                    status=503,
                    body={"message": "model swap in progress; retry shortly"},
                    headers={"Retry-After": "1"},
                )
            body = request.json()
            if not isinstance(body, dict):
                return Response.error("request body must be a JSON object", 400)
            try:
                return Response.json_bytes(server.serve_query_bytes(body, v))
            except QueryDeadlineExceeded as e:
                obs_metrics.counter(
                    "pio_query_unavailable_total",
                    "Queries 503'd while unavailable",
                    reason="deadline",
                ).inc()
                # like the swap branch: a deadline 503 burst must be
                # visible in /traces.json, not just as a counter
                tr = obs_trace.current_trace()
                if tr is not None:
                    now = time.perf_counter()
                    tr.add_span("serve.unavailable", now, now)
                return Response(
                    status=503,
                    body={"message": str(e)},
                    headers={"Retry-After": "1"},
                )
            except (TypeError, KeyError, ValueError) as e:
                # reference: MappingException -> 400 + remote log
                # (CreateServer.scala:596-604)
                server._remote_log(
                    f"Query:\n{request.body.decode(errors='replace')}\n\n"
                    f"Error:\n{e}\n\n"
                )
                return Response.error(f"Your query is not valid. {e}", 400)
            except Exception as e:
                # reference: Throwable -> 500 + remote log (:605-618)
                logger.exception("serving failed")
                server._remote_log(
                    f"Query:\n{request.body.decode(errors='replace')}\n\n"
                    f"Error:\n{e}\n\n"
                )
                return Response.error(f"serving failed: {e}", 500)

        @router.route("POST", "/queries.json")
        def queries(request: Request) -> Response:
            v = _resolve_header_variant(request)
            if v is None:
                return Response.error(
                    "unknown engine variant "
                    f"{request.headers.get('x-pio-variant')!r}", 404
                )
            return _handle_queries(request, v)

        def _handle_reload(request: Request, v: "_Variant") -> Response:
            if not server._auth_control(request):
                return Response.error("Invalid accessKey.", 401)
            ok = server.reload(v)
            if not ok:
                return Response.error("no completed engine instance found", 404)
            return Response.json({"message": "Reloading..."})

        @router.route("POST", "/reload")
        def reload(request: Request) -> Response:
            v = _resolve_header_variant(request)
            if v is None:
                return Response.error(
                    "unknown engine variant "
                    f"{request.headers.get('x-pio-variant')!r}", 404
                )
            return _handle_reload(request, v)

        @router.route("POST", "/stop")
        def stop(request: Request) -> Response:
            if not server._auth_control(request):
                return Response.error("Invalid accessKey.", 401)
            response = Response.json({"message": "Shutting down..."})
            response.after_send = server.stop  # runs after the bytes flush
            return response

        @router.route("GET", "/plugins.json")
        def plugins_route(request: Request) -> Response:
            return Response.json(
                {
                    "plugins": {
                        p.plugin_name: {
                            "outputblocker": p.plugin_type
                            == plugin_mod.OUTPUT_BLOCKER,
                            "description": p.plugin_description,
                        }
                        for p in server.plugins
                    }
                }
            )

        @router.route("GET", "/plugins/<name>.json")
        def plugin_rest(request: Request) -> Response:
            name = request.path_params["name"]
            for p in server.plugins:
                if p.plugin_name == name:
                    return Response.json(p.handle_rest(dict(request.query)))
            return Response.error("plugin not found", 404)

        # path-prefix tenant routing: /<variant>/queries.json is the
        # load-balancer-friendly form of the X-PIO-Variant header. The
        # exact routes above win first (registration order), so a mount
        # can never shadow /plugins.json or /stats.json.
        @router.route("POST", "/<variant>/queries.json")
        def variant_queries(request: Request) -> Response:
            v = server.variants.get(request.path_params["variant"])
            if v is None:
                return Response.error(
                    f"unknown engine variant "
                    f"{request.path_params['variant']!r}", 404
                )
            return _handle_queries(request, v)

        @router.route("POST", "/<variant>/reload")
        def variant_reload(request: Request) -> Response:
            v = server.variants.get(request.path_params["variant"])
            if v is None:
                return Response.error(
                    f"unknown engine variant "
                    f"{request.path_params['variant']!r}", 404
                )
            return _handle_reload(request, v)

        add_obs_routes(router)
        return router

    def _auth_control(self, request: Request) -> bool:
        """/reload and /stop are guarded by the server key when set
        (reference common KeyAuthentication). When a ServerConfig is
        present its enforcement flag decides — an enforced-but-empty key
        still requires a matching (empty-string) param rather than
        silently disabling auth."""
        if self.server_config is not None:
            from predictionio_tpu.common import KeyAuthentication

            allowed = KeyAuthentication(self.server_config).authorized(request.query)
            if not allowed:
                return False
            if self.server_key is None:
                return True
        if not self.server_key:
            return True
        return request.query.get("accessKey") == self.server_key

    # -- lifecycle ---------------------------------------------------------
    def warmup(self) -> int:
        """Deploy-time AOT warmup: one throwaway ``batch_predict`` per
        algorithm BEFORE the port binds, so the first real query pays a
        scoring-program cache hit instead of an XLA compile (seconds on
        CPU, tens of seconds on TPU attachments). Queries come from each
        algorithm's ``warmup_query`` hook; failures are logged and
        swallowed — warmup must never block a deploy. Returns how many
        algorithms were warmed. Multi-tenant mounts warm every variant
        — co-tenants of one instance share the scoring-program cache, so
        repeats hit compiled programs and cost one throwaway score."""
        warmed = 0
        # normally warmup runs before the port binds, but reuse_port
        # workers and late warmups can overlap live traffic — those
        # queries get 503 + Retry-After instead of queueing behind the
        # warm-up compile
        self._swapping.set()
        try:
            for v in self.variants.values():
                with self._lock:
                    algorithms, models = v.algorithms, v.models
                for a, m in zip(algorithms, models):
                    try:
                        q = a.warmup_query(m)
                        if q is None:
                            continue
                        t0 = time.perf_counter()
                        a.batch_predict(m, [(0, q)])
                        logger.info(
                            "warmup: %s compiled+scored in %.3fs",
                            type(a).__name__, time.perf_counter() - t0,
                        )
                        warmed += 1
                    except Exception:
                        logger.exception(
                            "warmup predict failed for %s (serving unaffected)",
                            type(a).__name__,
                        )
        finally:
            self._swapping.clear()
        return warmed

    def _ready_reason(self) -> str | None:
        """The engine half of ``/readyz`` (the HTTPApp adds the draining
        check): warmup/model-swap fencing and a loaded model on every
        mount."""
        if self._swapping.is_set():
            return "model swap/warmup in progress"
        for v in self.variants.values():
            if not v.models:
                return f"no model loaded ({v.name})"
        return None

    def _drain_flush(self) -> None:
        for v in self.variants.values():
            if v.speed_layer is not None:
                v.speed_layer.stop()
        if self.batcher is not None:
            self.batcher.stop()

    def start(self, background: bool = True) -> int:
        port = self.app.start(background=background)
        logger.info("Engine Server listening on %s:%d", self.host, port)
        return port

    def drain(self) -> None:
        """Graceful shutdown: finish in-flight queries, flush the speed
        layer's cursor, then stop."""
        self.app.drain()

    def stop(self) -> None:
        for v in self.variants.values():
            if v.speed_layer is not None:
                v.speed_layer.stop()
        if self.batcher is not None:
            self.batcher.stop()
        self.app.stop()
