"""Dashboard: browse completed evaluation instances (default port 9000).

Capability parity with the reference dashboard
(tools/.../dashboard/Dashboard.scala:40-160): an index of completed
evaluations (most recent first) with links to per-instance
``evaluator_results.{txt,html,json}``.
"""

from __future__ import annotations

import html
import json
import logging

from predictionio_tpu.data.storage import Storage, get_storage
from predictionio_tpu.obs import device as obs_device
from predictionio_tpu.obs import history as obs_history
from predictionio_tpu.obs import progress as obs_progress
from predictionio_tpu.obs import slo as obs_slo
from predictionio_tpu.obs import trace as obs_trace
from predictionio_tpu.server.http import (
    HTTPApp,
    Request,
    Response,
    Router,
    add_obs_routes,
)

logger = logging.getLogger(__name__)


def _result_summary(instance) -> tuple[str, str]:
    """(metric scores, best params) cells for an evaluation row, parsed
    from the persisted ``evaluator_results_json`` (MetricEvaluatorResult
    .to_json) — empty cells when the instance predates the format or the
    JSON is malformed."""
    try:
        doc = json.loads(instance.evaluator_results_json or "")
    except (ValueError, TypeError):
        return "", ""
    if not isinstance(doc, dict) or "bestScore" not in doc:
        return "", ""
    scores = [f"{doc.get('metricHeader', 'metric')}: {doc['bestScore']:.4f}"]
    best_idx = doc.get("bestIndex", 0)
    candidates = doc.get("scores", [])
    if isinstance(candidates, list) and 0 <= best_idx < len(candidates):
        other = candidates[best_idx].get("otherScores", [])
        for header, val in zip(doc.get("otherMetricHeaders", []), other):
            try:
                scores.append(f"{header}: {float(val):.4f}")
            except (TypeError, ValueError):
                continue
    best = doc.get("bestEngineParams", {})
    # the algorithm params are the part a tuning sweep varies; the full
    # EngineParams JSON is a click away on the JSON results link
    params = best.get("algorithms", best) if isinstance(best, dict) else best
    params_str = json.dumps(params, sort_keys=True)
    if len(params_str) > 300:
        params_str = params_str[:300] + "…"
    return "<br>".join(html.escape(s) for s in scores), html.escape(params_str)


def render_waterfall(traces: list[dict], source: str) -> str:
    """Slowest-traces waterfall: one block per trace, one proportional
    bar per span (offset -> left margin, duration -> width). Pure
    inline-styled HTML — the dashboard ships no assets."""
    blocks = []
    for t in traces:
        total_ms = max(t.get("durationMs", 0.0), 1e-6)
        rows = []
        for s in t.get("spans", []):
            left = 100.0 * s.get("offsetMs", 0.0) / total_ms
            width = max(100.0 * s.get("durationMs", 0.0) / total_ms, 0.3)
            width = min(width, 100.0 - min(left, 99.7))
            rows.append(
                "<tr>"
                f"<td style='white-space:nowrap;padding:1px 8px 1px 2px;"
                f"font-family:monospace'>{html.escape(str(s.get('name', '')))}"
                f"</td>"
                f"<td style='width:70%'><div style='margin-left:{left:.2f}%;"
                f"width:{width:.2f}%;background:#4a90d9;height:12px;"
                f"min-width:2px'></div></td>"
                f"<td style='font-family:monospace;text-align:right'>"
                f"{s.get('durationMs', 0.0):.3f} ms</td>"
                "</tr>"
            )
        blocks.append(
            f"<h3 style='margin-bottom:2px'>{html.escape(str(t.get('name', '')))}"
            f" — {total_ms:.3f} ms"
            f" <small>(trace {html.escape(str(t.get('traceId', '')))},"
            f" status {html.escape(str(t.get('status')))})</small></h3>"
            f"<table style='width:100%;border-collapse:collapse'>"
            f"{''.join(rows)}</table>"
        )
    body = "".join(blocks) or "<p>No traces retained yet.</p>"
    return (
        "<html><head><title>Slowest traces</title></head><body>"
        f"<h1>Slowest recent traces</h1>"
        f"<p>source: {html.escape(source)} — slowest first; the ring "
        "retains outliers, not a uniform sample. Fetch another server "
        "with <code>?src=http://host:port</code>.</p>"
        f"{body}</body></html>"
    )


def render_sparkline(
    points: list, width: int = 160, height: int = 28, color: str = "#4a90d9"
) -> str:
    """One bounded metrics-history series as an inline SVG polyline
    (no assets, like everything else here). ``points`` is the
    ``/history.json`` shape: ``[[t_ms, value], ...]``."""
    vals = [float(p[1]) for p in points]
    if not vals:
        return "<span style='color:#999'>no samples</span>"
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    n = len(vals)
    step = width / max(n - 1, 1)
    pts = " ".join(
        f"{i * step:.1f},{height - 2 - (v - lo) / span * (height - 4):.1f}"
        for i, v in enumerate(vals)
    )
    return (
        f"<svg width='{width}' height='{height}' "
        f"style='vertical-align:middle'>"
        f"<polyline points='{pts}' fill='none' stroke='{color}' "
        f"stroke-width='1.5'/></svg>"
    )


def render_history_rows(hist: dict, contains: str = "", limit: int = 12) -> str:
    """Sparkline table rows for every history series whose key contains
    ``contains``: key | sparkline | latest value. Empty string when the
    history layer has nothing matching (obs off, or no samples yet)."""
    rows = []
    for key, doc in hist.get("series", {}).items():
        if contains and contains not in key:
            continue
        points = doc.get("points", [])
        if not points:
            continue
        latest = points[-1][1]
        unit = " Δ/step" if doc.get("kind") == "delta" else ""
        rows.append(
            f"<tr><td style='font-family:monospace;padding:1px 8px'>"
            f"{html.escape(key)}</td>"
            f"<td>{render_sparkline(points)}</td>"
            f"<td style='font-family:monospace;text-align:right'>"
            f"{latest:g}{unit}</td></tr>"
        )
        if len(rows) >= limit:
            break
    if not rows:
        return ""
    return (
        "<table style='border-collapse:collapse'>" + "".join(rows) + "</table>"
    )


def render_alerts_table(alerts: list[dict], limit: int = 10) -> str:
    """The SLO alert ring (newest first): time, objective, transition,
    burn — the same record ``pio status --json`` surfaces."""
    rows = "".join(
        f"<tr><td>{a.get('t')}</td>"
        f"<td>{html.escape(str(a.get('slo')))}</td>"
        f"<td>{html.escape(str(a.get('from')))} &rarr; "
        f"{html.escape(str(a.get('to')))}</td>"
        f"<td>{a.get('burn_fast')}</td><td>{a.get('burn_slow')}</td></tr>"
        for a in list(reversed(alerts))[:limit]
    )
    if not rows:
        return "<p>No SLO state transitions recorded.</p>"
    return (
        "<table border='1' cellpadding='4'>"
        "<tr><th>t</th><th>Objective</th><th>Transition</th>"
        f"<th>Burn (fast)</th><th>Burn (slow)</th></tr>{rows}</table>"
    )


_SLO_COLORS = {"ok": "#2a2", "burning": "#c80", "violated": "#c22"}


def render_slo_panel(doc: dict, source: str, hist: dict | None = None) -> str:
    """SLO state table (objective, state, burn fast/slow, SLI, current)
    plus the alert ring, color-coded by state, and — when a history
    snapshot is supplied — burn-rate sparklines per objective."""
    rows = []
    for s in doc.get("slos", []):
        state = str(s.get("state", "?"))
        color = _SLO_COLORS.get(state, "#888")
        rows.append(
            f"<tr><td>{html.escape(str(s.get('name')))}</td>"
            f"<td>{html.escape(str(s.get('kind', '')))}</td>"
            f"<td style='color:{color};font-weight:bold'>"
            f"{html.escape(state.upper())}</td>"
            f"<td>{s.get('objective', '')}</td>"
            f"<td>{s.get('burn_fast', '')}</td>"
            f"<td>{s.get('burn_slow', '')}</td>"
            f"<td>{s.get('sli_slow', '')}</td>"
            f"<td>{s.get('current', '')}</td></tr>"
        )
    body = (
        f"<p>No SLOs registered on {html.escape(source)}.</p>"
        if not rows
        else (
            "<table border='1' cellpadding='4'>"
            "<tr><th>Objective</th><th>Kind</th><th>State</th>"
            "<th>Target</th><th>Burn (fast)</th><th>Burn (slow)</th>"
            "<th>SLI (slow)</th><th>Current</th></tr>"
            + "".join(rows) + "</table>"
        )
    )
    alerts = "<h2>Alerts</h2>" + render_alerts_table(doc.get("alerts", []))
    burn = ""
    if hist:
        spark = render_history_rows(hist, "pio_slo_burn_rate")
        if spark:
            burn = "<h2>Burn-rate history</h2>" + spark
    return (
        "<html><head><title>SLOs</title></head><body>"
        f"<h1>SLOs</h1><p>source: {html.escape(source)}</p>"
        f"{body}{burn}{alerts}</body></html>"
    )


def _kv_table(rows: list[tuple[str, str]]) -> str:
    return (
        "<table border='1' style='border-collapse:collapse'>"
        + "".join(
            f"<tr><th style='text-align:left;padding:2px 8px'>"
            f"{html.escape(k)}</th>"
            f"<td style='font-family:monospace;padding:2px 8px'>"
            f"{html.escape(v)}</td></tr>"
            for k, v in rows
        )
        + "</table>"
    )


def render_device_panel(
    block: dict,
    progress: dict | None,
    source: str,
    hist: dict | None = None,
    retrieval: dict | None = None,
) -> str:
    """Device telemetry panel: per-device memory, transfer byte totals,
    the compile tracker table, history sparklines for the device-side
    series, two-stage retrieval counters when the source serves them,
    and — while a checkpointed ``pio train`` is live on this
    host — its progress."""
    sections = []
    devices = block.get("devices") or []
    if devices:
        rows = []
        for d in devices:
            mem = d.get("memory")
            mem_str = (
                f"in_use {mem['in_use']:,} / limit {mem['limit']:,} "
                f"(peak {mem['peak']:,})"
                if mem
                else "no allocator stats (CPU backend)"
            )
            rows.append((f"{d.get('device')} {d.get('kind', '')}".strip(), mem_str))
        sections.append("<h2>Devices</h2>" + _kv_table(rows))
    else:
        sections.append(
            "<h2>Devices</h2><p>jax not initialized in this process.</p>"
        )
    transfers = block.get("transfer_bytes") or {}
    if transfers:
        sections.append(
            "<h2>Host&harr;device transfers</h2>"
            + _kv_table([(k, f"{v:,} bytes") for k, v in transfers.items()])
        )
    jit = block.get("jit") or {}
    if jit:
        sections.append(
            "<h2>Compile tracker</h2>"
            + _kv_table(
                [
                    (
                        name,
                        f"calls {s['calls']:,}, compiles {s['compiles']:,}, "
                        f"cache hits {s['cache_hits']:,}",
                    )
                    for name, s in jit.items()
                ]
            )
        )
    if progress is not None:
        rows = [
            (
                "iteration",
                f"{progress.get('iteration')}/{progress.get('total_iterations')}",
            )
        ]
        if progress.get("eta_s") is not None:
            rows.append(("ETA", f"{progress['eta_s']}s"))
        if progress.get("rmse"):
            rows.append(("RMSE", str(progress["rmse"][-1])))
        if progress.get("events_per_s"):
            rows.append(("events/s", f"{progress['events_per_s']:,.0f}"))
        if progress.get("mesh"):
            rows.append(("mesh", str(progress["mesh"])))
        sections.append("<h2>Training in progress</h2>" + _kv_table(rows))
    if retrieval:
        rows = [
            ("threshold", f"{retrieval.get('threshold', 0):,} rows"),
            ("oversample", str(retrieval.get("oversample", ""))),
            (
                "queries (two-stage / exact)",
                f"{retrieval.get('two_stage_queries', 0):,} / "
                f"{retrieval.get('exact_queries', 0):,}",
            ),
        ]
        size = retrieval.get("shortlist_size") or {}
        if size.get("count"):
            rows.append(
                (
                    "shortlist size p50/p99",
                    f"{size.get('p50', 0):,.0f} / {size.get('p99', 0):,.0f}",
                )
            )
        for stage in ("shortlist", "rescore"):
            s = retrieval.get(f"{stage}_seconds") or {}
            if s.get("count"):
                rows.append(
                    (
                        f"{stage} p50/p99",
                        f"{s.get('p50', 0) * 1e3:.2f}ms / "
                        f"{s.get('p99', 0) * 1e3:.2f}ms",
                    )
                )
        if retrieval.get("probes"):
            rows.append(
                (
                    "live recall probe (sampled)",
                    f"{retrieval.get('probe_recall', 0):.4f} "
                    f"({retrieval['probes']:,} probes)",
                )
            )
        sections.append("<h2>Two-stage retrieval</h2>" + _kv_table(rows))
    if hist:
        spark = render_history_rows(hist, "pio_device") or render_history_rows(
            hist, "pio_jit"
        )
        if spark:
            sections.append("<h2>Device history</h2>" + spark)
    return (
        "<html><head><title>Device telemetry</title></head><body>"
        "<h1>Device telemetry</h1>"
        f"<p>source: {html.escape(source)}. Fetch a serving process "
        "with <code>?src=http://host:port</code> (reads its "
        "/stats.json device block).</p>"
        f"{''.join(sections)}</body></html>"
    )


def _fetch_src_json(src: str, path: str) -> dict | None:
    """Best-effort server-side fetch of another server's obs endpoint
    (the serving processes don't speak CORS, so the browser can't)."""
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"{src.rstrip('/')}{path}", timeout=2
        ) as resp:
            return json.loads(resp.read())
    except Exception:
        return None


class Dashboard:
    def __init__(
        self,
        storage: Storage | None = None,
        host: str = "0.0.0.0",
        port: int = 9000,
        server_config=None,
    ):
        self.storage = storage or get_storage()
        self.host = host
        # dashboard pages are server-key-authenticated when enforced
        # (Dashboard.scala wraps routes in withAccessKeyFromFile)
        self.server_config = server_config
        self.app = HTTPApp(
            self._router(),
            host=host,
            port=port,
            ssl_context=(
                server_config.ssl_context() if server_config is not None else None
            ),
            name="dashboard",
        )

    def _authorized(self, request: Request) -> bool:
        if self.server_config is None:
            return True
        from predictionio_tpu.common import KeyAuthentication

        return KeyAuthentication(self.server_config).authorized(request.query)

    def _router(self) -> Router:
        # CORS on all dashboard routes (reference dashboard CORSSupport)
        router = Router(cors=True)
        server = self

        @router.route("GET", "/")
        def index(request: Request) -> Response:
            if not server._authorized(request):
                return Response.error("Not authenticated", 401)
            instances = server.storage.get_metadata_evaluation_instances().get_completed()
            cells = []
            for i in instances:
                scores_cell, params_cell = _result_summary(i)
                cells.append(
                    f"<tr><td>{html.escape(i.id)}</td>"
                    f"<td>{html.escape(i.evaluation_class)}</td>"
                    f"<td>{i.start_time:%Y-%m-%d %H:%M:%S}</td>"
                    f"<td>{i.end_time:%Y-%m-%d %H:%M:%S}</td>"
                    f"<td>{html.escape(i.evaluator_results)}</td>"
                    f"<td>{scores_cell}</td>"
                    f"<td><pre>{params_cell}</pre></td>"
                    f"<td><a href='/engine_instances/{i.id}/evaluator_results.txt'>txt</a> "
                    f"<a href='/engine_instances/{i.id}/evaluator_results.html'>HTML</a> "
                    f"<a href='/engine_instances/{i.id}/evaluator_results.json'>JSON</a>"
                    f"</td></tr>"
                )
            rows = "".join(cells)
            # operations strip: recent SLO alerts + request-rate
            # sparklines — ?src= aims them at a live serving process
            src = request.query.get("src")
            variants = {}
            replicas = {}
            routing = {}
            if src and src.startswith(("http://", "https://")):
                slo_doc = _fetch_src_json(src, "/slo.json") or {}
                hist = _fetch_src_json(src, "/history.json") or {}
                stats = _fetch_src_json(src, "/stats.json") or {}
                # one row per mounted tenant of a multi-tenant engine
                # server; solo deploys show no table
                v = stats.get("variants")
                if isinstance(v, dict) and len(v) > 1:
                    variants = v
                # router tier (?src= at a `pio route` process): one row
                # per pool replica plus the hedging counters
                r = stats.get("replicas")
                if isinstance(r, dict) and r:
                    replicas = r
                    routing = stats.get("routing") or {}
                ops_source = src
            else:
                slo_doc = obs_slo.document()
                hist = obs_history.snapshot()
                ops_source = "this dashboard process"
            spark = render_history_rows(hist, "pio_http_requests_total")
            variants_html = ""
            if variants:
                vrows = "".join(
                    "<tr>"
                    f"<td>{html.escape(str(name))}</td>"
                    f"<td>{v.get('requestCount', 0)}</td>"
                    f"<td>{v.get('p99Ms', '-')}</td>"
                    f"<td>{v.get('epoch', '-')}</td>"
                    f"<td>{v.get('foldinEpoch', '-')}</td>"
                    f"<td>{v.get('secondsBehind', '-')}</td>"
                    f"<td>{v.get('modelAgeSec', '-')}</td>"
                    "</tr>"
                    for name, v in variants.items()
                )
                variants_html = (
                    "<h3>Engine variants</h3>"
                    "<table border='1'><tr><th>Mount</th><th>Requests</th>"
                    "<th>p99 ms</th><th>Epoch</th><th>Fold-ins</th>"
                    "<th>Behind s</th><th>Model age s</th></tr>"
                    f"{vrows}</table>"
                )
            replicas_html = ""
            if replicas:
                rrows = "".join(
                    "<tr>"
                    f"<td>{html.escape(str(name))}</td>"
                    f"<td>{html.escape(str(r.get('state', '?')))}</td>"
                    f"<td>{r.get('inflight', 0)}</td>"
                    f"<td>{r.get('p99Ms', '-')}</td>"
                    f"<td>{r.get('requests', 0)}</td>"
                    f"<td>{r.get('ejections', 0)}</td>"
                    f"<td>{html.escape(str(r.get('instance') or '-'))}</td>"
                    "</tr>"
                    for name, r in replicas.items()
                )
                replicas_html = (
                    "<h3>Router replicas</h3>"
                    "<table border='1'><tr><th>Replica</th><th>State</th>"
                    "<th>Inflight</th><th>p99 ms</th><th>Requests</th>"
                    "<th>Ejections</th><th>Instance</th></tr>"
                    f"{rrows}</table>"
                    "<p>routing: "
                    f"{routing.get('requests', 0)} requests, "
                    f"{routing.get('retries', 0)} retries, "
                    f"{routing.get('hedges', 0)} hedges "
                    f"({routing.get('hedge_win_ratio', 0)} win ratio, "
                    f"delay {routing.get('hedge_delay_ms', '-')} ms)</p>"
                )
            ops = (
                f"<h2>Operations <small>({html.escape(ops_source)})</small>"
                "</h2>"
                "<p><a href='/slo'>SLOs</a> · <a href='/traces'>traces</a> "
                "· <a href='/device'>device</a> · "
                "<a href='/history.json'>history.json</a> — append "
                "<code>?src=http://host:port</code> for a live server.</p>"
                "<h3>Recent SLO alerts</h3>"
                + render_alerts_table(slo_doc.get("alerts", []))
                + variants_html
                + replicas_html
                + (
                    "<h3>Request rate (per history step)</h3>" + spark
                    if spark
                    else ""
                )
            )
            page = (
                "<html><head><title>PredictionIO-TPU Dashboard</title></head>"
                "<body><h1>Completed evaluations</h1>"
                "<table border='1'><tr><th>ID</th><th>Evaluation</th>"
                "<th>Started</th><th>Finished</th><th>One-liner</th>"
                "<th>Metric scores</th><th>Best params</th>"
                f"<th>Results</th></tr>{rows}</table>{ops}</body></html>"
            )
            return Response.html(page)

        @router.route("GET", "/engine_instances/<iid>/evaluator_results.txt")
        def results_txt(request: Request) -> Response:
            if not server._authorized(request):
                return Response.error("Not authenticated", 401)
            i = server._get(request.path_params["iid"])
            if i is None:
                return Response.error("Not Found", 404)
            return Response(
                200, ("text/plain; charset=utf-8", i.evaluator_results.encode())
            )

        @router.route("GET", "/engine_instances/<iid>/evaluator_results.html")
        def results_html(request: Request) -> Response:
            if not server._authorized(request):
                return Response.error("Not authenticated", 401)
            i = server._get(request.path_params["iid"])
            if i is None:
                return Response.error("Not Found", 404)
            return Response.html(i.evaluator_results_html or "<html></html>")

        @router.route("GET", "/engine_instances/<iid>/evaluator_results.json")
        def results_json(request: Request) -> Response:
            if not server._authorized(request):
                return Response.error("Not authenticated", 401)
            i = server._get(request.path_params["iid"])
            if i is None:
                return Response.error("Not Found", 404)
            return Response(
                200,
                (
                    "application/json; charset=utf-8",
                    (i.evaluator_results_json or "{}").encode(),
                ),
            )

        @router.route("GET", "/traces")
        def traces_page(request: Request) -> Response:
            """Waterfall of this process's slowest traces, or — with
            ``?src=http://host:port`` — of another server's
            ``/traces.json`` fetched server-side (the engine/event
            servers don't speak CORS, so the browser can't)."""
            if not server._authorized(request):
                return Response.error("Not authenticated", 401)
            src = request.query.get("src")
            if src:
                if not src.startswith(("http://", "https://")):
                    return Response.error("src must be an http(s) URL", 400)
                import urllib.request

                try:
                    with urllib.request.urlopen(
                        f"{src.rstrip('/')}/traces.json", timeout=2
                    ) as resp:
                        traces = json.loads(resp.read()).get("traces", [])
                except Exception as e:
                    return Response.error(f"fetch from {src} failed: {e}", 502)
                source = src
            else:
                traces = obs_trace.TRACES.snapshot()
                source = "this dashboard process"
            return Response.html(render_waterfall(traces, source))

        @router.route("GET", "/device")
        def device_page(request: Request) -> Response:
            """Device telemetry panel: this process's device block (the
            dashboard is usually jax-free, so mostly useful with
            ``?src=`` pointing at an engine server), plus any live
            training progress on this host."""
            if not server._authorized(request):
                return Response.error("Not authenticated", 401)
            src = request.query.get("src")
            if src:
                if not src.startswith(("http://", "https://")):
                    return Response.error("src must be an http(s) URL", 400)
                import urllib.request

                try:
                    with urllib.request.urlopen(
                        f"{src.rstrip('/')}/stats.json", timeout=2
                    ) as resp:
                        stats = json.loads(resp.read())
                        block = stats.get("device", {})
                        retrieval = stats.get("retrieval")
                except Exception as e:
                    return Response.error(f"fetch from {src} failed: {e}", 502)
                hist = _fetch_src_json(src, "/history.json")
                source = src
            else:
                block = obs_device.device_block()
                hist = obs_history.snapshot()
                source = "this dashboard process"
                try:
                    from predictionio_tpu.ops import retrieval as _r

                    retrieval = _r.stats_block()
                except Exception:
                    retrieval = None
            doc = obs_progress.read_progress()
            progress = doc if obs_progress.is_live(doc) else None
            return Response.html(
                render_device_panel(
                    block, progress, source, hist=hist, retrieval=retrieval
                )
            )

        @router.route("GET", "/slo")
        def slo_page(request: Request) -> Response:
            """SLO state panel: this process's objectives (the dashboard
            usually has none), or — with ``?src=http://host:port`` — a
            live server's ``/slo.json`` fetched server-side."""
            if not server._authorized(request):
                return Response.error("Not authenticated", 401)
            src = request.query.get("src")
            if src:
                if not src.startswith(("http://", "https://")):
                    return Response.error("src must be an http(s) URL", 400)
                import urllib.request

                try:
                    with urllib.request.urlopen(
                        f"{src.rstrip('/')}/slo.json", timeout=2
                    ) as resp:
                        doc = json.loads(resp.read())
                except Exception as e:
                    return Response.error(f"fetch from {src} failed: {e}", 502)
                hist = _fetch_src_json(src, "/history.json")
                source = src
            else:
                doc = obs_slo.document()
                hist = obs_history.snapshot()
                source = "this dashboard process"
            return Response.html(render_slo_panel(doc, source, hist=hist))

        add_obs_routes(router)
        return router

    def _get(self, iid: str):
        return self.storage.get_metadata_evaluation_instances().get(iid)

    def start(self, background: bool = True) -> int:
        port = self.app.start(background=background)
        logger.info("Dashboard listening on %s:%d", self.host, port)
        return port

    def stop(self) -> None:
        self.app.stop()
