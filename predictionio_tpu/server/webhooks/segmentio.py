"""Segment.io webhook connector.

Parity with the reference SegmentIOConnector
(data/.../webhooks/segmentio/SegmentIOConnector.scala:24-186): supports
the identify/track/alias/page/screen/group message types, maps
userId-or-anonymousId to the ``user`` entity, carries type-specific
fields plus optional ``context`` into properties, and authenticates with
the shared-secret HTTP basic scheme (SegmentIOAuthSpec)."""

from __future__ import annotations

from typing import Any, Mapping

from predictionio_tpu.server.webhooks import ConnectorError, JsonConnector


class SegmentIOConnector(JsonConnector):
    def to_event_json(self, data: Mapping[str, Any]) -> dict[str, Any]:
        if "version" not in data:
            raise ConnectorError("Failed to get segment.io API version.")
        msg_type = data.get("type")
        user_id = data.get("userId") or data.get("anonymousId")
        if not user_id:
            raise ConnectorError(
                "there was no `userId` or `anonymousId` in the common fields."
            )

        if msg_type == "identify":
            props: dict[str, Any] = {"traits": data.get("traits")}
        elif msg_type == "track":
            props = {
                "properties": data.get("properties"),
                "event": data.get("event"),
            }
        elif msg_type == "alias":
            props = {"previous_id": data.get("previousId") or data.get("previous_id")}
        elif msg_type == "page":
            props = {"name": data.get("name"), "properties": data.get("properties")}
        elif msg_type == "screen":
            props = {"name": data.get("name"), "properties": data.get("properties")}
        elif msg_type == "group":
            props = {
                "group_id": data.get("groupId") or data.get("group_id"),
                "traits": data.get("traits"),
            }
        else:
            raise ConnectorError(
                f"Cannot convert unknown type {msg_type} to event JSON."
            )

        if data.get("context") is not None:
            props["context"] = data["context"]
        props = {k: v for k, v in props.items() if v is not None}

        event_json: dict[str, Any] = {
            "event": msg_type,
            "entityType": "user",
            "entityId": user_id,
            "properties": props,
        }
        if data.get("timestamp"):
            event_json["eventTime"] = data["timestamp"]
        return event_json
