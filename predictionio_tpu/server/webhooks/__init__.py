"""Webhook connectors: third-party payloads -> events.

Parity with the reference webhooks subsystem
(data/.../webhooks/{JsonConnector,FormConnector}.scala:24-25, dispatch in
api/Webhooks.scala, registry in api/WebhooksConnectors.scala). A JSON
connector converts a JSON payload to event JSON; a form connector
converts urlencoded form data. Shipped connectors: Segment.io (JSON, with
shared-secret auth) and MailChimp (form).
"""

from __future__ import annotations

import abc
from typing import Any, Mapping


class ConnectorError(ValueError):
    """Raised when a payload cannot be converted (reference
    ConnectorException)."""


class JsonConnector(abc.ABC):
    @abc.abstractmethod
    def to_event_json(self, data: Mapping[str, Any]) -> dict[str, Any]: ...


class FormConnector(abc.ABC):
    @abc.abstractmethod
    def to_event_json(self, data: Mapping[str, str]) -> dict[str, Any]: ...


def default_connectors() -> dict[str, JsonConnector | FormConnector]:
    """Name -> connector registry (reference WebhooksConnectors.scala)."""
    from predictionio_tpu.server.webhooks.mailchimp import MailChimpConnector
    from predictionio_tpu.server.webhooks.segmentio import SegmentIOConnector

    return {
        "segmentio": SegmentIOConnector(),
        "mailchimp": MailChimpConnector(),
    }
