"""MailChimp webhook connector (form flavor).

Parity with the reference MailChimpConnector
(data/.../webhooks/mailchimp/MailChimpConnector.scala:32-330): converts
the subscribe/unsubscribe/profile/upemail/cleaned/campaign form payloads
into events keyed on the list member (or list/campaign for
cleaned/campaign)."""

from __future__ import annotations

from datetime import datetime, timezone
from typing import Any, Mapping

from predictionio_tpu.server.webhooks import ConnectorError, FormConnector


def _parse_time(s: str) -> str:
    # MailChimp format: "2009-03-26 21:35:57" (UTC)
    try:
        dt = datetime.strptime(s, "%Y-%m-%d %H:%M:%S").replace(tzinfo=timezone.utc)
    except ValueError as e:
        raise ConnectorError(f"cannot parse MailChimp fired_at {s!r}") from e
    return dt.strftime("%Y-%m-%dT%H:%M:%S.000Z")


class MailChimpConnector(FormConnector):
    SUPPORTED = ("subscribe", "unsubscribe", "profile", "upemail", "cleaned", "campaign")

    def to_event_json(self, data: Mapping[str, str]) -> dict[str, Any]:
        event_type = data.get("type")
        if event_type not in self.SUPPORTED:
            raise ConnectorError(
                f"Cannot convert unknown MailChimp type {event_type!r}"
            )
        if "fired_at" not in data:
            raise ConnectorError("MailChimp payload missing fired_at")
        event_time = _parse_time(data["fired_at"])

        def props(*keys: str) -> dict[str, str]:
            return {k.split("[", 1)[1].rstrip("]"): data[k] for k in keys if k in data}

        if event_type in ("subscribe", "unsubscribe", "profile"):
            return {
                "event": event_type,
                "entityType": "user",
                "entityId": data["data[id]"],
                "targetEntityType": "list",
                "targetEntityId": data["data[list_id]"],
                "eventTime": event_time,
                "properties": props(
                    "data[email]",
                    "data[email_type]",
                    "data[merges][FNAME]",
                    "data[merges][LNAME]",
                    "data[ip_opt]",
                    "data[ip_signup]",
                    "data[reason]",
                    "data[campaign_id]",
                ),
            }
        if event_type == "upemail":
            return {
                "event": event_type,
                "entityType": "user",
                "entityId": data["data[new_id]"],
                "targetEntityType": "list",
                "targetEntityId": data["data[list_id]"],
                "eventTime": event_time,
                "properties": props(
                    "data[new_email]", "data[old_email]"
                ),
            }
        if event_type == "cleaned":
            return {
                "event": event_type,
                "entityType": "list",
                "entityId": data["data[list_id]"],
                "eventTime": event_time,
                "properties": props("data[campaign_id]", "data[reason]", "data[email]"),
            }
        # campaign
        return {
            "event": event_type,
            "entityType": "campaign",
            "entityId": data["data[id]"],
            "targetEntityType": "list",
            "targetEntityId": data["data[list_id]"],
            "eventTime": event_time,
            "properties": props(
                "data[subject]", "data[status]", "data[reason]"
            ),
        }
