"""Event Server: REST ingestion API (default port 7070).

Capability parity with the reference Event Server
(data/.../api/EventServer.scala:54-663):

- ``GET /``                       welcome status
- ``POST /events.json``           single event, 201 Created + eventId
- ``GET /events.json``            query (startTime/untilTime/entityType/
                                  entityId/event/targetEntityType/
                                  targetEntityId/limit/reversed)
- ``GET|DELETE /events/<id>.json`` point read/delete
- ``POST /batch/events.json``     at most **50** events per request
                                  (:376-390), per-event status list
- ``GET /stats.json``             ingestion stats (when enabled)
- ``GET /plugins.json``           loaded plugin inventory (:156-177)
- ``GET|POST /plugins/<type>/<name>/<args...>`` plugin REST dispatch
                                  (:178-196, PluginsActor.scala)
- ``POST /webhooks/<name>.json``  JSON webhooks; ``.form`` form flavor
- ``GET /webhooks/<name>.json``   connector presence check

Auth mirrors the reference: per-app ``accessKey`` via query param or
HTTP basic username, optional ``channel`` query param resolved against
the app's channels, per-key event-name allowlists
(api/EventServer.scala:92-150). Input blocker/sniffer plugins intercept
ingestion.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from datetime import datetime, timezone
from typing import Any

from predictionio_tpu.data.event import (
    Event,
    EventValidationError,
    format_time,
    parse_time,
    validate,
)
from predictionio_tpu.data.storage import AccessKey, Storage, get_storage
from predictionio_tpu.data.storage import frame as frame_mod
from predictionio_tpu.obs import device as obs_device
from predictionio_tpu.obs import history as obs_history
from predictionio_tpu.obs import metrics as obs_metrics
from predictionio_tpu.obs import slo as obs_slo
from predictionio_tpu.obs import trace as obs_trace
from predictionio_tpu.server import plugins as plugin_mod
from predictionio_tpu.server.http import (
    HTTPApp,
    Request,
    Response,
    Router,
    add_obs_routes,
)
from predictionio_tpu.server.stats import Stats
from predictionio_tpu.server.webhooks import (
    ConnectorError,
    FormConnector,
    JsonConnector,
    default_connectors,
)

logger = logging.getLogger(__name__)

MAX_BATCH_SIZE = 50  # reference EventServer.scala:70


def _batch_max_events() -> int:
    """``PIO_BATCH_MAX_EVENTS`` knob for ``POST /batch/events.json``
    (default keeps the reference-compatible 50)."""
    raw = os.environ.get("PIO_BATCH_MAX_EVENTS", "").strip()
    try:
        return max(1, int(raw)) if raw else MAX_BATCH_SIZE
    except ValueError:
        return MAX_BATCH_SIZE


class _InflightBudget:
    """Bounded in-flight ingest bytes (``PIO_INGEST_MAX_INFLIGHT_MB``).

    Admission control for the batch endpoints: a request acquires its
    Content-Length before its body is processed (for the binary stream
    route, before the body is even READ off the socket) and releases it
    when done. A request that doesn't fit is shed with 429+Retry-After —
    explicit backpressure instead of an unbounded group-commit queue.
    An oversized request is still admitted when the budget is idle, so
    a single body larger than the whole budget stays servable."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max(1, int(max_bytes))
        self.in_flight = 0
        self._lock = threading.Lock()

    def try_acquire(self, n: int) -> bool:
        with self._lock:
            if self.in_flight > 0 and self.in_flight + n > self.max_bytes:
                return False
            self.in_flight += n
            return True

    def release(self, n: int) -> None:
        with self._lock:
            self.in_flight = max(0, self.in_flight - n)

    def utilization(self) -> float:
        with self._lock:
            return self.in_flight / self.max_bytes


@dataclass
class AuthData:
    app_id: int
    channel_id: int | None
    events: list[str]


class EventServer:
    def __init__(
        self,
        storage: Storage | None = None,
        host: str = "0.0.0.0",
        port: int = 7070,
        stats: bool = False,
        connectors: dict | None = None,
        reuse_port: bool = False,
    ):
        self.storage = storage or get_storage()
        self.stats_enabled = stats
        self.stats = Stats()
        self.connectors = (
            connectors if connectors is not None else default_connectors()
        )
        self.plugins = plugin_mod.load_plugins(plugin_mod.EventServerPlugin)
        self.plugin_context: dict[str, Any] = {"storage": self.storage}
        for p in self.plugins:
            p.start(self.plugin_context)
        self._m_validate = obs_metrics.histogram(
            "pio_ingest_validate_seconds",
            "Per-event plugin+parse+validate time",
        )
        self._m_append = obs_metrics.histogram(
            "pio_ingest_append_seconds",
            "Single-event storage insert time (row log write + fsync)",
        )
        self._m_group_commit = obs_metrics.histogram(
            "pio_ingest_group_commit_seconds",
            "Batch storage insert time (one lock+append+fsync per request)",
        )
        self._m_accepted = obs_metrics.counter(
            "pio_ingest_events_total", "Events ingested", result="created"
        )
        self._m_rejected = obs_metrics.counter(
            "pio_ingest_events_total", "Events ingested", result="rejected"
        )
        # wire-speed binary ingest (/batch/events.bin): per-request
        # in-flight-bytes budget + the pio_ingest_* backpressure family
        self.batch_max_events = _batch_max_events()
        try:
            inflight_mb = float(
                os.environ.get("PIO_INGEST_MAX_INFLIGHT_MB", "64") or 64
            )
        except ValueError:
            inflight_mb = 64.0
        self._budget = _InflightBudget(int(inflight_mb * (1 << 20)))
        self._g_inflight = obs_metrics.gauge(
            "pio_ingest_inflight_bytes",
            "Request body bytes admitted and not yet committed",
        )
        self._g_inflight.set_function(lambda: float(self._budget.in_flight))
        self._g_queue_depth = obs_metrics.gauge(
            "pio_ingest_queue_depth",
            "Group-commit appends flushed but not yet fsync-covered",
        )
        self._g_queue_depth.set_function(self._queue_depth)
        self._m_shed = obs_metrics.counter(
            "pio_ingest_shed_total",
            "Batch requests shed with 429 by the in-flight-bytes budget",
        )
        self._m_frames = obs_metrics.counter(
            "pio_ingest_frames_total", "Binary ingest frames committed"
        )
        # default objectives: ingest availability + group-commit latency
        # + backpressure-budget headroom (registered after _budget exists)
        obs_slo.install_event_server_slos(self)
        # minute-bucket ingest counts join /history.json's read shape
        obs_history.register_provider("ingest_stats", self.stats.history_series)
        self.app = HTTPApp(
            self._router(),
            host=host,
            port=port,
            reuse_port=reuse_port,
            name="eventserver",
            ready_check=self._ready_reason,
        )
        # drain-time flush: force-fsync the group-commit coalescers so
        # every acked event is durable before the process exits
        self.app.add_shutdown_hook(self._drain_flush)

    # -- auth --------------------------------------------------------------
    def _auth(self, request: Request) -> AuthData | Response:
        key = request.access_key
        if not key:
            return Response.error("Missing accessKey.", 401)
        access_key: AccessKey | None = self.storage.get_metadata_access_keys().get(key)
        if access_key is None:
            return Response.error("Invalid accessKey.", 401)
        channel_id: int | None = None
        if "channel" in request.query:
            channels = self.storage.get_metadata_channels().get_by_appid(
                access_key.appid
            )
            match = [c for c in channels if c.name == request.query["channel"]]
            if not match:
                return Response.error("Invalid channel.", 401)
            channel_id = match[0].id
        return AuthData(access_key.appid, channel_id, access_key.events)

    def _check_event_allowed(self, auth: AuthData, event_name: str) -> bool:
        return not auth.events or event_name in auth.events

    # -- event ingestion ---------------------------------------------------
    def _prepare_one(
        self, auth: AuthData, event_json: dict
    ) -> Event | tuple[int, dict]:
        """Plugins + parse + validate + allowlist for one event; returns
        the Event ready to insert, or the (status, body) error — shared
        by the single, batch, and webhook paths so semantics match."""
        try:
            for p in self.plugins:
                if p.plugin_type == plugin_mod.INPUT_BLOCKER:
                    event_json = p.process(event_json, self.plugin_context) or event_json
                else:
                    p.process(dict(event_json), self.plugin_context)
            event = Event.from_dict(event_json)
            validate(event)
        except (EventValidationError, KeyError, TypeError, ValueError) as e:
            return 400, {"message": str(e)}
        if not self._check_event_allowed(auth, event.event):
            return 403, {
                "message": f"event {event.event} is not allowed by this access key"
            }
        return event

    def _ingest_one(self, auth: AuthData, event_json: dict) -> tuple[int, dict]:
        """Returns (status_code, body) per event."""
        t0 = time.perf_counter()
        prepared = self._prepare_one(auth, event_json)
        t1 = time.perf_counter()
        self._m_validate.observe(t1 - t0)
        if not isinstance(prepared, Event):
            self._m_rejected.inc()
            return prepared
        event_id = self.storage.get_events().insert(
            prepared, auth.app_id, auth.channel_id
        )
        t2 = time.perf_counter()
        self._m_append.observe(t2 - t1)
        self._m_accepted.inc()
        tr = obs_trace.current_trace()
        if tr is not None:
            tr.add_span("ingest.validate", t0, t1)
            tr.add_span("ingest.append", t1, t2)
        if self.stats_enabled:
            self.stats.update(
                auth.app_id, 201, prepared.event, prepared.entity_type
            )
        return 201, {"eventId": event_id}

    def _ingest_batch(self, auth: AuthData, body: list) -> list[dict]:
        """Bulk import: validate every item first, then write all valid
        events with ONE ``batch_insert`` — one lock + append + fsync for
        the request instead of up to MAX_BATCH_SIZE of each (the row log
        is still written before any 201 is returned, so per-event
        durability is exactly the single-insert path's). The response
        keeps the reference's per-event status list, in request order."""
        t0 = time.perf_counter()
        results: list[dict | None] = [None] * len(body)
        events: list[Event] = []
        slots: list[int] = []
        for i, item in enumerate(body):
            if not isinstance(item, dict):
                results[i] = {"status": 400, "message": "not a JSON object"}
                continue
            prepared = self._prepare_one(auth, item)
            if isinstance(prepared, Event):
                events.append(prepared)
                slots.append(i)
            else:
                status, payload = prepared
                results[i] = {"status": status, **payload}
        t1 = time.perf_counter()
        self._m_validate.observe(t1 - t0)
        n_rejected = len(body) - len(events)
        if n_rejected:
            self._m_rejected.inc(n_rejected)
        if events:
            ids = self.storage.get_events().batch_insert(
                events, auth.app_id, auth.channel_id
            )
            t2 = time.perf_counter()
            self._m_group_commit.observe(t2 - t1)
            self._m_accepted.inc(len(events))
            tr = obs_trace.current_trace()
            if tr is not None:
                tr.add_span(f"ingest.validate[{len(body)}]", t0, t1)
                tr.add_span(f"ingest.group_commit[{len(events)}]", t1, t2)
            for i, event, event_id in zip(slots, events, ids):
                results[i] = {"status": 201, "eventId": event_id}
                if self.stats_enabled:
                    self.stats.update(
                        auth.app_id, 201, event.event, event.entity_type
                    )
        return results

    # -- health/drain -------------------------------------------------------
    def _ready_reason(self) -> str | None:
        """Readiness gate: the event server is ready iff its events
        backend answers (storage reachable)."""
        try:
            self.storage.get_events()
        except Exception as exc:  # pragma: no cover - backend-specific
            return f"storage unreachable: {exc}"
        return None

    def _drain_flush(self) -> None:
        """Graceful-shutdown hook: force-fsync any group-commit backlog
        so every acked event is durable before the process exits."""
        try:
            fn = getattr(self.storage.get_events(), "sync_commits", None)
            if fn is not None:
                fn()
        except Exception:  # pragma: no cover - disk error at exit
            logger.exception("drain-time event flush failed")

    # -- wire-speed binary ingest -------------------------------------------
    def _queue_depth(self) -> float:
        """Group-commit backlog of the events backend (0.0 when the
        backend has no coalescer, e.g. sqlite/memory)."""
        try:
            fn = getattr(self.storage.get_events(), "commit_backlog", None)
            return float(fn()) if fn is not None else 0.0
        except Exception:
            return 0.0

    def ingest_stats(self) -> dict[str, Any]:
        """Backpressure block for ``/stats.json``."""
        return {
            "inflight_bytes": self._budget.in_flight,
            "max_inflight_bytes": self._budget.max_bytes,
            "utilization": round(self._budget.utilization(), 4),
            "queue_depth": int(self._queue_depth()),
            "shed_total": int(self._m_shed.value()),
            "frames_total": int(self._m_frames.value()),
            "batch_max_events": self.batch_max_events,
        }

    def _shed(self) -> Response:
        self._m_shed.inc()
        return Response(
            status=429,
            body={
                "error": "IngestBackpressure",
                "message": "in-flight ingest budget exhausted; retry",
            },
            headers={"Retry-After": "1"},
        )

    def _ingest_frames(self, auth: AuthData, stream) -> Response:
        """Decode + validate + commit binary frames incrementally off the
        request body. Each frame is all-or-nothing and durably committed
        (one lock+append+fsync) before the next frame is read; a framing
        or validation error rejects the REST of the request with 400 but
        reports how many frames/events were already committed."""
        allowed = frozenset(auth.events) if auth.events else None
        events_dao = self.storage.get_events()
        # the splice-through exit renders storage-format JSONL and skips
        # the Event-object round trip; input-blocker plugins must see
        # per-event dicts, so a plugin-loaded server takes the dict path
        splice = getattr(events_dao, "append_jsonl", None)
        stamp_iso = format_time(datetime.now(tz=timezone.utc), "us")
        accepted = 0
        frames = 0
        t_start = time.perf_counter()
        try:
            for payload in frame_mod.read_frames(stream):
                t0 = time.perf_counter()
                batch = frame_mod.decode_frame(payload)
                if self.plugins:
                    events, _ = batch.to_events(allowed, stamp_iso)
                    prepared: list[Event] = []
                    for e in events:
                        p = self._prepare_one(auth, e.to_dict(for_api=False))
                        if not isinstance(p, Event):
                            _status, payload = p
                            raise frame_mod.FrameError(
                                "InvalidEvent",
                                payload.get("message", "rejected"),
                            )
                        prepared.append(p)
                    if prepared:
                        events_dao.batch_insert(
                            prepared, auth.app_id, auth.channel_id
                        )
                    accepted += len(prepared)
                    frames += 1
                    self._m_accepted.inc(len(prepared))
                    self._m_frames.inc()
                    continue
                if splice is not None:
                    blob, _ids, _ = batch.render_jsonl(allowed, stamp_iso)
                    t1 = time.perf_counter()
                    self._m_validate.observe(t1 - t0)
                    if blob:
                        splice(blob, auth.app_id, auth.channel_id)
                        self._m_group_commit.observe(time.perf_counter() - t1)
                else:
                    events, _ids = batch.to_events(allowed, stamp_iso)
                    t1 = time.perf_counter()
                    self._m_validate.observe(t1 - t0)
                    if events:
                        events_dao.batch_insert(
                            events, auth.app_id, auth.channel_id
                        )
                        self._m_group_commit.observe(time.perf_counter() - t1)
                accepted += batch.n
                frames += 1
                self._m_accepted.inc(batch.n)
                self._m_frames.inc()
                if self.stats_enabled:
                    for ev, et in zip(
                        batch.column_str(frame_mod.COL_EVENT),
                        batch.column_str(frame_mod.COL_ENTITY_TYPE),
                    ):
                        self.stats.update(auth.app_id, 201, ev, et)
        except frame_mod.FrameError as e:
            self._m_rejected.inc()
            return Response.json(
                {
                    "error": e.code,
                    "message": str(e),
                    "accepted": accepted,
                    "frames": frames,
                },
                status=400,
            )
        # one span covering the whole framed body: with the client
        # minting X-PIO-Trace (pio import --http / batch_insert HTTP
        # paths), the stitched server-side trace carries the ingest
        # stage alongside the request envelope
        tr = obs_trace.current_trace()
        if tr is not None:
            tr.add_span(
                f"ingest.frames[{frames}x{accepted}]",
                t_start,
                time.perf_counter(),
            )
        return Response.json({"accepted": accepted, "frames": frames})

    # -- routes ------------------------------------------------------------
    def _router(self) -> Router:
        router = Router()
        server = self

        @router.route("GET", "/")
        def welcome(request: Request) -> Response:
            return Response.json({"status": "alive"})

        @router.route("POST", "/events.json")
        def create_event(request: Request) -> Response:
            auth = server._auth(request)
            if isinstance(auth, Response):
                return auth
            body = request.json()
            if not isinstance(body, dict):
                return Response.error("request body must be a JSON object", 400)
            status, payload = server._ingest_one(auth, body)
            return Response.json(payload, status=status)

        @router.route("GET", "/events.json")
        def find_events(request: Request) -> Response:
            auth = server._auth(request)
            if isinstance(auth, Response):
                return auth
            q = request.query
            try:
                limit = int(q.get("limit", 20))
                events = server.storage.get_events().find(
                    app_id=auth.app_id,
                    channel_id=auth.channel_id,
                    start_time=parse_time(q["startTime"]) if q.get("startTime") else None,
                    until_time=parse_time(q["untilTime"]) if q.get("untilTime") else None,
                    entity_type=q.get("entityType"),
                    entity_id=q.get("entityId"),
                    event_names=[q["event"]] if q.get("event") else None,
                    target_entity_type=q.get("targetEntityType", ...),
                    target_entity_id=q.get("targetEntityId", ...),
                    limit=None if limit == -1 else limit,
                    reversed_order=q.get("reversed") == "true",
                )
            except (EventValidationError, ValueError) as e:
                return Response.error(str(e), 400)
            if not events:
                return Response.error("Not Found", 404)
            return Response.json([e.to_dict() for e in events])

        @router.route("GET", "/events/<event_id>.json")
        def get_event(request: Request) -> Response:
            auth = server._auth(request)
            if isinstance(auth, Response):
                return auth
            event = server.storage.get_events().get(
                request.path_params["event_id"], auth.app_id, auth.channel_id
            )
            if event is None:
                return Response.error("Not Found", 404)
            return Response.json(event.to_dict())

        @router.route("DELETE", "/events/<event_id>.json")
        def delete_event(request: Request) -> Response:
            auth = server._auth(request)
            if isinstance(auth, Response):
                return auth
            found = server.storage.get_events().delete(
                request.path_params["event_id"], auth.app_id, auth.channel_id
            )
            if not found:
                return Response.error("Not Found", 404)
            return Response.json({"message": "Found"})

        @router.route("POST", "/batch/events.json")
        def batch_events(request: Request) -> Response:
            auth = server._auth(request)
            if isinstance(auth, Response):
                return auth
            body = request.json()
            if not isinstance(body, list):
                return Response.error("request body must be a JSON array", 400)
            if len(body) > server.batch_max_events:
                return Response.json(
                    {
                        "error": "BatchTooLarge",
                        "message": (
                            f"Batch request must have less than or equal "
                            f"to {server.batch_max_events} events "
                            f"(PIO_BATCH_MAX_EVENTS)"
                        ),
                    },
                    status=413,
                )
            n_bytes = len(request.body)
            if not server._budget.try_acquire(n_bytes):
                return server._shed()
            try:
                return Response.json(server._ingest_batch(auth, body))
            finally:
                server._budget.release(n_bytes)

        def batch_events_bin(request: Request) -> Response:
            """Wire-speed framed binary batch ingest: frames decode
            straight into the columnar group-commit path, streamed off
            the socket (data/storage/frame.py). Backpressure: the
            request's Content-Length must fit the in-flight budget or it
            is shed with 429 BEFORE the body is read."""
            auth = server._auth(request)
            if isinstance(auth, Response):
                return auth
            stream = request.body_stream
            total = stream.remaining if stream is not None else 0
            if total <= 0:
                return Response.json(
                    {
                        "error": "EmptyBody",
                        "message": "framed binary body required "
                                   "(Content-Length > 0)",
                    },
                    status=400,
                )
            if not server._budget.try_acquire(total):
                return server._shed()
            try:
                return server._ingest_frames(auth, stream)
            finally:
                server._budget.release(total)

        router.add_stream("POST", "/batch/events.bin", batch_events_bin)

        @router.route("GET", "/stats.json")
        def stats(request: Request) -> Response:
            auth = server._auth(request)
            if isinstance(auth, Response):
                return auth
            if not server.stats_enabled:
                return Response.error(
                    "To see stats, launch Event Server with --stats argument.", 404
                )
            payload = server.stats.get(auth.app_id)
            # additive: existing consumers keep their fields untouched
            payload["obs"] = obs_metrics.stats_block()
            payload["device"] = obs_device.device_block()
            payload["ingest"] = server.ingest_stats()
            return Response.json(payload)

        @router.route("GET", "/plugins.json")
        def plugins_json(request: Request) -> Response:
            """Loaded plugin inventory grouped by interception type
            (reference EventServer.scala:156-177)."""
            def group(ptype: str) -> dict:
                return {
                    p.plugin_name: {
                        "name": p.plugin_name,
                        "description": p.plugin_description,
                        "class": type(p).__module__ + "." + type(p).__qualname__,
                    }
                    for p in server.plugins
                    if p.plugin_type == ptype
                }

            return Response.json(
                {
                    "plugins": {
                        "inputblockers": group(plugin_mod.INPUT_BLOCKER),
                        "inputsniffers": group(plugin_mod.INPUT_SNIFFER),
                    }
                }
            )

        @router.route("GET", "/plugins/<ptype>/<name>")
        @router.route("POST", "/plugins/<ptype>/<name>")
        @router.route("GET", "/plugins/<ptype>/<name>/<rest:path>")
        @router.route("POST", "/plugins/<ptype>/<name>/<rest:path>")
        def plugin_rest(request: Request) -> Response:
            """Dispatch ``/plugins/<type>/<name>/<args...>`` to the named
            plugin's ``handle_rest`` behind access-key auth (reference
            EventServer.scala:178-196 + PluginsActor.scala)."""
            return server._plugin_rest(request)

        @router.route("POST", "/webhooks/<name>.json")
        def webhook_json(request: Request) -> Response:
            return server._webhook(request, form=False)

        @router.route("POST", "/webhooks/<name>.form")
        def webhook_form(request: Request) -> Response:
            return server._webhook(request, form=True)

        @router.route("GET", "/webhooks/<name>.json")
        def webhook_check_json(request: Request) -> Response:
            return server._webhook_check(request, JsonConnector)

        @router.route("GET", "/webhooks/<name>.form")
        def webhook_check_form(request: Request) -> Response:
            return server._webhook_check(request, FormConnector)

        add_obs_routes(router)
        return router

    def _webhook(self, request: Request, form: bool) -> Response:
        auth = self._auth(request)
        if isinstance(auth, Response):
            return auth
        name = request.path_params["name"]
        connector = self.connectors.get(name)
        want = FormConnector if form else JsonConnector
        if not isinstance(connector, want):
            return Response.error(f"webhooks connection for {name} is not supported.", 404)
        try:
            data = request.form() if form else request.json()
            if data is None:
                return Response.error("empty payload", 400)
            event_json = connector.to_event_json(data)
            status, payload = self._ingest_one(auth, event_json)
        except ConnectorError as e:
            return Response.error(str(e), 400)
        return Response.json(payload, status=status)

    def _plugin_rest(self, request: Request) -> Response:
        auth = self._auth(request)
        if isinstance(auth, Response):
            return auth
        ptype = request.path_params["ptype"]
        name = request.path_params["name"]
        if ptype not in (plugin_mod.INPUT_BLOCKER, plugin_mod.INPUT_SNIFFER):
            return Response.error(f"invalid plugin type {ptype}", 404)
        for p in self.plugins:
            if p.plugin_name == name and p.plugin_type == ptype:
                # the reference hands handleREST the authenticated app +
                # channel along with the path args; params carries them —
                # ALWAYS overwritten from auth so a client can't spoof
                # the authenticated context via query params
                params = dict(request.query)
                params["appId"] = str(auth.app_id)
                params.pop("channelId", None)
                if auth.channel_id is not None:
                    params["channelId"] = str(auth.channel_id)
                try:
                    result = p.handle_rest(
                        request.path_params.get("rest", ""), params
                    )
                except Exception as e:  # plugin bug must not kill the server
                    logger.exception("plugin %s handle_rest failed", name)
                    return Response.error(str(e), 500)
                return Response.json(result)
        return Response.error(f"plugin {name} not found", 404)

    def _webhook_check(self, request: Request, want: type) -> Response:
        auth = self._auth(request)
        if isinstance(auth, Response):
            return auth
        name = request.path_params["name"]
        if not isinstance(self.connectors.get(name), want):
            return Response.error(f"webhooks connection for {name} is not supported.", 404)
        return Response.json({"message": "Ok"})

    # -- lifecycle ---------------------------------------------------------
    def start(self, background: bool = True) -> int:
        port = self.app.start(background=background)
        logger.info("Event Server listening on %s:%d", self.app.host, port)
        return port

    def stop(self) -> None:
        self.app.stop()


def create_event_server(**kwargs) -> EventServer:
    """Reference createEventServer (api/EventServer.scala:633)."""
    return EventServer(**kwargs)
