"""JSON encode/decode for the HTTP hot paths: orjson when available,
stdlib fallback, byte-identical output.

orjson's Rust encoder is ~5-10x the stdlib on the small dict payloads
the serving and ingest paths move, but the container image may not ship
it — so every hot-path caller goes through ``dumps_bytes``/``loads``
here and gets whichever backend exists. The fallback is pinned to
orjson's wire format (compact separators, UTF-8 not ``\\uXXXX`` escapes)
so switching backends can never change response bytes — the query cache
stores PRESERIALIZED responses keyed across processes/restarts, and a
byte-stable encoding keeps cached entries and fresh encodes
interchangeable (parity asserted in tests/test_servers.py).

Scope note: orjson rejects NaN/Infinity (encodes as ``null``) while the
stdlib emits bare ``NaN``; framework responses carry finite floats only
(scores pass ``float()`` and top-k masks sentinel values out), so the
difference is unreachable on these paths.
"""

from __future__ import annotations

import json as _json
from typing import Any

try:  # pragma: no cover - exercised only where orjson is installed
    import orjson as _orjson
except ImportError:
    _orjson = None

#: which encoder backs dumps_bytes/loads ("orjson" or "json")
backend = "orjson" if _orjson is not None else "json"


if _orjson is not None:  # pragma: no cover - container has no orjson

    def dumps_bytes(obj: Any) -> bytes:
        """Compact UTF-8 JSON bytes."""
        return _orjson.dumps(obj)

    def loads(data: bytes | str) -> Any:
        return _orjson.loads(data)

else:

    def dumps_bytes(obj: Any) -> bytes:
        """Compact UTF-8 JSON bytes (orjson wire format)."""
        return _json.dumps(
            obj, separators=(",", ":"), ensure_ascii=False
        ).encode("utf-8")

    def loads(data: bytes | str) -> Any:
        return _json.loads(data)
