// Native event codec / data-loader hot paths.
//
// The reference framework delegates bulk event IO to Spark executors
// (JDBCPEvents JdbcRDD reads, FileToEvents/EventsToFile jobs,
// BiMap.stringInt id indexing — data/.../storage/BiMap.scala:96-110).
// Here the equivalent host-side hot loops are implemented natively and
// exposed through ctypes (predictionio_tpu/native/__init__.py):
//
//   pio_scan_events     — newline-delimited JSON event scanner: extracts
//                         byte spans of the fixed wire fields per line
//                         (no allocation, no DOM; lines whose extracted
//                         strings contain escapes are flagged for the
//                         Python json fallback).
//   pio_index_spans     — dense string-id indexing (the BiMap build):
//                         id span -> stable dense int via one hash map.
//   pio_parse_times     — ISO-8601 -> epoch seconds for time spans.
//   pio_extract_number  — pull one numeric property (e.g. "rating") out
//                         of each properties-object span.
//
// Everything operates on ONE immutable input buffer with (offset, length)
// spans, so Python hands over a single bytes object and gets back numpy
// arrays — the file -> device-array boundary crosses the interpreter once.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 (see predictionio_tpu/native).

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

extern "C" {

// field slots written by pio_scan_events, per line
enum PioField {
  PIO_F_EVENT = 0,
  PIO_F_ENTITY_TYPE,
  PIO_F_ENTITY_ID,
  PIO_F_TARGET_ENTITY_TYPE,
  PIO_F_TARGET_ENTITY_ID,
  PIO_F_PROPERTIES,
  PIO_F_EVENT_TIME,
  PIO_F_PR_ID,
  PIO_F_EVENT_ID,
  PIO_F_TAGS,
  PIO_F_CREATION_TIME,
  PIO_N_FIELDS
};

// per-line flags
enum PioFlag {
  PIO_FLAG_FALLBACK = 1,  // needs Python json parse (escapes / odd syntax)
  PIO_FLAG_EMPTY = 2,     // blank line
};

}  // extern "C"

namespace {

struct Cursor {
  const char* p;
  const char* end;

  bool done() const { return p >= end; }
  char peek() const { return *p; }
  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  }
};

// scan a JSON string body starting after the opening quote; returns the
// span [start, close-quote) and advances past the closing quote.
// Sets *escaped if a backslash was seen (span then isn't the raw value).
bool scan_string(Cursor& c, const char** s, long* len, bool* escaped) {
  const char* start = c.p;
  while (!c.done()) {
    char ch = *c.p;
    if (ch == '\\') {
      *escaped = true;
      c.p += 2;
      continue;
    }
    if (ch == '"') {
      *s = start;
      *len = (long)(c.p - start);
      ++c.p;  // past closing quote
      return true;
    }
    ++c.p;
  }
  return false;
}

// skip a JSON value of any type; for objects/arrays does bracket matching
// with in-string tracking. Returns the full value span.
bool scan_value(Cursor& c, const char** s, long* len, bool* escaped) {
  c.skip_ws();
  if (c.done()) return false;
  const char* start = c.p;
  char ch = c.peek();
  if (ch == '"') {
    ++c.p;
    const char* body;
    long blen;
    if (!scan_string(c, &body, &blen, escaped)) return false;
    *s = start;
    *len = (long)(c.p - start);
    return true;
  }
  if (ch == '{' || ch == '[') {
    int depth = 0;
    bool in_str = false;
    while (!c.done()) {
      char d = *c.p;
      if (in_str) {
        if (d == '\\') {
          c.p += 2;
          continue;
        }
        if (d == '"') in_str = false;
        ++c.p;
        continue;
      }
      if (d == '"') in_str = true;
      else if (d == '{' || d == '[') ++depth;
      else if (d == '}' || d == ']') {
        --depth;
        if (depth == 0) {
          ++c.p;
          *s = start;
          *len = (long)(c.p - start);
          return true;
        }
      }
      ++c.p;
    }
    return false;
  }
  // number / true / false / null
  while (!c.done()) {
    char d = *c.p;
    if (d == ',' || d == '}' || d == ']' || d == ' ' || d == '\t' ||
        d == '\r' || d == '\n')
      break;
    ++c.p;
  }
  *s = start;
  *len = (long)(c.p - start);
  return *len > 0;
}

int field_slot(std::string_view key) {
  if (key == "event") return PIO_F_EVENT;
  if (key == "entityType") return PIO_F_ENTITY_TYPE;
  if (key == "entityId") return PIO_F_ENTITY_ID;
  if (key == "targetEntityType") return PIO_F_TARGET_ENTITY_TYPE;
  if (key == "targetEntityId") return PIO_F_TARGET_ENTITY_ID;
  if (key == "properties") return PIO_F_PROPERTIES;
  if (key == "eventTime") return PIO_F_EVENT_TIME;
  if (key == "prId") return PIO_F_PR_ID;
  if (key == "eventId") return PIO_F_EVENT_ID;
  if (key == "tags") return PIO_F_TAGS;
  if (key == "creationTime") return PIO_F_CREATION_TIME;
  return -1;
}

// expected JSON shape per slot: 's' string, 'o' object, 'a' array
char slot_shape(int slot) {
  switch (slot) {
    case PIO_F_PROPERTIES:
      return 'o';
    case PIO_F_TAGS:
      return 'a';
    default:
      return 's';
  }
}

// days from civil date (Howard Hinnant's algorithm, public domain)
long days_from_civil(long y, unsigned m, unsigned d) {
  y -= m <= 2;
  const long era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = (unsigned)(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + (long)doe - 719468;
}

bool parse_uint(const char*& p, const char* end, int digits, long* out) {
  long v = 0;
  for (int i = 0; i < digits; ++i) {
    if (p >= end || !isdigit((unsigned char)*p)) return false;
    v = v * 10 + (*p - '0');
    ++p;
  }
  *out = v;
  return true;
}

// One line's field-span extraction (shared by the serial and threaded
// scan paths; writes exactly the [PIO_N_FIELDS] row for this line).
void scan_one_line(const char* buf, const char* p, const char* line_end,
                   int64_t* lo, int64_t* ll, uint8_t* flag) {
  for (int f = 0; f < PIO_N_FIELDS; ++f) {
    lo[f] = -1;
    ll[f] = 0;
  }
  *flag = 0;

  Cursor c{p, line_end};
  c.skip_ws();
  if (c.done()) {
    *flag = PIO_FLAG_EMPTY;
  } else if (c.peek() != '{') {
    *flag = PIO_FLAG_FALLBACK;
  } else {
    ++c.p;  // past '{'
    bool ok = true;
    bool closed = false;
    bool line_escaped = false;
    bool first = true;
    while (true) {
      c.skip_ws();
      // '}' is only valid here for the empty object; after a comma a key
      // must follow (json.loads rejects trailing commas — the fast path
      // must not be more lenient than the fallback it stands in for)
      if (first && !c.done() && c.peek() == '}') {
        ++c.p;
        closed = true;
        break;
      }
      first = false;
      if (c.done() || c.peek() != '"') {
        ok = false;
        break;
      }
      ++c.p;
      const char* key;
      long keylen;
      bool key_escaped = false;
      if (!scan_string(c, &key, &keylen, &key_escaped)) {
        ok = false;
        break;
      }
      c.skip_ws();
      if (c.done() || c.peek() != ':') {
        ok = false;
        break;
      }
      ++c.p;
      const char* val;
      long vallen;
      bool val_escaped = false;
      if (!scan_value(c, &val, &vallen, &val_escaped)) {
        ok = false;
        break;
      }
      // an escaped key can be a known field in disguise (e.g.
      // "entityId"); the span scan can't see that, so the whole
      // line must go through the json fallback rather than silently
      // keeping a value json.loads would overwrite (duplicate keys:
      // last wins)
      if (key_escaped) line_escaped = true;
      int slot = key_escaped ? -1 : field_slot({key, (size_t)keylen});
      if (slot >= 0) {
        bool is_null = vallen == 4 && memcmp(val, "null", 4) == 0;
        char shape = slot_shape(slot);
        char open = shape == 's' ? '"' : (shape == 'o' ? '{' : '[');
        if (is_null) {
          lo[slot] = -1;
          ll[slot] = 0;
        } else if (vallen >= 1 && val[0] == open) {
          // type mismatches (numeric entityId etc.) must go through the
          // json fallback so they are rejected like before
          if (val_escaped && shape == 's') line_escaped = true;
          if (shape == 's') {
            lo[slot] = (int64_t)(val + 1 - buf);  // strip quotes
            ll[slot] = vallen - 2;
          } else {
            lo[slot] = (int64_t)(val - buf);
            ll[slot] = vallen;
          }
        } else {
          line_escaped = true;
        }
      }
      c.skip_ws();
      if (!c.done() && c.peek() == ',') {
        ++c.p;
        continue;
      }
      if (!c.done() && c.peek() == '}') {
        ++c.p;
        closed = true;
      }
      break;
    }
    c.skip_ws();
    // unterminated objects or trailing bytes after '}' (concatenated
    // records, truncated lines) fall back so json.loads fails loudly
    if (!ok || line_escaped || !closed || !c.done())
      *flag = PIO_FLAG_FALLBACK;
  }
}

int scan_thread_count(long n_lines, long requested) {
  if (requested <= 0) {
    // env override for benchmarking/tests; read only when the caller
    // didn't pass an explicit count (callers that run concurrently with
    // other threads pass it explicitly — getenv racing a putenv from
    // another thread is UB in glibc)
    const char* env = std::getenv("PIO_NATIVE_THREADS");
    requested = env ? std::atol(env) : 0;
  }
  if (requested > 0) {
    // explicit override wins outright (benchmarking / tests)
    return (int)(requested > n_lines ? (n_lines < 1 ? 1 : n_lines)
                                     : requested);
  }
  unsigned hw = std::thread::hardware_concurrency();
  long t = (long)(hw ? (hw > 8 ? 8 : hw) : 1);
  // one thread per >=100k lines: below that, spawn cost beats the win
  long by_work = n_lines / 100000;
  if (t > by_work) t = by_work;
  return (int)(t < 1 ? 1 : t);
}

}  // namespace

extern "C" {

// Scan newline-delimited JSON events. offs/lens are [capacity*PIO_N_FIELDS]
// int64 arrays (-1 offset = field absent); flags is [capacity] bytes.
// String field spans exclude quotes; properties spans include braces.
// Returns the number of lines consumed (including blank/fallback lines),
// or -1 if capacity was exceeded.
//
// Large buffers scan MULTITHREADED (std::thread over line ranges; output
// rows are disjoint, the buffer is read-only — the caller releases the
// GIL for the whole call via ctypes): first pass indexes newlines, second
// pass extracts field spans in parallel. n_threads > 0 pins the thread
// count; 0 = auto (PIO_NATIVE_THREADS env override, else min(cores, 8),
// scaled down for small inputs).
long pio_scan_events(const char* buf, long buflen, int64_t* offs,
                     int64_t* lens, uint8_t* flags, long capacity,
                     long n_threads) {
  // pass 1: line starts (cheap memchr sweep); line ends are derived —
  // ends[i] = starts[i+1] - 1 (the newline), last line ends at bufend
  // unless the buffer is newline-terminated
  std::vector<const char*> starts;
  const char* p = buf;
  const char* bufend = buf + buflen;
  while (p < bufend) {
    const char* nl = (const char*)memchr(p, '\n', (size_t)(bufend - p));
    if ((long)starts.size() >= capacity) return -1;
    starts.push_back(p);
    p = nl ? nl + 1 : bufend;
  }
  long n = (long)starts.size();
  const char* last_end =
      (buflen > 0 && buf[buflen - 1] == '\n') ? bufend - 1 : bufend;
  int nthreads = scan_thread_count(n, n_threads);
  auto run = [&](long lo_line, long hi_line) {
    for (long i = lo_line; i < hi_line; ++i) {
      const char* line_end =
          i + 1 < n ? starts[(size_t)i + 1] - 1 : last_end;
      scan_one_line(buf, starts[(size_t)i], line_end,
                    offs + i * PIO_N_FIELDS, lens + i * PIO_N_FIELDS,
                    flags + i);
    }
  };
  if (nthreads <= 1) {
    run(0, n);
  } else {
    std::vector<std::thread> workers;
    workers.reserve((size_t)nthreads);
    long chunk = (n + nthreads - 1) / nthreads;
    for (int t = 0; t < nthreads; ++t) {
      long lo_line = (long)t * chunk;
      long hi_line = lo_line + chunk < n ? lo_line + chunk : n;
      if (lo_line >= hi_line) break;
      workers.emplace_back(run, lo_line, hi_line);
    }
    for (auto& w : workers) w.join();
  }
  return n;
}

// Partition routing for event-id spans (mirrors PartitionedEvents._route):
// ids shaped "<2 hex>-..." with value < n_partitions route by the embedded
// partition; everything else by FNV-1a 32 of the id bytes mod n_partitions.
// Spans with offset -1 get -1. Used to vectorize bulk-import routing.
void pio_route_ids(const char* buf, const int64_t* offs, const int64_t* lens,
                   long n, int32_t n_partitions, int32_t* out) {
  auto hexval = [](char ch) -> int {
    if (ch >= '0' && ch <= '9') return ch - '0';
    if (ch >= 'a' && ch <= 'f') return ch - 'a' + 10;
    return -1;
  };
  for (long i = 0; i < n; ++i) {
    if (offs[i] < 0) {
      out[i] = -1;
      continue;
    }
    const char* s = buf + offs[i];
    long len = lens[i];
    if (len >= 3 && s[2] == '-') {
      int h1 = hexval(s[0]), h2 = hexval(s[1]);
      if (h1 >= 0 && h2 >= 0) {
        int pp = h1 * 16 + h2;
        if (pp < n_partitions) {
          out[i] = pp;
          continue;
        }
      }
    }
    uint32_t h = 2166136261u;
    for (long j = 0; j < len; ++j) {
      h ^= (uint8_t)s[j];
      h *= 16777619u;
    }
    out[i] = (int32_t)(h % (uint32_t)n_partitions);
  }
}

// Dense-index string spans (BiMap.stringInt analog): idx[i] gets the dense
// id of span i; uniq_repr[j] records the first i carrying unique id j
// (so Python can slice the id strings back out of the buffer).
// Spans with offset -1 get idx -1. Returns the number of unique ids.
long pio_index_spans(const char* buf, const int64_t* offs,
                     const int64_t* lens, long n, int32_t* idx,
                     int64_t* uniq_repr) {
  std::unordered_map<std::string_view, int32_t> map;
  map.reserve((size_t)n);
  int32_t next = 0;
  for (long i = 0; i < n; ++i) {
    if (offs[i] < 0) {
      idx[i] = -1;
      continue;
    }
    std::string_view sv(buf + offs[i], (size_t)lens[i]);
    auto [it, inserted] = map.try_emplace(sv, next);
    if (inserted) {
      uniq_repr[next] = i;
      ++next;
    }
    idx[i] = it->second;
  }
  return next;
}

// ISO-8601 span -> epoch seconds (UTC). Accepts
// YYYY-MM-DD['T'HH:MM[:SS[.fraction]]][Z|±HH[:MM]]; NaN when unparseable
// or absent.
void pio_parse_times(const char* buf, const int64_t* offs,
                     const int64_t* lens, long n, double* out) {
  for (long i = 0; i < n; ++i) {
    out[i] = NAN;
    if (offs[i] < 0) continue;
    const char* p = buf + offs[i];
    const char* end = p + lens[i];
    long y, mo, d;
    if (!parse_uint(p, end, 4, &y)) continue;
    if (p >= end || *p != '-') continue;
    ++p;
    if (!parse_uint(p, end, 2, &mo)) continue;
    if (p >= end || *p != '-') continue;
    ++p;
    if (!parse_uint(p, end, 2, &d)) continue;
    long h = 0, mi = 0, s = 0;
    double frac = 0.0;
    long tz = 0;
    bool ok = true;
    if (p < end && (*p == 'T' || *p == 't' || *p == ' ')) {
      ++p;
      if (!parse_uint(p, end, 2, &h)) continue;
      if (p >= end || *p != ':') continue;
      ++p;
      if (!parse_uint(p, end, 2, &mi)) continue;
      if (p < end && *p == ':') {
        ++p;
        if (!parse_uint(p, end, 2, &s)) continue;
        if (p < end && *p == '.') {
          ++p;
          double scale = 0.1;
          while (p < end && isdigit((unsigned char)*p)) {
            frac += (*p - '0') * scale;
            scale *= 0.1;
            ++p;
          }
        }
      }
    }
    if (p < end) {
      char z = *p;
      if (z == 'Z' || z == 'z') {
        ++p;
      } else if (z == '+' || z == '-') {
        int sign = z == '+' ? 1 : -1;
        ++p;
        long th, tm = 0;
        if (!parse_uint(p, end, 2, &th)) ok = false;
        if (ok && p < end && *p == ':') {
          ++p;
          if (!parse_uint(p, end, 2, &tm)) ok = false;
        } else if (ok && p < end && isdigit((unsigned char)*p)) {
          if (!parse_uint(p, end, 2, &tm)) ok = false;
        }
        if (ok) tz = sign * (th * 3600 + tm * 60);
      }
    }
    if (!ok || p != end) continue;
    double epoch = (double)days_from_civil(y, (unsigned)mo, (unsigned)d) *
                       86400.0 +
                   h * 3600.0 + mi * 60.0 + (double)s + frac - (double)tz;
    out[i] = epoch;
  }
}

// Extract one numeric property per properties-object span: out[i] = the
// value of `"key": <number>` at the top level of span i, else NaN.
void pio_extract_number(const char* buf, const int64_t* offs,
                        const int64_t* lens, long n, const char* key,
                        double* out) {
  size_t keylen = strlen(key);
  for (long i = 0; i < n; ++i) {
    out[i] = NAN;
    if (offs[i] < 0 || lens[i] < 2) continue;
    Cursor c{buf + offs[i], buf + offs[i] + lens[i]};
    if (c.peek() != '{') continue;
    ++c.p;
    while (true) {
      c.skip_ws();
      if (c.done() || c.peek() == '}') break;
      if (c.peek() != '"') break;
      ++c.p;
      const char* k;
      long klen;
      bool esc = false;
      if (!scan_string(c, &k, &klen, &esc)) break;
      c.skip_ws();
      if (c.done() || c.peek() != ':') break;
      ++c.p;
      const char* v;
      long vlen;
      bool vesc = false;
      if (!scan_value(c, &v, &vlen, &vesc)) break;
      if (!esc && (size_t)klen == keylen && memcmp(k, key, keylen) == 0) {
        if (vlen > 0 && v[0] != '"' && v[0] != '{' && v[0] != '[') {
          char tmp[64];
          long cl = vlen < 63 ? vlen : 63;
          memcpy(tmp, v, (size_t)cl);
          tmp[cl] = 0;
          char* endp = nullptr;
          double val = strtod(tmp, &endp);
          if (endp && endp != tmp) out[i] = val;
        }
        break;
      }
      c.skip_ws();
      if (!c.done() && c.peek() == ',') {
        ++c.p;
        continue;
      }
      break;
    }
  }
}

// FNV-1a 64 over byte spans: out[i] = hash of buf[offs[i], +lens[i]),
// or 0 for absent spans (offs[i] < 0). Used by the chunked log
// cleanliness proof to check id uniqueness without materializing
// millions of Python strings.
void pio_hash64_spans(const char* buf, const int64_t* offs,
                      const int64_t* lens, long n, uint64_t* out) {
  for (long i = 0; i < n; ++i) {
    if (offs[i] < 0) {
      out[i] = 0;
      continue;
    }
    const unsigned char* p = (const unsigned char*)buf + offs[i];
    uint64_t h = 1469598103934665603ULL;
    for (long j = 0; j < lens[i]; ++j) {
      h ^= (uint64_t)p[j];
      h *= 1099511628211ULL;
    }
    out[i] = h;
  }
}

// Splice per-line JSON suffixes for the import fast path: for each of
// n_sel selected line spans [starts[i], ends[i]) of buf (each a closed
// JSON object per the scanner), emit the line with optional
// `,"eventId":"<32 hex>"` (want_id[i]; ids holds 32 bytes per wanting
// line, consumed in order) and/or the fixed ct_tail (want_ct[i])
// inserted before the closing '}', lines joined by '\n' with a trailing
// '\n'. Returns bytes written into out (caller sizes it worst-case), or
// -1 if a line doesn't end in '}' after rstrip (caller falls back).
long pio_splice_lines(const char* buf, const int64_t* starts,
                      const int64_t* ends, long n_sel,
                      const uint8_t* want_id, const uint8_t* want_ct,
                      const char* ids, const char* ct_tail, long ct_len,
                      char* out) {
  static const char kIdPrefix[] = ",\"eventId\":\"";
  const long kIdPrefixLen = (long)sizeof(kIdPrefix) - 1;
  char* w = out;
  long id_i = 0;
  for (long i = 0; i < n_sel; ++i) {
    const char* s = buf + starts[i];
    const char* e = buf + ends[i];
    while (e > s && (e[-1] == ' ' || e[-1] == '\t' || e[-1] == '\r' ||
                     e[-1] == '\n'))
      --e;
    bool id = want_id[i] != 0;
    bool ct = want_ct[i] != 0;
    if (!id && !ct) {
      memcpy(w, s, (size_t)(e - s));
      w += e - s;
    } else {
      if (e == s || e[-1] != '}') return -1;
      memcpy(w, s, (size_t)(e - s - 1));
      w += e - s - 1;
      if (id) {
        memcpy(w, kIdPrefix, (size_t)kIdPrefixLen);
        w += kIdPrefixLen;
        memcpy(w, ids + 32 * id_i, 32);
        w += 32;
        *w++ = '"';
        ++id_i;
      }
      if (ct) {
        memcpy(w, ct_tail, (size_t)ct_len);
        w += ct_len;
      }
      *w++ = '}';
    }
    *w++ = '\n';
  }
  return (long)(w - out);
}

}  // extern "C"
